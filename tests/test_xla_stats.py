"""XLA introspection (paddle_tpu.observe.xla_stats): compile telemetry,
HBM accounting, and the pre-dispatch memory budget gate.

Reference parity: the memory_optimize/profiler role (SURVEY L1/L11) —
here rebuilt on jax's AOT stages (``jit(f).lower(...).compile()`` →
``memory_analysis()``/``cost_analysis()``), so an over-budget program
fails BEFORE dispatch with a per-var attribution table instead of an
opaque RESOURCE_EXHAUSTED after it.
"""
import io
import json
import threading
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from conftest import jax_capability
from paddle_tpu import layers, observe
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.passes import TPShardingPlan
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.monitor import stat_get, stat_reset
from paddle_tpu.observe import flight, health, xla_stats
from paddle_tpu.observe.xla_stats import MemoryBudgetError
from paddle_tpu.optimizer import MomentumOptimizer


@pytest.fixture
def restore_flags():
    """Tests flip the gate/introspection flags; always restore."""
    yield
    pt.set_flags({"FLAGS_hbm_budget_fraction": 0.0,
                  "FLAGS_hbm_bytes_per_device": 0,
                  "FLAGS_xla_introspect": True,
                  "FLAGS_hlo_dump_dir": ""})


def _train_program(seed=3):
    """fc -> fc, MSE, Momentum: parameters + velocity slots in scope."""
    main, startup = Program(), Program()
    main.random_seed = seed
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        MomentumOptimizer(0.05, 0.9).minimize(loss)
    return main, startup, loss


def _feed(batch=16):
    rs = np.random.RandomState(0)
    X = rs.randn(batch, 8).astype("f4")
    return {"x": X, "y": X.sum(1, keepdims=True).astype("f4") * 0.3}


def _fresh_executor(main_startup=None):
    main, startup, loss = main_startup or _train_program()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    return exe, scope, main, loss


# ---------------------------------------------------------------------------
# mocked compiled objects (the unit half: no XLA required)
# ---------------------------------------------------------------------------


class _FakeMemStats:
    argument_size_in_bytes = 1000
    output_size_in_bytes = 500
    temp_size_in_bytes = 300
    generated_code_size_in_bytes = 0
    alias_size_in_bytes = 200


class _FakeCompiled:
    """Duck-typed jax AOT Compiled: enough surface for on_compile."""

    def __init__(self, mem=_FakeMemStats(), flops=None,
                 text="HloModule fake\n  %a = f32[] add(x, y)\n"):
        self._mem = mem
        self._flops = flops
        self._text = text

    def memory_analysis(self):
        if isinstance(self._mem, Exception):
            raise self._mem
        return self._mem

    def cost_analysis(self):
        if self._flops is None:
            raise NotImplementedError("no cost analysis")
        return [{"flops": self._flops}]

    def as_text(self):
        return self._text


class TestMemoryBreakdown:
    def test_breakdown_fields_and_total(self):
        b = xla_stats.memory_breakdown(_FakeCompiled())
        assert b["arguments_bytes"] == 1000
        assert b["outputs_bytes"] == 500
        assert b["temporaries_bytes"] == 300
        assert b["aliased_bytes"] == 200
        # total = args + outs + temps + code - aliased
        assert b["total_bytes"] == 1000 + 500 + 300 + 0 - 200

    def test_missing_memory_analysis_is_none(self):
        assert xla_stats.memory_breakdown(object()) is None

    def test_raising_memory_analysis_is_none(self):
        c = _FakeCompiled(mem=RuntimeError("backend says no"))
        assert xla_stats.memory_breakdown(c) is None


# ---------------------------------------------------------------------------
# attribution: TPShardingPlan x var sizes
# ---------------------------------------------------------------------------


def _mesh_2x4():
    import jax

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return jax.sharding.Mesh(devs, ("dp", "mp"))


class TestAttribution:
    def test_sorted_and_truncated(self):
        entries = [(f"v{i}", (i + 1, 4), "float32", "state")
                   for i in range(12)]
        rows = xla_stats.var_attribution(entries, top_n=5)
        assert len(rows) == 5
        assert rows[0]["name"] == "v11"  # biggest first
        sizes = [r["per_chip_bytes"] for r in rows]
        assert sizes == sorted(sizes, reverse=True)
        assert rows[0]["global_bytes"] == 12 * 4 * 4

    def test_plan_join_divides_sharded_vars(self):
        mesh = _mesh_2x4()
        plan = TPShardingPlan(
            {"w": (None, "mp"), "b": (), "z": ("dp", "mp")}, mp_degree=4)
        entries = [("w", (64, 64), "float32", "state"),
                   ("b", (64,), "float32", "state"),
                   ("z", (64, 64), "float32", "state")]
        rows = {r["name"]: r
                for r in xla_stats.var_attribution(entries, plan, mesh)}
        assert rows["w"]["per_chip_bytes"] == 64 * 64 * 4 // 4
        assert rows["w"]["spec"] == "P(None, 'mp')"
        assert rows["b"]["per_chip_bytes"] == 64 * 4  # replicated
        assert rows["b"]["spec"] == "replicated"
        assert rows["z"]["per_chip_bytes"] == 64 * 64 * 4 // 8  # dp*mp
        # plan helpers directly (the passes.py join surface)
        assert plan.shard_divisor("z", mesh) == 8
        assert plan.shard_divisor("unknown", mesh) == 1
        assert plan.spec_str("unknown") == "replicated"

    def test_format_is_aligned_text(self):
        rows = xla_stats.var_attribution(
            [("weight", (1024, 1024), "float32", "state")])
        txt = xla_stats.format_attribution(rows)
        assert "weight" in txt and "per-chip MB" in txt
        assert "4.0" in txt  # 1024*1024*4 = 4MB


# ---------------------------------------------------------------------------
# the budget gate (unit: explicit capacity override, no device probing)
# ---------------------------------------------------------------------------


class TestBudgetGate:
    def test_disabled_by_default(self, restore_flags):
        pt.set_flags({"FLAGS_hbm_budget_fraction": 0.0})
        v = xla_stats.check_hbm_budget(10 ** 15)
        assert v["verdict"] == "disabled"

    def test_skips_loudly_without_capacity(self, restore_flags):
        # CPU devices report no memory_stats and no override is set:
        # the gate cannot judge — it must skip with a counter, never
        # guess, and NEVER pass the program silently as "fits"
        pt.set_flags({"FLAGS_hbm_budget_fraction": 0.9,
                      "FLAGS_hbm_bytes_per_device": 0})
        stat_reset("hbm_budget_gate_skipped")
        v = xla_stats.check_hbm_budget(10 ** 15)
        assert v["verdict"] == "skipped"
        assert stat_get("hbm_budget_gate_skipped") == 1

    def test_under_budget_passes(self, restore_flags):
        pt.set_flags({"FLAGS_hbm_budget_fraction": 0.5,
                      "FLAGS_hbm_bytes_per_device": 1000})
        stat_reset("hbm_budget_gate_passed")
        v = xla_stats.check_hbm_budget(400)
        assert v["verdict"] == "pass"
        assert v["budget_bytes"] == 500
        assert stat_get("hbm_budget_gate_passed") == 1

    def test_over_budget_raises_with_attribution(self, restore_flags):
        pt.set_flags({"FLAGS_hbm_budget_fraction": 0.5,
                      "FLAGS_hbm_bytes_per_device": 1000})
        rows = xla_stats.var_attribution(
            [("big.w_0", (100, 100), "float32", "state"),
             ("mid.w_0", (10, 10), "float32", "state"),
             ("tiny.b_0", (4,), "float32", "state"),
             ("x", (16, 8), "float32", "feed")])
        stat_reset("hbm_budget_gate_rejections")
        with pytest.raises(MemoryBudgetError) as ei:
            xla_stats.check_hbm_budget(900, rows, fingerprint="abcd1234")
        e = ei.value
        msg = str(e)
        # the top-3 largest vars and their specs are IN the error
        assert "big.w_0" in msg and "mid.w_0" in msg and "tiny.b_0" in msg
        assert "replicated" in msg
        assert "BEFORE dispatch" in msg
        assert e.required_bytes == 900 and e.budget_bytes == 500
        assert e.attribution[0]["name"] == "big.w_0"
        assert stat_get("hbm_budget_gate_rejections") == 1
        # the rejection left a flight event naming the top vars
        ev = [r for r in flight.tail(20)
              if r["event"] == "xla/hbm_budget_reject"]
        assert ev and ev[-1]["top_vars"][0] == "big.w_0"


# ---------------------------------------------------------------------------
# on_compile (mocked compiled): record, gauges, mfu cross-check
# ---------------------------------------------------------------------------


class TestOnCompileMocked:
    def test_record_gauges_and_flight_event(self):
        xla_stats.clear_compile_records()
        observe.histogram("compile_seconds").reset()
        rec = xla_stats.on_compile(
            _FakeCompiled(), fingerprint="deadbeefcafe", seconds=0.25,
            size_entries=[("w", (32, 32), "float32", "state")])
        assert rec["compile_seconds"] == 0.25
        assert observe.histogram("compile_seconds").count == 1
        assert rec["memory"]["total_bytes"] == 1600
        assert stat_get("hbm_required_bytes") == 1600
        # CPU-style zero code size falls back to the HLO text length
        assert rec["executable_size_bytes"] == len(
            _FakeCompiled().as_text())
        assert rec["executable_size_is_hlo_text"] is True
        assert rec["hlo_ops"] == 1
        assert rec["attribution"][0]["name"] == "w"
        assert xla_stats.last_compile() is rec
        ev = [r for r in flight.tail(10)
              if r["event"] == "executor/compile_done"]
        assert ev and ev[-1]["fingerprint"] == "deadbeefcafe"
        assert ev[-1]["seconds"] == 0.25
        assert ev[-1]["hbm_required_bytes"] == 1600

    def test_capability_skip_without_memory_analysis(self, restore_flags):
        # a jax whose compiled objects lack memory_analysis: telemetry
        # that exists is still recorded, the counter says why the HBM
        # half is missing, and an ARMED gate does not fire (it cannot
        # judge what it cannot see — the skip path, not a crash)
        pt.set_flags({"FLAGS_hbm_budget_fraction": 0.9,
                      "FLAGS_hbm_bytes_per_device": 1})
        stat_reset("xla_memory_analysis_unavailable")
        rec = xla_stats.on_compile(
            _FakeCompiled(mem=RuntimeError("nope")), seconds=0.1)
        assert "memory" not in rec
        assert stat_get("xla_memory_analysis_unavailable") == 1

    def test_mfu_mismatch_prefers_xla(self):
        stat_reset("mfu_flops_mismatch")
        rec = xla_stats.on_compile(
            _FakeCompiled(flops=1000.0), seconds=0.0,
            program_flops=100.0)  # 10x apart: the IR count mispriced
        assert rec["flops_source"] == "xla"
        assert rec["xla_flops_per_step"] == 1000.0
        assert stat_get("mfu_flops_mismatch") == 1

    def test_mfu_within_2x_keeps_ir_count(self):
        stat_reset("mfu_flops_mismatch")
        rec = xla_stats.on_compile(
            _FakeCompiled(flops=150.0), seconds=0.0, program_flops=100.0)
        assert "xla_flops_per_step" not in rec
        assert rec["flops_ratio_xla_over_ir"] == 1.5
        assert stat_get("mfu_flops_mismatch") == 0

    def test_no_cross_check_for_scans_or_meshes(self):
        rec = xla_stats.on_compile(
            _FakeCompiled(flops=1000.0), seconds=0.0,
            program_flops=1.0, n_steps=10)
        assert "xla_flops_per_step" not in rec
        rec = xla_stats.on_compile(
            _FakeCompiled(flops=1000.0), seconds=0.0,
            program_flops=1.0, mesh=_mesh_2x4())
        assert "xla_flops_per_step" not in rec


# ---------------------------------------------------------------------------
# Executor integration (the tentpole end-to-end, real XLA)
# ---------------------------------------------------------------------------


class TestExecutorIntrospection:
    def test_compile_telemetry_end_to_end(self, require_memory_analysis):
        xla_stats.clear_compile_records()
        observe.histogram("compile_seconds").reset()
        exe, scope, main, loss = _fresh_executor()
        out = exe.run(main, feed=_feed(), fetch_list=[loss], scope=scope)
        assert np.isfinite(out[0]).all()
        # startup + train = two compiles, both measured
        assert observe.histogram("compile_seconds").count >= 2
        recs = xla_stats.compile_records()
        assert len(recs) >= 2
        train = recs[-1]
        assert train["memory"]["total_bytes"] > 0
        assert stat_get("hbm_required_bytes") > 0
        assert stat_get("executable_size_bytes") > 0
        names = [r["name"] for r in train["attribution"]]
        assert "fc_0.w_0" in names  # scope state joined in
        assert any(r["kind"] == "feed" for r in train["attribution"])
        assert any(r["event"] == "executor/compile_done"
                   for r in flight.tail(20))
        # the AOT executable replaced the lazy callable (paid once)
        assert any(getattr(e.fn, "__name__", "") == "run_compiled"
                   for e in exe._cache.values())
        # StepTimer surfaces the compiler's own bill
        s = observe.step_timer().summary()
        assert s["xla_compile_seconds"]["count"] >= 2
        assert s["executable_size_bytes"] > 0

    def test_budget_gate_rejects_before_dispatch(
            self, restore_flags, require_memory_analysis):
        exe, scope, main, loss = _fresh_executor()
        pt.set_flags({"FLAGS_hbm_budget_fraction": 0.5,
                      "FLAGS_hbm_bytes_per_device": 1024})
        d0 = stat_get("executor_steps_dispatched")
        with pytest.raises(MemoryBudgetError) as ei:
            exe.run(main, feed=_feed(), fetch_list=[loss], scope=scope)
        # NOTHING dispatched: the rejection is a report, not a dead chip
        assert stat_get("executor_steps_dispatched") == d0
        assert "fc_0.w_0" in str(ei.value)  # largest var named
        # the rejected compile still left its record for memory.json
        assert xla_stats.last_compile()["budget"]["verdict"] == "rejected"
        # widening the budget lets the same cached entry run
        pt.set_flags({"FLAGS_hbm_budget_fraction": 0.0})
        out = exe.run(main, feed=_feed(), fetch_list=[loss], scope=scope)
        assert np.isfinite(out[0]).all()

    def test_flag_gates_introspection_off(self, restore_flags):
        pt.set_flags({"FLAGS_xla_introspect": False})
        xla_stats.clear_compile_records()
        exe, scope, main, loss = _fresh_executor()
        out = exe.run(main, feed=_feed(), fetch_list=[loss], scope=scope)
        assert np.isfinite(out[0]).all()
        assert xla_stats.compile_records() == []

    def test_capability_skip_runs_unintrospected(self, restore_flags,
                                                 monkeypatch):
        # simulate a jax lacking memory_analysis on REAL compiled
        # objects: the run must proceed, counted, with the armed gate
        # skipping (capacity known, footprint unknowable)
        from paddle_tpu.framework import jax_compat

        monkeypatch.setattr(jax_compat, "compiled_memory_stats",
                            lambda compiled: None)
        pt.set_flags({"FLAGS_hbm_budget_fraction": 0.9,
                      "FLAGS_hbm_bytes_per_device": 1})
        stat_reset("xla_memory_analysis_unavailable")
        exe, scope, main, loss = _fresh_executor()
        out = exe.run(main, feed=_feed(), fetch_list=[loss], scope=scope)
        assert np.isfinite(out[0]).all()
        assert stat_get("xla_memory_analysis_unavailable") >= 1

    def test_introspection_parity(self, restore_flags):
        """Same program, same seed: losses bitwise-equal with the AOT
        introspection path on vs off (the compiled executable must be
        the same computation the lazy path would have traced)."""
        losses = {}
        for flag in (True, False):
            pt.set_flags({"FLAGS_xla_introspect": flag})
            exe, scope, main, loss = _fresh_executor(_train_program(7))
            vals = []
            for _ in range(3):
                out = exe.run(main, feed=_feed(), fetch_list=[loss],
                              scope=scope)
                vals.append(np.asarray(out[0]).copy())
            exe.drain()
            losses[flag] = np.concatenate(vals)
        np.testing.assert_array_equal(losses[True], losses[False])

    def test_hlo_dump_dir(self, restore_flags, tmp_path):
        if not jax_capability("aot_stages"):
            pytest.skip("installed jax has no AOT stages")
        d = tmp_path / "hlo"
        pt.set_flags({"FLAGS_hlo_dump_dir": str(d)})
        exe, scope, main, loss = _fresh_executor()
        exe.run(main, feed=_feed(), fetch_list=[loss], scope=scope)
        exe.drain()
        dumps = sorted(d.glob("hlo_*.txt"))
        assert dumps, "no optimized-HLO dumps written"
        assert dumps[0].stat().st_size > 0
        assert xla_stats.last_compile().get("hlo_dump_path")


# ---------------------------------------------------------------------------
# live HBM telemetry: heartbeat fields + cluster aggregation
# ---------------------------------------------------------------------------


class _FakeDevice:
    def __init__(self, limit, used):
        self._s = {"bytes_limit": limit, "bytes_in_use": used}

    def memory_stats(self):
        return self._s


class TestDeviceMemoryTelemetry:
    def test_record_device_memory_gauges_min_free(self):
        devs = [_FakeDevice(1000, 100), _FakeDevice(1000, 700)]
        out = xla_stats.record_device_memory(devs)
        # min free across chips: the one that OOMs first
        assert out["hbm_free_bytes"] == 300
        assert out["hbm_used_bytes"] == 700
        assert out["hbm_limit_bytes"] == 1000
        assert stat_get("hbm_free_bytes") == 300
        assert stat_get("hbm_used_bytes") == 700

    def test_cpu_devices_capability_skip(self):
        import jax

        # the CPU backend has no memory stats: {} — never a crash, and
        # the heartbeat payload simply omits the hbm fields
        assert xla_stats.record_device_memory(jax.local_devices()) == {}

    def test_no_device_probe_before_backend_in_use(self, monkeypatch):
        """The heartbeat thread samples through the default path; until
        the Executor's first compile marks the backend in use, it must
        not touch jax at all — jax.local_devices() on an uninitialized
        (possibly dead) backend IS the 240s device-init hang the
        health plane exists to survive (the PR 6 topology rule)."""
        monkeypatch.setattr(xla_stats, "_BACKEND_IN_USE", False)

        def boom(device=None):  # any probe here is the bug
            raise AssertionError("device probed before backend in use")

        monkeypatch.setattr(xla_stats, "device_memory_stats", boom)
        assert xla_stats.record_device_memory() == {}
        # explicit devices (tests, supervisors) still bypass the gate
        monkeypatch.setattr(xla_stats, "device_memory_stats",
                            lambda d=None: {"bytes_limit": 10,
                                            "bytes_in_use": 4})
        assert xla_stats.record_device_memory(
            [object()])["hbm_free_bytes"] == 6

    def test_memory_report_never_probes_by_default(self, monkeypatch):
        """dump_postmortem fires exactly when a device call is hung: the
        memory.json section must read the cached heartbeat gauges, not
        re-probe the wedged PJRT runtime."""
        monkeypatch.setattr(xla_stats, "_BACKEND_IN_USE", True)
        monkeypatch.setattr(
            xla_stats, "device_memory_stats",
            lambda d=None: (_ for _ in ()).throw(
                AssertionError("live probe on the dump path")))
        from paddle_tpu.monitor import stat_set

        stat_set("hbm_free_bytes", 777)
        rep = xla_stats.memory_report()
        assert rep["device_memory"] == []
        assert rep["hbm_gauges"]["hbm_free_bytes"] == 777
        stat_set("hbm_free_bytes", 0)

    def test_heartbeat_payload_carries_hbm_fields(self, monkeypatch):
        monkeypatch.setattr(
            xla_stats, "record_device_memory",
            lambda devices=None: {"hbm_free_bytes": 123,
                                  "hbm_used_bytes": 7,
                                  "hbm_limit_bytes": 130})
        stats = health._default_rank_stats()
        assert stats["hbm_free_bytes"] == 123

    def test_cluster_health_min_free_across_ranks(self):
        import time as _time

        now = _time.time()
        kv = {
            "health/rank/0": json.dumps(
                {"rank": 0, "ts": now, "interval_s": 10.0,
                 "hbm_free_bytes": 5000}),
            "health/rank/1": json.dumps(
                {"rank": 1, "ts": now, "interval_s": 10.0,
                 "hbm_free_bytes": 2000}),
        }
        out = health.cluster_health(kv, world_size=2, now=now)
        assert out["min_hbm_free_bytes"] == 2000
        assert out["min_hbm_free_rank"] == 1
        assert stat_get("cluster_min_hbm_free_bytes") == 2000
        # a fleet without hbm reporters (CPU) omits the key
        for v in kv:
            kv[v] = json.dumps({"rank": 0, "ts": now, "interval_s": 10.0})
        out = health.cluster_health(kv, world_size=2, now=now)
        assert "min_hbm_free_bytes" not in out


# ---------------------------------------------------------------------------
# memory.json: postmortem bundle section + pure-stdlib CLI rendering
# ---------------------------------------------------------------------------


class TestMemoryJsonBundle:
    def _bundle_with_record(self, tmp_path, restore=None):
        xla_stats.clear_compile_records()
        pt.set_flags({"FLAGS_hbm_budget_fraction": 0.5,
                      "FLAGS_hbm_bytes_per_device": 1000})
        try:
            xla_stats.on_compile(
                _FakeCompiled(), fingerprint="feedface", seconds=0.5,
                size_entries=[("giant.w_0", (128, 128), "float32",
                               "state")])
        except MemoryBudgetError:
            pass  # 1600 > 500: the rejection is part of the fixture
        finally:
            pt.set_flags({"FLAGS_hbm_budget_fraction": 0.0,
                          "FLAGS_hbm_bytes_per_device": 0})
        return health.dump_postmortem("memtest", directory=str(tmp_path))

    def test_bundle_has_memory_section(self, tmp_path):
        bundle = self._bundle_with_record(tmp_path)
        with open(f"{bundle}/memory.json") as f:
            mem = json.load(f)
        assert mem["compiles"], "compile records missing from bundle"
        last = mem["compiles"][-1]
        assert last["memory"]["total_bytes"] == 1600
        assert last["budget"]["verdict"] == "rejected"
        # the rejection keeps its numbers (they matter MOST here)
        assert last["budget"]["required_bytes"] == 1600
        assert last["budget"]["budget_bytes"] == 500
        assert last["budget"]["capacity_bytes"] == 1000
        assert last["attribution"][0]["name"] == "giant.w_0"
        with open(f"{bundle}/meta.json") as f:
            meta = json.load(f)
        assert "memory.json" not in meta.get("section_errors", {})

    def test_postmortem_cli_renders_memory(self, tmp_path):
        from tools import postmortem

        bundle = self._bundle_with_record(tmp_path)
        buf = io.StringIO()
        assert postmortem.render(bundle, out=buf) == 0
        txt = buf.getvalue()
        assert "xla compiles recorded" in txt
        assert "giant.w_0" in txt
        assert "per-chip footprint" in txt
        assert "budget gate: rejected" in txt
        assert "memory.json" in txt  # listed among the bundle files


# ---------------------------------------------------------------------------
# /metrics well-formedness with the new gauges under concurrent scrape
# ---------------------------------------------------------------------------


class TestConcurrentScrapeWithXlaGauges:
    def test_scrape_while_compiles_record(self):
        """4 scrapers x 25 GETs over real HTTP while a thread feeds
        compile records (compile_seconds histogram + hbm/executable
        gauges): every exposition must stay well-formed and carry the
        new series — including the phase-attribution and profiler-
        capture gauges."""
        from paddle_tpu.distributed.fleet.utils.http_server import KVServer
        from paddle_tpu.monitor import stat_set
        from paddle_tpu.observe import phases as phases_mod

        # seed one record so the first scrape already sees the series
        xla_stats.on_compile(_FakeCompiled(), seconds=0.01)
        phases_mod.reset_phases()
        phases_mod.phase_engine().on_step_drained(
            wall_s=0.01, sync_s=0.005, host_s=0.001)
        stat_set("prof_capture_latched", 0)
        srv = KVServer(0)
        srv.start()
        stop = threading.Event()
        errors = []

        def compiler():
            i = 0
            while not stop.is_set():
                i += 1
                xla_stats.on_compile(
                    _FakeCompiled(flops=float(i)), seconds=1e-4 * i,
                    fingerprint=f"fp{i}",
                    size_entries=[("w", (i % 7 + 1, 8), "float32",
                                   "state")])

        def scraper():
            url = f"http://127.0.0.1:{srv.port}/metrics"
            for _ in range(25):
                try:
                    with urllib.request.urlopen(url, timeout=10) as r:
                        assert r.status == 200
                        body = r.read().decode()
                    for ln in body.splitlines():
                        if ln and not ln.startswith("#"):
                            float(ln.rsplit(" ", 1)[1])
                    assert "paddle_tpu_compile_seconds_bucket" in body
                    assert "paddle_tpu_hbm_required_bytes" in body
                    assert "paddle_tpu_executable_size_bytes" in body
                    assert "paddle_tpu_phase_compute_seconds_micro" \
                        in body
                    assert "paddle_tpu_phase_compute_fraction_ppm" \
                        in body
                    assert "paddle_tpu_prof_capture_latched" in body
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        t = threading.Thread(target=compiler, daemon=True)
        scrapers = [threading.Thread(target=scraper) for _ in range(4)]
        t.start()
        for s in scrapers:
            s.start()
        for s in scrapers:
            s.join()
        stop.set()
        t.join(timeout=10)
        srv.stop()
        assert errors == []
