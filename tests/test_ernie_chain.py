"""BASELINE config 5 analog: ERNIE-style finetune with the fleet
meta-optimizer CHAIN (amp + recompute together) on a transformer
encoder, data-parallel over the mesh.

Reference parity: fleet StrategyCompiler chaining
(strategy_compiler.py:89) with AMPOptimizer + RecomputeOptimizer around
the inner optimizer — the combination the reference ships for ERNIE.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import Program, program_guard

B, S, V, H = 8, 8, 32, 16


@pytest.fixture(autouse=True)
def _mesh_reset():
    from paddle_tpu.distributed.parallel_env import reset_mesh

    reset_mesh()
    yield
    reset_mesh()


def _build_finetune(strategy=None, use_fleet=False):
    """1-layer transformer encoder + classifier head (finetune shape)."""
    from paddle_tpu.optimizer import AdamWOptimizer
    from paddle_tpu.text.static_models import _encoder_layer
    from paddle_tpu.initializer import NormalInitializer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = Program(), Program()
    main.random_seed = 3
    with unique_name.guard(), program_guard(main, startup):
        ids = layers.data("ids", [B, S], dtype="int64",
                          append_batch_size=False)
        label = layers.data("label", [B, 1], dtype="int64",
                            append_batch_size=False)
        emb = layers.embedding(ids, (V, H), param_attr=ParamAttr(
            name="emb", initializer=NormalInitializer(0.0, 0.1)))
        y = _encoder_layer(emb, None, H, 4, 2 * H, dropout_prob=0.0,
                           name="enc", use_fused=False)
        cls = layers.slice(y, axes=[1], starts=[0], ends=[1])
        cls = layers.reshape(cls, [0, H])  # 0 = copy batch dim (shardable)
        logits = layers.fc(cls, 2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        opt = AdamWOptimizer(learning_rate=1e-2, weight_decay=0.01)
        if use_fleet:
            from paddle_tpu.distributed import fleet

            fleet.init(is_collective=True, strategy=strategy)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            opt.minimize(loss)
    return main, startup, loss


def _feed(rng):
    ids = rng.randint(0, V, (B, S)).astype("int64")
    label = (ids.sum(1, keepdims=True) % 2).astype("int64")
    return {"ids": ids, "label": label}


def test_amp_recompute_chain_builds_and_converges():
    """The chained program must carry BOTH rewrites (bf16 casts AND
    recompute re-emission barriers) and still converge."""
    from paddle_tpu.distributed import fleet

    strat = fleet.DistributedStrategy()
    strat.amp = True
    strat.recompute = True
    # checkpoint the encoder block boundary (post-LN output)
    main, startup, loss = _build_finetune(strategy=None, use_fleet=False)
    ck = [v for v in main.global_block.vars if "ln2" in v and "tmp" in v]
    strat.recompute_configs = {"checkpoints": ck[:1]}

    main2, startup2, loss2 = _build_finetune(strategy=strat, use_fleet=True)
    ops = [op.type for op in main2.global_block.ops]
    assert "cast" in ops, "amp rewrite missing from the chain"
    assert "recompute_barrier" in ops, "recompute rewrite missing"

    rng = np.random.RandomState(0)
    feed = _feed(rng)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup2, scope=scope)
    losses = [float(np.asarray(exe.run(
        main2, feed=feed, fetch_list=[loss2], scope=scope)[0]).ravel()[0])
        for _ in range(20)]
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] * 0.7, losses


def test_chain_matches_plain_amp_run():
    """Recompute must be a pure memory trade: amp+recompute losses equal
    amp-only losses (same numerics, re-emitted segments)."""
    from paddle_tpu.distributed import fleet

    rng = np.random.RandomState(1)
    feed = _feed(rng)

    def run(strat):
        main, startup, loss = _build_finetune(strategy=strat,
                                              use_fleet=strat is not None)
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.framework.Scope()
        exe.run(startup, scope=scope)
        return [float(np.asarray(exe.run(
            main, feed=feed, fetch_list=[loss], scope=scope)[0]).ravel()[0])
            for _ in range(6)]

    s_amp = fleet.DistributedStrategy()
    s_amp.amp = True
    amp_only = run(s_amp)

    probe_main, _, _ = _build_finetune()
    ck = [v for v in probe_main.global_block.vars
          if "ln2" in v and "tmp" in v]
    s_both = fleet.DistributedStrategy()
    s_both.amp = True
    s_both.recompute = True
    s_both.recompute_configs = {"checkpoints": ck[:1]}
    both = run(s_both)

    np.testing.assert_allclose(amp_only, both, rtol=1e-4, atol=1e-6)
