"""Executor.run_steps: K training steps in one XLA executable via lax.scan.

TPU-native replacement for the reference's train_from_dataset C++ loop
(paddle/fluid/framework/executor.cc:166) + buffered_reader prefetching:
instead of K python→executor round-trips, feeds carry a leading step dim
and the whole block scans on device.  Oracle: per-step exe.run losses.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.optimizer import MomentumOptimizer


def _build(lr=0.05, use_fleet=False):
    from paddle_tpu.distributed import fleet

    main_p, startup = Program(), Program()
    main_p.random_seed = 1
    with program_guard(main_p, startup):
        img = layers.data("img", [1, 28, 28])
        label = layers.data("label", [1], dtype="int64")
        c1 = layers.conv2d(img, 8, 3, padding=1, act="relu")
        p1 = layers.pool2d(c1, 2, "max", 2)
        f1 = layers.fc(p1, 32, act="relu")
        logits = layers.fc(f1, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        opt = MomentumOptimizer(lr, 0.9)
        if use_fleet:
            fleet.init(is_collective=True)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            opt.minimize(loss)
    return main_p, startup, loss


def _data(rng, K, B):
    imgs = rng.randn(K, B, 1, 28, 28).astype("float32")
    labels = rng.randint(0, 10, (K, B, 1)).astype("int64")
    return imgs, labels


def test_run_steps_matches_sequential(rng):
    K, B = 5, 16
    imgs, labels = _data(rng, K, B)

    main_p, startup, loss = _build()
    sc = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=sc)
    seq = [
        float(np.asarray(exe.run(main_p, feed={"img": imgs[i], "label": labels[i]},
                                 fetch_list=[loss], scope=sc)[0]).ravel()[0])
        for i in range(K)
    ]

    main_p2, startup2, loss2 = _build()
    sc2 = pt.framework.Scope()
    exe2 = pt.Executor(pt.CPUPlace())
    exe2.run(startup2, scope=sc2)
    out = exe2.run_steps(main_p2, feed={"img": imgs, "label": labels},
                         fetch_list=[loss2], scope=sc2, return_numpy=True)
    scan = np.asarray(out[0]).ravel()
    assert scan.shape == (K,)
    np.testing.assert_allclose(seq, scan, rtol=1e-5, atol=1e-6)


def test_run_steps_returns_device_arrays_without_numpy(rng):
    K, B = 3, 8
    imgs, labels = _data(rng, K, B)
    main_p, startup, loss = _build()
    sc = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=sc)
    out = exe.run_steps(main_p, feed={"img": imgs, "label": labels},
                        fetch_list=[loss], scope=sc)
    assert hasattr(out[0], "sharding")  # jax array, not numpy: async fetch


def test_run_steps_rejects_mismatched_step_dims(rng):
    main_p, startup, loss = _build()
    sc = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=sc)
    with pytest.raises(ValueError, match="leading step dim"):
        exe.run_steps(main_p,
                      feed={"img": np.zeros((3, 8, 1, 28, 28), "float32"),
                            "label": np.zeros((2, 8, 1), "int64")},
                      fetch_list=[loss], scope=sc)


def test_run_steps_mesh_matches_per_step(rng):
    import jax

    from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("dp",))
    K, B = 4, 16
    imgs, labels = _data(rng, K, B)
    set_mesh(mesh)
    try:
        main_p, startup, loss = _build(use_fleet=True)
        sc = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
        exe.run(startup, scope=sc)
        seq = [
            float(np.asarray(exe.run(main_p,
                                     feed={"img": imgs[i], "label": labels[i]},
                                     fetch_list=[loss], scope=sc)[0]).ravel()[0])
            for i in range(K)
        ]

        main_p2, startup2, loss2 = _build(use_fleet=True)
        sc2 = pt.framework.Scope()
        exe2 = pt.Executor(pt.CPUPlace(), mesh=mesh)
        exe2.run(startup2, scope=sc2)
        out = exe2.run_steps(main_p2, feed={"img": imgs, "label": labels},
                             fetch_list=[loss2], scope=sc2, return_numpy=True)
        np.testing.assert_allclose(seq, np.asarray(out[0]).ravel(),
                                   rtol=1e-4, atol=1e-5)
    finally:
        reset_mesh()
