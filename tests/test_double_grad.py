"""Double grad (create_graph=True): grad-of-grad vs pure-jax oracle.

Reference parity: paddle.grad / imperative/partial_grad_engine.cc (the
1.1k-LoC double-grad engine); here the backward is tape-recorded so the
engine differentiates itself.
"""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.dygraph.tensor import Tensor


def test_second_derivative_of_cube():
    # y = sum(x^3): dy/dx = 3x^2, d2y/dx2 via grad of sum(dy/dx) = 6x
    x = Tensor(np.array([1.0, 2.0, 3.0], "f4"), stop_gradient=False)
    y = x * x * x
    s = pt.tensor.math.sum(y)
    (gx,) = pt.grad(s, [x], create_graph=True)
    np.testing.assert_allclose(np.asarray(gx.numpy()),
                               3.0 * np.array([1, 4, 9], "f4"), rtol=1e-5)
    s2 = pt.tensor.math.sum(gx)
    (ggx,) = pt.grad(s2, [x])
    np.testing.assert_allclose(np.asarray(ggx.numpy()),
                               6.0 * np.array([1, 2, 3], "f4"), rtol=1e-5)


def test_gradient_penalty_matches_jax():
    """WGAN-GP style: penalty = mean((||dy/dx||_2 - 1)^2), backward
    through the penalty must match jax.grad of the same composite."""
    rs = np.random.RandomState(0)
    w_np = rs.randn(4, 1).astype("f4")
    x_np = rs.randn(3, 4).astype("f4")

    # paddle_tpu dygraph
    w = Tensor(w_np, stop_gradient=False)
    x = Tensor(x_np, stop_gradient=False)
    y = pt.tensor.math.sum(pt.tanh(pt.matmul(x, w)))
    (gx,) = pt.grad(y, [x], create_graph=True)
    gnorm = pt.tensor.math.sum(gx * gx)
    (gw,) = pt.grad(gnorm, [w])

    # jax oracle
    def f(w, x):
        return jnp.sum(jnp.tanh(x @ w))

    def penalty(w, x):
        gx = jax.grad(f, argnums=1)(w, x)
        return jnp.sum(gx * gx)

    want = jax.grad(penalty, argnums=0)(jnp.asarray(w_np), jnp.asarray(x_np))
    np.testing.assert_allclose(np.asarray(gw.numpy()), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_double_grad_then_backward_accumulates_param_grad():
    """grad(create_graph=True) composes with .backward() (the common GAN
    training pattern: total = task_loss + penalty; total.backward())."""
    rs = np.random.RandomState(1)
    w = Tensor(rs.randn(4, 1).astype("f4"), stop_gradient=False)
    x = Tensor(rs.randn(3, 4).astype("f4"), stop_gradient=False)
    y = pt.tensor.math.sum(pt.tanh(pt.matmul(x, w)))
    (gx,) = pt.grad(y, [x], create_graph=True)
    penalty = pt.tensor.math.sum(gx * gx)
    penalty.backward()
    assert w.grad is not None
    assert np.isfinite(np.asarray(w.grad.numpy())).all()
    assert np.any(np.asarray(w.grad.numpy()) != 0.0)
