"""Sharded checkpoint: save/restore ZeRO-sharded state over the mesh.

Beyond-reference (SURVEY §5 failure-recovery row): the ZeRO optimizer
state lives sharded over the dp axis; the checkpoint must round-trip it
distributed and resume the exact loss trajectory.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.distributed.checkpoint import load_sharded, save_sharded
from paddle_tpu.distributed.parallel_env import (init_parallel_env,
                                                 reset_mesh)
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import Program, program_guard


# mesh8 fixture: shared in tests/conftest.py


def _build_sharded():
    from paddle_tpu.distributed import fleet
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.optimizer import MomentumOptimizer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = Program(), Program()
    main.random_seed = 1
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        h = layers.fc(x, 16, act="relu", param_attr=ParamAttr(
            initializer=ConstantInitializer(0.1)), bias_attr=False)
        pred = layers.fc(h, 1, param_attr=ParamAttr(
            initializer=ConstantInitializer(0.2)), bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        strat = fleet.DistributedStrategy()
        strat.sharding = True
        fleet.init(is_collective=True, strategy=strat)
        fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
        fleet.minimize(loss)
    return main, startup, loss


def _data():
    rs = np.random.RandomState(0)
    return rs.randn(32, 8).astype("f4"), rs.randn(32, 1).astype("f4")


def test_zero_sharded_state_roundtrip(tmp_path, mesh8):
    X, Y = _data()

    def fresh():
        main, startup, loss = _build_sharded()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh8)
        scope = pt.framework.Scope()
        exe.run(startup, scope=scope)
        return main, startup, loss, exe, scope

    def step(exe, main, loss, scope):
        return float(np.asarray(exe.run(
            main, feed={"x": X, "y": Y}, fetch_list=[loss],
            scope=scope)[0]).ravel()[0])

    # uninterrupted 6-step oracle
    main, _, loss, exe, scope = fresh()
    full = [step(exe, main, loss, scope) for _ in range(6)]

    # run A: 3 steps, save (state includes dp-sharded accumulators)
    main, _, loss, exe, scope = fresh()
    for _ in range(3):
        step(exe, main, loss, scope)
    saved = save_sharded(scope, str(tmp_path))
    assert saved, "nothing saved"
    # at least one saved array is genuinely sharded over the mesh
    import jax

    sharded = [n for n in saved
               if hasattr(scope.get_var(n), "sharding")
               and not scope.get_var(n).sharding.is_fully_replicated]
    assert sharded, "expected dp-sharded optimizer state in the checkpoint"

    # run B: fresh process-equivalent; one step materializes the sharded
    # layout, then restore and continue
    main2, _, loss2, exe2, scope2 = fresh()
    step(exe2, main2, loss2, scope2)
    load_sharded(scope2, str(tmp_path))
    resumed = [step(exe2, main2, loss2, scope2) for _ in range(3)]
    np.testing.assert_allclose(resumed, full[3:6], rtol=1e-5, atol=1e-7)
