"""Dygraph runtime tests: eager ops, tape autograd, Layer.

Parity model: reference unittests test_imperative_basic.py /
test_imperative_autograd_*.py — grads checked against jax.grad oracles.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.dygraph import Layer, Parameter, Tensor, no_grad, run_op, to_variable


def t(x, stop_gradient=True):
    v = to_variable(np.asarray(x, dtype="float32"))
    v.stop_gradient = stop_gradient
    return v


class TestEagerOps:
    def test_arithmetic_matches_numpy(self):
        a = np.random.RandomState(0).randn(3, 4).astype("float32")
        b = np.random.RandomState(1).randn(3, 4).astype("float32") + 2.0
        x, y = t(a), t(b)
        np.testing.assert_allclose((x + y).numpy(), a + b, rtol=1e-6)
        np.testing.assert_allclose((x - y).numpy(), a - b, rtol=1e-6)
        np.testing.assert_allclose((x * y).numpy(), a * b, rtol=1e-6)
        np.testing.assert_allclose((x / y).numpy(), a / b, rtol=1e-5)
        np.testing.assert_allclose((x @ y.transpose([1, 0])).numpy(), a @ b.T, rtol=1e-5)
        np.testing.assert_allclose((-x).numpy(), -a, rtol=1e-6)
        np.testing.assert_allclose((x + 1.5).numpy(), a + 1.5, rtol=1e-6)
        np.testing.assert_allclose((2.0 - x).numpy(), 2.0 - a, rtol=1e-6)

    def test_comparisons_and_indexing(self):
        a = np.arange(12, dtype="float32").reshape(3, 4)
        x = t(a)
        assert (x > 5.0).numpy().dtype == np.bool_
        np.testing.assert_array_equal((x > 5.0).numpy(), a > 5.0)
        np.testing.assert_allclose(x[1].numpy(), a[1])
        np.testing.assert_allclose(x[:, 2].numpy(), a[:, 2])

    def test_reductions(self):
        a = np.random.RandomState(2).randn(2, 5).astype("float32")
        x = t(a)
        np.testing.assert_allclose(x.sum().numpy(), a.sum(), rtol=1e-5)
        np.testing.assert_allclose(x.mean(axis=1).numpy(), a.mean(1), rtol=1e-5)
        np.testing.assert_allclose(x.max(axis=0).numpy(), a.max(0), rtol=1e-6)

    def test_run_op_multi_output(self):
        a = np.random.RandomState(3).randn(4, 6).astype("float32")
        res = run_op("top_k_v2", {"X": t(a)}, {"k": 2, "axis": -1})
        vals, idx = res["Out"], res["Indices"]
        ref = np.sort(a, axis=-1)[:, ::-1][:, :2]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)
        assert idx.numpy().shape == (4, 2)


class TestAutograd:
    def test_simple_chain_grad(self):
        a = np.random.RandomState(0).randn(3, 4).astype("float32")
        x = t(a, stop_gradient=False)
        y = (x * x + x).mean()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), (2 * a + 1) / a.size, rtol=1e-5)

    def test_mlp_grads_match_jax(self):
        rs = np.random.RandomState(42)
        w1 = rs.randn(4, 8).astype("float32")
        w2 = rs.randn(8, 3).astype("float32")
        xv = rs.randn(5, 4).astype("float32")
        tv = rs.randn(5, 3).astype("float32")

        def loss_fn(w1v, w2v):
            h = jnp.maximum(xv @ w1v, 0.0)
            y = h @ w2v
            return jnp.mean((y - tv) ** 2)

        gw1_ref, gw2_ref = jax.grad(loss_fn, argnums=(0, 1))(w1, w2)

        W1, W2 = t(w1, False), t(w2, False)
        x = t(xv)
        h = run_op("relu", {"X": x @ W1}, {})["Out"]
        y = h @ W2
        diff = y - t(tv)
        loss = (diff * diff).mean()
        loss.backward()
        np.testing.assert_allclose(W1.grad.numpy(), np.asarray(gw1_ref), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(W2.grad.numpy(), np.asarray(gw2_ref), rtol=1e-4, atol=1e-5)

    def test_grad_accumulates(self):
        x = t([2.0], stop_gradient=False)
        (x * x).sum().backward()
        (x * 3.0).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0 + 3.0], rtol=1e-6)

    def test_shared_input_fanout(self):
        a = np.array([1.5, -2.0], dtype="float32")
        x = t(a, stop_gradient=False)
        y = x * x  # used twice below
        z = (y + y * 2.0).sum()  # dz/dx = 3 * 2x
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), 6 * a, rtol=1e-6)

    def test_no_grad_blocks_tape(self):
        x = t([1.0], stop_gradient=False)
        with no_grad():
            y = x * x
        assert y.stop_gradient
        assert y.grad_node is None

    def test_paddle_grad_api(self):
        a = np.array([3.0], dtype="float32")
        x = t(a, stop_gradient=False)
        y = x * x * x
        (gx,) = pt.grad(y.sum(), x)
        np.testing.assert_allclose(gx.numpy(), 3 * a * a, rtol=1e-5)
        assert x.grad is None  # paddle.grad does not touch .grad

    def test_grad_through_conv_softmax(self):
        rs = np.random.RandomState(7)
        img = rs.randn(2, 3, 8, 8).astype("float32")
        w = rs.randn(4, 3, 3, 3).astype("float32")
        lbl = rs.randint(0, 4, size=(2, 1)).astype("int64")
        W = t(w, False)
        conv = run_op("conv2d", {"Input": t(img), "Filter": W},
                      {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1], "groups": 1},
                      out_slots=("Output",))["Output"]
        pooled = conv.mean(axis=[2, 3])
        loss = run_op("softmax_with_cross_entropy",
                      {"Logits": pooled, "Label": Tensor(jnp.asarray(lbl))},
                      {"soft_label": False, "axis": -1})["Loss"].mean()
        loss.backward()
        assert W.grad is not None and W.grad.shape == list(w.shape)
        assert np.isfinite(W.grad.numpy()).all()


class MLP(Layer):
    def __init__(self):
        super().__init__()
        self.w1 = self.create_parameter([4, 8])
        self.b1 = self.create_parameter([8], is_bias=True)
        self.w2 = self.create_parameter([8, 2])

    def forward(self, x):
        h = run_op("relu", {"X": x @ self.w1 + self.b1}, {})["Out"]
        return h @ self.w2


class TestLayer:
    def test_parameters_and_state_dict(self):
        m = MLP()
        names = [n for n, _ in m.named_parameters()]
        assert names == ["w1", "b1", "w2"]
        sd = m.state_dict()
        assert set(sd.keys()) == {"w1", "b1", "w2"}

        m2 = MLP()
        m2.set_state_dict({k: v for k, v in sd.items()})
        for (_, p), (_, q) in zip(m.named_parameters(), m2.named_parameters()):
            np.testing.assert_allclose(p.numpy(), q.numpy())

    def test_sublayer_traversal_and_modes(self):
        class Outer(Layer):
            def __init__(self):
                super().__init__()
                self.inner = MLP()
                self.scale = self.create_parameter([1])

            def forward(self, x):
                return self.inner(x) * self.scale

        o = Outer()
        assert len(o.parameters()) == 4
        assert [n for n, _ in o.named_parameters()] == ["scale", "inner.w1", "inner.b1", "inner.w2"]
        o.eval()
        assert not o.inner.training
        o.train()
        assert o.inner.training

    def test_forward_backward_clear(self):
        m = MLP()
        x = t(np.random.RandomState(0).randn(6, 4).astype("float32"))
        out = m(x)
        out.mean().backward()
        assert all(p.grad is not None for p in m.parameters())
        m.clear_gradients()
        assert all(p.grad is None for p in m.parameters())

    def test_buffers(self):
        class BN(Layer):
            def __init__(self):
                super().__init__()
                self.register_buffer("running_mean", Tensor(jnp.zeros(4)))

            def forward(self, x):
                return x

        b = BN()
        assert "running_mean" in b.state_dict()
        b.running_mean = Tensor(jnp.ones(4))
        np.testing.assert_allclose(b.state_dict()["running_mean"].numpy(), np.ones(4))


class TestDropoutRNG:
    def test_dropout_deterministic_replay(self):
        """Replayed forward (backward pass) must see the same mask."""
        x = t(np.ones((64, 64), dtype="float32"), stop_gradient=False)
        out = run_op("dropout", {"X": x},
                     {"dropout_prob": 0.5, "is_test": False,
                      "dropout_implementation": "upscale_in_train"})["Out"]
        out.sum().backward()
        # grad is 1/keep_prob exactly where mask kept values
        g = x.grad.numpy()
        o = out.numpy()
        np.testing.assert_allclose((g > 0), (o > 0))


class TestGradHooks:
    """Tensor.register_hook (reference imperative/hooks.h): fires when
    the grad is computed, may replace it, removable."""

    def test_hook_scales_leaf_grad(self):
        from paddle_tpu import dygraph

        with dygraph.guard():
            x = dygraph.to_variable(np.array([1.0, 2.0], "f4"))
            x.stop_gradient = False
            x.register_hook(lambda g: g * 2.0)
            (x * 3.0).sum().backward()
            np.testing.assert_allclose(np.asarray(x.grad._value),
                                       [6.0, 6.0])

    def test_hook_on_intermediate_and_remove(self):
        from paddle_tpu import dygraph

        with dygraph.guard():
            x = dygraph.to_variable(np.array([1.0, 2.0], "f4"))
            x.stop_gradient = False
            h = x * 2.0          # intermediate
            seen = []
            handle = h.register_hook(lambda g: seen.append(1) or g * 10.0)
            (h * 1.0).sum().backward()
            assert seen == [1]
            np.testing.assert_allclose(np.asarray(x.grad._value),
                                       [20.0, 20.0])  # 2 * 10

            x2 = dygraph.to_variable(np.array([1.0], "f4"))
            x2.stop_gradient = False
            h2 = x2 * 2.0
            handle2 = h2.register_hook(lambda g: g * 10.0)
            handle2.remove()
            (h2 * 1.0).sum().backward()
            np.testing.assert_allclose(np.asarray(x2.grad._value), [2.0])

    def test_hook_on_stopped_tensor_is_loud(self):
        from paddle_tpu import dygraph

        with dygraph.guard():
            x = dygraph.to_variable(np.array([1.0], "f4"))  # stop_gradient
            with pytest.raises(RuntimeError, match="stop_gradient"):
                x.register_hook(lambda g: g)

    def test_hooks_fire_through_paddle_grad(self):
        from paddle_tpu import dygraph
        from paddle_tpu.dygraph import grad as pgrad

        with dygraph.guard():
            x = dygraph.to_variable(np.array([1.0, 2.0], "f4"))
            x.stop_gradient = False
            x.register_hook(lambda g: g * 2.0)
            h = x * 2.0
            h.register_hook(lambda g: g * 10.0)
            out = (h * 1.0).sum()
            gs = pgrad([out], [h, x])
            # h's reported grad is its HOOKED value; x's grad saw the
            # hooked cotangent AND its own leaf hook: 2*10*2 = 40
            np.testing.assert_allclose(np.asarray(gs[0]._value),
                                       [10.0, 10.0])
            np.testing.assert_allclose(np.asarray(gs[1]._value),
                                       [40.0, 40.0])

    def test_hooks_fire_under_create_graph(self):
        from paddle_tpu import dygraph
        from paddle_tpu.dygraph import grad as pgrad

        with dygraph.guard():
            x = dygraph.to_variable(np.array([3.0], "f4"))
            x.stop_gradient = False
            h = x * x
            h.register_hook(lambda g: g * 10.0)
            out = (h * 1.0).sum()
            (gx,) = pgrad([out], [x], create_graph=True)
            np.testing.assert_allclose(np.asarray(gx._value), [60.0])

    def test_one_shot_hook_does_not_skip_neighbor(self):
        from paddle_tpu import dygraph

        with dygraph.guard():
            x = dygraph.to_variable(np.array([1.0], "f4"))
            x.stop_gradient = False
            calls = []
            handle_box = []

            def one_shot(g):
                calls.append("a")
                handle_box[0].remove()
                return g

            handle_box.append(x.register_hook(one_shot))
            x.register_hook(lambda g: calls.append("b") or g)
            (x * 1.0).sum().backward()
            assert calls == ["a", "b"], calls
