"""A/B harness: plain XLA attention vs flash kernels, fwd+bwd, on the
real chip.  Writes artifacts/flash_ab.json; the numbers back the
engagement heuristic documented in ops/fused.py:_flash_engaged.

Run (TPU):  python artifacts/flash_ab.py
Each config measures a grad step of sum(attention(q,k,v,mask)^2) —
forward + backward, the training-shaped workload the heuristic serves.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, iters=10):
    jax.block_until_ready(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    from paddle_tpu.ops.fused import _plain_attention
    from paddle_tpu.ops.pallas_attention import flash_attention_bias

    on_tpu = jax.default_backend() == "tpu"
    results = {"backend": jax.default_backend(), "configs": []}
    shapes = [
        # (B, H, S, D) — BERT-base-ish through long-context
        (32, 12, 128, 64),
        (8, 12, 512, 64),
        (4, 12, 1024, 64),
        (2, 12, 2048, 64),
        (1, 12, 4096, 64),
    ]
    for b, h, s, d in shapes:
        rs = np.random.RandomState(0)
        q = jnp.asarray(rs.randn(b, h, s, d).astype("float32"))
        k = jnp.asarray(rs.randn(b, h, s, d).astype("float32"))
        v = jnp.asarray(rs.randn(b, h, s, d).astype("float32"))
        mask = jnp.asarray(
            np.where(rs.rand(b, 1, 1, s) > 0.2, 0.0, -1e9)
            .astype("float32"))
        scale = 1.0 / np.sqrt(d)

        @jax.jit
        def step_plain(q, k, v):
            def loss(q, k, v):
                return jnp.sum(
                    _plain_attention(q, k, v, mask, scale) ** 2)
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        @jax.jit
        def step_flash(q, k, v):
            def loss(q, k, v):
                return jnp.sum(flash_attention_bias(
                    q, k, v, mask, sm_scale=scale,
                    interpret=not on_tpu) ** 2)
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        t_plain = timeit(step_plain, q, k, v)
        t_flash = timeit(step_flash, q, k, v)
        results["configs"].append({
            "shape": [b, h, s, d],
            "plain_ms": round(t_plain * 1e3, 3),
            "flash_bias_ms": round(t_flash * 1e3, 3),
            "flash_speedup": round(t_plain / t_flash, 3),
            "scores_mb": round(4 * b * h * s * s / 2**20, 1),
        })
        print(results["configs"][-1], flush=True)

    out = os.path.join(os.path.dirname(__file__), "flash_ab.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote", out)


if __name__ == "__main__":
    main()
