#!/usr/bin/env bash
# Run the moment the axon TPU pool recovers: captures every artifact the
# round needs from the real chip, in priority order, each step logged.
# Usage: bash artifacts/on_chip_recovery.sh
set -u
cd "$(dirname "$0")/.."
LOG=artifacts/recovery_$(date +%H%M%S)
mkdir -p "$LOG"

echo "== 1. preflight =="
timeout 120 python -c "import jax; print(jax.devices())" \
    > "$LOG/preflight.log" 2>&1 || { echo "chip still down"; exit 1; }
cat "$LOG/preflight.log"

echo "== 2. flagship bench (ResNet-50 + BERT + pipeline) =="
timeout 1800 python bench.py | tee "$LOG/bench.json"

echo "== 3. flash attention A/B =="
timeout 1800 python artifacts/flash_ab.py | tee "$LOG/flash_ab.log"

echo "== 4. ResNet profile capture =="
timeout 900 python - <<'EOF' 2>&1 | tee "$LOG/profile.log"
import numpy as np, jax
import paddle_tpu as pt
from paddle_tpu.amp.static_amp import decorate
from paddle_tpu.framework.place import _default_place
from paddle_tpu.framework.program import program_guard
from paddle_tpu.vision.static_models import resnet50_train_program

main_p, startup, _, loss, opt = resnet50_train_program(lr=0.1, momentum=0.9)
main_p.random_seed = 1
with program_guard(main_p, startup):
    decorate(opt, use_bf16=True).minimize(loss)
exe = pt.Executor(_default_place())
scope = pt.framework.Scope()
exe.run(startup, scope=scope)
rng = np.random.RandomState(0)
feed = {"image": jax.device_put(rng.randn(128,3,224,224).astype("float32")),
        "label": jax.device_put(rng.randint(0,1000,(128,1)).astype("int32"))}
out = exe.run_steps(main_p, feed=feed, fetch_list=[loss], scope=scope, steps=10)
np.asarray(out[0])  # compile
with jax.profiler.trace("artifacts/resnet50_profile_r5"):
    out = exe.run_steps(main_p, feed=feed, fetch_list=[loss], scope=scope, steps=10)
    np.asarray(out[0])
print("profile captured to artifacts/resnet50_profile_r5")
EOF

echo "== done; artifacts in $LOG =="
