"""`paddle.autograd`: user-defined differentiable ops (PyLayer) and the
functional backward entry.

Role parity: reference python/paddle/autograd/py_layer.py (PyLayer:
forward/backward staticmethods + ctx.save_for_backward) and
paddle.autograd.backward.  TPU-native: a PyLayer becomes a
``jax.custom_vjp`` function recorded on the dygraph tape like any other
op — the engine's vjp replay then calls the USER's backward, so
PyLayers compose with the rest of autograd (including grad
accumulation and hooks) with no special casing in the engine.
"""
from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp

__all__ = ["PyLayer", "PyLayerContext", "backward"]


class PyLayerContext:
    """Reference py_layer.py PyLayerContext: carries state from forward
    to backward (``save_for_backward``/``saved_tensor`` plus arbitrary
    python attributes)."""

    def __init__(self):
        self._saved: tuple = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayer:
    """Custom differentiable operation.

    Subclass with two staticmethods::

        class Exp(PyLayer):
            @staticmethod
            def forward(ctx, x):
                y = paddle.exp(x)
                ctx.save_for_backward(y)
                return y

            @staticmethod
            def backward(ctx, dy):
                (y,) = ctx.saved_tensor()
                return dy * y

        y = Exp.apply(x)

    ``backward`` receives one cotangent per (tensor) forward output and
    must return one gradient per TENSOR forward input, in order (None
    for non-differentiable inputs).  The forward re-runs during the
    backward replay (the framework's vjp-replay design; XLA CSEs the
    recomputation under jit), so non-tensor ctx attributes set in
    forward are available in backward.
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        from .dygraph import base, eager
        from .dygraph.tensor import Tensor

        tensor_pos = [i for i, a in enumerate(args)
                      if isinstance(a, Tensor)]
        if not tensor_pos:
            raise ValueError(
                f"{cls.__name__}.apply needs at least one Tensor input")
        kw_tensors = [k for k, v in kwargs.items()
                      if isinstance(v, Tensor)]
        if kw_tensors:
            # reference PyLayer semantics: keyword tensors are legal but
            # NON-DIFFERENTIABLE — say so loudly instead of silently
            import warnings

            warnings.warn(
                f"{cls.__name__}.apply: keyword tensor(s) {kw_tensors} "
                f"are treated as non-differentiable constants (pass "
                f"positionally to get gradients)", RuntimeWarning,
                stacklevel=2)
        const_args = {i: a for i, a in enumerate(args)
                      if not isinstance(a, Tensor)}
        n_args = len(args)
        tset = set(tensor_pos)
        cell: List[Any] = [None, False]  # [last forward ctx, out-is-tuple]

        def rebuild(vals):
            it = iter(vals)
            return [Tensor(next(it)) if i in tset else const_args[i]
                    for i in range(n_args)]

        def run_forward(vals):
            ctx = PyLayerContext()
            with base.no_grad():
                outs = cls.forward(ctx, *rebuild(vals), **kwargs)
            is_tuple = isinstance(outs, (list, tuple))
            outs_l = list(outs) if is_tuple else [outs]
            cell[0], cell[1] = ctx, is_tuple
            return tuple(o._value for o in outs_l)

        @jax.custom_vjp
        def f(*vals):
            return run_forward(vals)

        def f_fwd(*vals):
            out_vals = run_forward(vals)
            saved = tuple(t._value for t in cell[0]._saved)
            return out_vals, (saved, vals)

        def _is_float_dtype(v):
            return jnp.issubdtype(v.dtype, jnp.floating) or \
                jnp.issubdtype(v.dtype, jnp.complexfloating)

        def _zero_cot(v):
            # custom_vjp contract: integer primals take float0 cotangents
            if _is_float_dtype(v):
                return jnp.zeros_like(v)
            import numpy as np

            return np.zeros(np.shape(v), dtype=jax.dtypes.float0)

        def f_bwd(res, cots):
            saved_vals, in_vals = res
            ctx = cell[0] if cell[0] is not None else PyLayerContext()
            ctx._saved = tuple(Tensor(v) for v in saved_vals)
            # integer outputs carry float0 cotangents — the user's
            # backward sees None for those slots
            cot_ts = [None if getattr(c, "dtype", None) == jax.dtypes.float0
                      else Tensor(c) for c in cots]
            with base.no_grad():
                gs = cls.backward(ctx, *cot_ts)
            gs_l = list(gs) if isinstance(gs, (list, tuple)) else [gs]
            if len(gs_l) != len(in_vals):
                raise RuntimeError(
                    f"{cls.__name__}.backward returned {len(gs_l)} "
                    f"gradient(s) for {len(in_vals)} tensor input(s)")
            out = []
            for g, v in zip(gs_l, in_vals):
                if g is None or not _is_float_dtype(v):
                    out.append(_zero_cot(v))
                else:
                    gv = g._value if isinstance(g, Tensor) else \
                        jnp.asarray(g)
                    out.append(gv.astype(v.dtype))
            return tuple(out)

        f.defvjp(f_fwd, f_bwd)

        # run the forward ONCE: the probe learns the output count AND
        # seeds f's first invocation (apply_jax re-invokes f to record;
        # without the seed the user forward would execute twice per
        # apply).  Backward replays miss the cache and re-run, which is
        # the framework's normal vjp-replay behavior.
        probe_vals = run_forward(
            tuple(args[i]._value for i in tensor_pos))
        cache = [probe_vals]

        orig_run = run_forward

        def run_forward_cached(vals):
            if cache:
                return cache.pop()
            return orig_run(vals)

        run_forward = run_forward_cached  # noqa: F811 (f closes over name)
        outs = eager.apply_jax(f, *(args[i] for i in tensor_pos),
                               n_out=len(probe_vals))
        outs_l = outs if isinstance(outs, list) else [outs]
        if cell[1]:
            return tuple(outs_l)
        return outs_l[0]


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Reference paddle.autograd.backward: run the tape from ``tensors``
    with optional explicit cotangents."""
    from .dygraph.backward import run_backward

    tensors = list(tensors) if isinstance(tensors, (list, tuple)) \
        else [tensors]
    seeds = None
    if grad_tensors is not None:
        seeds = list(grad_tensors) if isinstance(
            grad_tensors, (list, tuple)) else [grad_tensors]
        if len(seeds) != len(tensors):
            raise ValueError(
                f"backward: grad_tensors has {len(seeds)} entries for "
                f"{len(tensors)} tensors (a shorter list would silently "
                f"zero the cotangents of the extra tensors)")
    run_backward(tensors, seeds=seeds, retain_graph=retain_graph)
