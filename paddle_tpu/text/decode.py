"""Sequence decoding: greedy and fixed-beam search, XLA-static.

Role parity: reference BeamSearchDecoder + dynamic_decode
(python/paddle/fluid/layers/rnn.py:866, :1398) and the LoD beam-search
kernels (paddle/fluid/operators/math/beam_search.cc).  TPU-native
redesign per SURVEY §7: no TensorArrays or LoD beam shrinking — a
`lax.scan` over `max_len` steps carries a fixed [batch, beam] lane set;
finished beams are forced to extend with `end_id` at zero added
log-prob, so they keep competing in the joint top-k exactly like the
reference's merged finished/alive queue.  Everything is jittable and
shape-static (MXU-friendly: the step_fn's matmuls stay batched over
batch*beam).

The step function contract:

    step_fn(token_ids, state) -> (logits, new_state)

with `token_ids` int32 [N], `logits` float [N, vocab], and `state` any
pytree batched on dim 0 (N = batch*beam for beam search; beam search
reorders it by parent beam every step).
"""
from __future__ import annotations


def greedy_search(step_fn, init_state, init_ids, max_len, end_id):
    """Argmax decoding.

    Args:
        init_ids: int32 [batch] start tokens (BOS).
        max_len: number of generated tokens (static).
        end_id: EOS token id; generation sticks to EOS once emitted.
    Returns:
        (ids [batch, max_len] int32, scores [batch] float32 — the summed
        log-probs of the chosen tokens up to and including EOS).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    init_ids = jnp.asarray(init_ids, jnp.int32)

    def body(carry, _):
        state, cur, done, score = carry
        logits, state = step_fn(cur, state)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        tok = jnp.where(done, jnp.int32(end_id), tok)
        step_lp = jnp.take_along_axis(logp, tok[:, None], axis=1)[:, 0]
        score = score + jnp.where(done, 0.0, step_lp)
        done = jnp.logical_or(done, tok == end_id)
        return (state, tok, done, score), tok

    b = init_ids.shape[0]
    carry0 = (init_state, init_ids, jnp.zeros((b,), bool),
              jnp.zeros((b,), jnp.float32))
    (_, _, _, scores), toks = lax.scan(body, carry0, None, length=max_len)
    return jnp.transpose(toks, (1, 0)), scores


def beam_search(step_fn, init_state, init_ids, beam_size, max_len, end_id,
                length_penalty=0.0):
    """Fixed-beam search (reference BeamSearchDecoder semantics).

    Args:
        init_state: pytree batched [batch, ...]; tiled to batch*beam
            internally (reference tile_beam_merge_with_batch,
            rnn.py:934).
        init_ids: int32 [batch] BOS tokens.
        beam_size: number of lanes kept per batch element (static).
        length_penalty: GNMT alpha; final score =
            log_prob / ((5 + len) / 6) ** alpha.
    Returns:
        (ids [batch, beam, max_len] int32 — best beam first,
         scores [batch, beam] float32 — length-penalized log-probs).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    K = int(beam_size)
    init_ids = jnp.asarray(init_ids, jnp.int32)
    B = init_ids.shape[0]
    NEG = jnp.float32(-1e9)

    state = jax.tree.map(lambda v: jnp.repeat(v, K, axis=0), init_state)
    cur = jnp.repeat(init_ids, K)
    # only lane 0 live initially so step 1 yields K DISTINCT expansions
    log_probs = jnp.tile(
        jnp.concatenate([jnp.zeros((1,), jnp.float32),
                         jnp.full((K - 1,), NEG)]), (B,)).reshape(B, K)
    finished = jnp.zeros((B, K), bool)

    def body(carry, _):
        state, cur, log_probs, finished = carry
        logits, state = step_fn(cur, state)
        V = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32)) \
            .reshape(B, K, V)
        # finished lanes extend ONLY with end_id at zero cost, keeping
        # their score frozen while still competing in the joint top-k
        eos_row = jnp.full((V,), NEG).at[end_id].set(0.0)
        logp = jnp.where(finished[:, :, None], eos_row[None, None, :], logp)
        total = (log_probs[:, :, None] + logp).reshape(B, K * V)
        top_scores, top_idx = lax.top_k(total, K)  # [B, K]
        parent = top_idx // V
        token = (top_idx % V).astype(jnp.int32)
        finished = jnp.take_along_axis(finished, parent, axis=1)
        finished = jnp.logical_or(finished, token == end_id)
        gidx = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
        state = jax.tree.map(lambda v: v[gidx], state)
        return (state, token.reshape(-1), top_scores, finished), \
            (token, parent.astype(jnp.int32))

    carry0 = (state, cur, log_probs, finished)
    (_, _, log_probs, finished), (toks, parents) = lax.scan(
        body, carry0, None, length=int(max_len))

    # single O(max_len) ancestry walk instead of re-gathering the whole
    # ids buffer every step (shared with the beam_search_decode lowering)
    from ..ops.linalg_ops import backtrack_beams

    ids_buf = jnp.transpose(backtrack_beams(toks, parents),
                            (1, 2, 0))  # [T, B, K] -> [B, K, T]

    # length = index of first EOS + 1, or max_len when never finished
    is_eos = ids_buf == end_id
    first_eos = jnp.argmax(is_eos, axis=-1)
    lengths = jnp.where(is_eos.any(axis=-1), first_eos + 1, int(max_len))
    if length_penalty:
        lp = ((5.0 + lengths.astype(jnp.float32)) / 6.0) ** float(
            length_penalty)
    else:
        lp = jnp.ones_like(lengths, jnp.float32)
    scores = log_probs / lp
    order = jnp.argsort(-scores, axis=-1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    ids_buf = jnp.take_along_axis(ids_buf, order[:, :, None], axis=1)
    return ids_buf, scores


def dynamic_decode(decoder_step, init_state, init_ids, max_len, end_id,
                   beam_size=None, **kw):
    """Reference dynamic_decode(rnn.py:1398) role: dispatch greedy vs
    beam by `beam_size`."""
    if beam_size is None or int(beam_size) <= 1:
        return greedy_search(decoder_step, init_state, init_ids, max_len,
                             end_id)
    return beam_search(decoder_step, init_state, init_ids, beam_size,
                       max_len, end_id, **kw)


__all__ = ["greedy_search", "beam_search", "dynamic_decode"]
