"""Static-graph BERT builders — BASELINE.json config 3 flagship workload.

Role parity: the reference's transformer workload lives in
python/paddle/fluid/tests/unittests/dist_transformer.py (fluid builder
functions emitting OpDescs) and the fused attention fast path in
paddle/fluid/operators/fused/multihead_matmul_op.cu.  TPU-native: the
builder defaults to the single fused_multihead_attention op (Pallas
flash kernel for long sequences, one fused XLA composition otherwise —
see ops/fused.py); ``use_fused_attention=False`` emits the reference's
plain matmul/softmax/dropout op chain instead.  Either way the whole
encoder compiles into one executable via the Executor.

Pretraining objective matches BERT phase 1: masked-LM over a seq-length
token stream (ignore_index marks unmasked positions) + next-sentence
prediction on the [CLS] vector.
"""
from __future__ import annotations

import math

from .. import layers
from ..initializer import NormalInitializer
from ..param_attr import ParamAttr


def _dense(x, size, act=None, name=None, init_std=0.02):
    return layers.fc(
        x, size, num_flatten_dims=len(x.shape) - 1, act=act, name=name,
        param_attr=ParamAttr(initializer=NormalInitializer(0.0, init_std)))


def _attention(x, attn_mask, hidden, n_heads, dropout_prob, name,
               use_fused=True):
    """Multi-head self-attention: q/k/v projections -> scaled-dot-product
    -> output projection.  ``use_fused`` emits the single
    fused_multihead_attention op (Pallas flash kernel on TPU; note the
    fused path has no attention-probs dropout — the standard flash
    trade-off); otherwise the reference matmul/softmax/dropout chain."""
    s = int(x.shape[1])
    d = hidden // n_heads

    q = _dense(x, hidden, name=name + "_q")
    k = _dense(x, hidden, name=name + "_k")
    v = _dense(x, hidden, name=name + "_v")

    if use_fused:
        ctxv = layers.fused_multihead_attention(
            q, k, v, num_heads=n_heads, bias_qk=attn_mask,
            name=name + "_fmha")
        return _dense(ctxv, hidden, name=name + "_out")

    def split_heads(t, n):
        # [B, S, H] -> [B, heads, S, d]; 0 copies the batch dim so the
        # program shards over dp without baking the global batch size
        t = layers.reshape(t, [0, s, n_heads, d], name=n + "_r")
        return layers.transpose(t, [0, 2, 1, 3], name=n + "_t")

    q, k, v = (split_heads(t, name + sfx)
               for t, sfx in ((q, "_q"), (k, "_k"), (v, "_v")))
    # scores: [B, heads, S, S]; scale folded into the matmul (alpha)
    scores = layers.matmul(q, k, transpose_y=True, alpha=1.0 / math.sqrt(d),
                           name=name + "_qk")
    if attn_mask is not None:
        scores = layers.elementwise_add(scores, attn_mask, name=name + "_m")
    probs = layers.softmax(scores, name=name + "_sm")
    if dropout_prob:
        probs = layers.dropout(probs, dropout_prob, name=name + "_pd")
    ctxv = layers.matmul(probs, v, name=name + "_pv")  # [B, heads, S, d]
    ctxv = layers.transpose(ctxv, [0, 2, 1, 3], name=name + "_ct")
    ctxv = layers.reshape(ctxv, [0, s, hidden], name=name + "_cr")
    return _dense(ctxv, hidden, name=name + "_out")


def _encoder_layer(x, attn_mask, hidden, n_heads, ffn_size, dropout_prob,
                   name, use_fused=True):
    """Post-LN transformer layer (original BERT): attn -> add&norm ->
    ffn(gelu) -> add&norm."""
    attn = _attention(x, attn_mask, hidden, n_heads, dropout_prob,
                      name + "_attn", use_fused=use_fused)
    if dropout_prob:
        attn = layers.dropout(attn, dropout_prob, name=name + "_ad")
    x = layers.layer_norm(layers.elementwise_add(x, attn),
                          begin_norm_axis=2, name=name + "_ln1")
    ffn = _dense(x, ffn_size, act="gelu", name=name + "_ffn1")
    ffn = _dense(ffn, hidden, name=name + "_ffn2")
    if dropout_prob:
        ffn = layers.dropout(ffn, dropout_prob, name=name + "_fd")
    return layers.layer_norm(layers.elementwise_add(x, ffn),
                             begin_norm_axis=2, name=name + "_ln2")


def bert_encoder(input_ids, token_type_ids, pos_ids, attn_mask,
                 vocab_size=30522, hidden=768, n_layers=12, n_heads=12,
                 ffn_size=3072, max_pos=512, type_vocab=2,
                 dropout_prob=0.1, use_fused_attention=True):
    """BERT encoder trunk: embeddings -> N transformer layers.

    Returns the [B, S, hidden] sequence output.
    """
    emb_attr = lambda n: ParamAttr(  # noqa: E731
        name=n, initializer=NormalInitializer(0.0, 0.02))
    we = layers.embedding(input_ids, (vocab_size, hidden),
                          param_attr=emb_attr("word_embedding"))
    pe = layers.embedding(pos_ids, (max_pos, hidden),
                          param_attr=emb_attr("pos_embedding"))
    te = layers.embedding(token_type_ids, (type_vocab, hidden),
                          param_attr=emb_attr("sent_embedding"))
    emb = layers.elementwise_add(layers.elementwise_add(we, pe), te)
    emb = layers.layer_norm(emb, begin_norm_axis=2, name="emb_ln")
    if dropout_prob:
        emb = layers.dropout(emb, dropout_prob, name="emb_drop")

    y = emb
    for i in range(n_layers):
        y = _encoder_layer(y, attn_mask, hidden, n_heads, ffn_size,
                           dropout_prob, name=f"enc_{i}",
                           use_fused=use_fused_attention)
    return y


def bert_base_pretrain_program(batch_size=64, seq_len=128, vocab_size=30522,
                               hidden=768, n_layers=12, n_heads=12,
                               ffn_size=3072, dropout_prob=0.1, lr=1e-4,
                               weight_decay=0.01, max_preds_per_seq=20,
                               use_fused_attention=True):
    """Build (main, startup, feeds, loss, optimizer) for one BERT-base
    pretraining step: masked-LM + NSP, AdamW — BASELINE.json config 3.

    The MLM head gathers the masked positions FIRST and projects only
    those ~max_preds_per_seq tokens onto the vocab (the standard
    pretraining data layout: masked positions/labels/weights come from
    the data pipeline).  Projecting all B*S positions would move a
    [B,S,30522] logits tensor through HBM for a 15% use rate — on TPU
    the gather costs nothing and the vocab matmul shrinks ~6x.

    Feeds: input_ids/token_type_ids/pos_ids [B,S] int64;
    input_mask [B,1,1,S] float32 (additive: 0 keep / -1e4 pad);
    masked_flat_pos [B*P] int64 (flattened b*S+pos indices);
    masked_labels [B*P,1] int64; masked_weights [B*P,1] float32
    (1.0 real prediction / 0.0 padding); nsp_labels [B,1] int64.
    """
    from ..framework.program import Program, program_guard
    from ..optimizer import AdamWOptimizer

    n_pred = batch_size * max_preds_per_seq
    main, startup = Program(), Program()
    with program_guard(main, startup):
        input_ids = layers.data("input_ids", [batch_size, seq_len],
                                dtype="int64", append_batch_size=False)
        token_type_ids = layers.data("token_type_ids", [batch_size, seq_len],
                                     dtype="int64", append_batch_size=False)
        pos_ids = layers.data("pos_ids", [batch_size, seq_len],
                              dtype="int64", append_batch_size=False)
        input_mask = layers.data("input_mask", [batch_size, 1, 1, seq_len],
                                 dtype="float32", append_batch_size=False)
        masked_flat_pos = layers.data("masked_flat_pos", [n_pred],
                                      dtype="int64", append_batch_size=False)
        masked_labels = layers.data("masked_labels", [n_pred, 1],
                                    dtype="int64", append_batch_size=False)
        masked_weights = layers.data("masked_weights", [n_pred, 1],
                                     dtype="float32", append_batch_size=False)
        nsp_labels = layers.data("nsp_labels", [batch_size, 1],
                                 dtype="int64", append_batch_size=False)

        seq_out = bert_encoder(
            input_ids, token_type_ids, pos_ids, input_mask,
            vocab_size=vocab_size, hidden=hidden, n_layers=n_layers,
            n_heads=n_heads, ffn_size=ffn_size, dropout_prob=dropout_prob,
            use_fused_attention=use_fused_attention)

        # --- masked-LM head on gathered positions only
        flat = layers.reshape(seq_out, [batch_size * seq_len, hidden])
        picked = layers.gather(flat, masked_flat_pos)  # [B*P, hidden]
        picked.shape = (n_pred, hidden)
        mlm = _dense(picked, hidden, act="gelu", name="mlm_trans")
        mlm = layers.layer_norm(mlm, begin_norm_axis=1, name="mlm_ln")
        mlm_logits = _dense(mlm, vocab_size, name="mlm_out")  # [B*P, V]
        tok_loss = layers.softmax_with_cross_entropy(
            mlm_logits, masked_labels)  # [B*P, 1]
        tok_loss = layers.elementwise_mul(tok_loss, masked_weights)
        denom = layers.elementwise_max(
            layers.reduce_sum(masked_weights), layers.ones([1]))
        mlm_loss = layers.elementwise_div(layers.reduce_sum(tok_loss), denom)

        # --- NSP head on [CLS] (position 0): tanh pool -> 2-way
        cls = layers.slice(seq_out, axes=[1], starts=[0], ends=[1])
        cls = layers.reshape(cls, [0, hidden])
        pooled = _dense(cls, hidden, act="tanh", name="pooler")
        nsp_logits = _dense(pooled, 2, name="nsp_out")
        nsp_loss = layers.mean(
            layers.softmax_with_cross_entropy(nsp_logits, nsp_labels))

        # mean() normalizes the [1]-vs-scalar shape mix from the div chain
        loss = layers.mean(
            layers.elementwise_add(mlm_loss, nsp_loss), name="total_loss")
        opt = AdamWOptimizer(learning_rate=lr, weight_decay=weight_decay)

    feeds = (input_ids, token_type_ids, pos_ids, input_mask,
             masked_flat_pos, masked_labels, masked_weights, nsp_labels)
    return main, startup, feeds, loss, opt
