"""``paddle.text.datasets``: NLP/tabular dataset loaders.

Reference parity: python/paddle/text/datasets/ (UCIHousing, Imdb,
Imikolov, Conll05, Movielens, WMT14/16).  This environment has zero
egress, so ``download=True`` raises with the upstream URL and the
loaders run off a local ``data_file`` — the parsing logic matches the
reference formats exactly.
"""
from __future__ import annotations

import gzip
import os
import re
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov"]

UCI_URL = "http://paddlemodels.bj.bcebos.com/uci_housing/housing.data"
IMDB_URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"
IMIKOLOV_URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"


def _require_file(data_file, url, name):
    if data_file is None:
        raise RuntimeError(
            f"{name}: automatic download is unavailable in this environment "
            f"(no egress); fetch {url} yourself and pass data_file=...")
    if not os.path.exists(data_file):
        raise FileNotFoundError(f"{name}: data_file {data_file!r} not found")
    return data_file


class UCIHousing(Dataset):
    """UCI housing regression set (reference text/datasets/uci_housing.py):
    13 features + target, 80/20 train/test split, feature-wise max-min
    normalization computed on the full data (reference semantics)."""

    def __init__(self, data_file=None, mode="train", download=False):
        data_file = _require_file(data_file, UCI_URL, "UCIHousing")
        raw = np.loadtxt(data_file).astype("float32")
        # reference feature normalization: (x - avg) / (max - min)
        maxs, mins, avgs = raw.max(0), raw.min(0), raw.mean(0)
        feat = (raw - avgs) / (maxs - mins)
        feat[:, -1] = raw[:, -1]  # target stays raw
        split = int(raw.shape[0] * 0.8)
        data = feat[:split] if mode == "train" else feat[split:]
        self.data = data[:, :-1]
        self.label = data[:, -1:].astype("float32")

    def __getitem__(self, idx):
        return self.data[idx], self.label[idx]

    def __len__(self):
        return len(self.data)


_TOKENIZE = re.compile(r"\w+|[<>]+")


class Imdb(Dataset):
    """IMDB sentiment set from the aclImdb tarball (reference
    text/datasets/imdb.py): word-frequency vocabulary with a cutoff,
    <unk> index = len(vocab)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        data_file = _require_file(data_file, IMDB_URL, "Imdb")
        self._tar = data_file
        self.word_idx = self._build_vocab(cutoff)
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        self.docs, self.labels = [], []
        unk = len(self.word_idx)
        with tarfile.open(self._tar) as tf:
            for m in tf.getmembers():
                g = pat.match(m.name)
                if not g:
                    continue
                text = tf.extractfile(m).read().decode("latin-1").lower()
                ids = [self.word_idx.get(w, unk)
                       for w in _TOKENIZE.findall(text)]
                self.docs.append(np.asarray(ids, "int64"))
                self.labels.append(
                    np.asarray([0 if g.group(1) == "pos" else 1], "int64"))

    def _build_vocab(self, cutoff):
        from collections import Counter

        freq = Counter()
        pat = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
        with tarfile.open(self._tar) as tf:
            for m in tf.getmembers():
                if pat.match(m.name):
                    text = tf.extractfile(m).read().decode("latin-1").lower()
                    freq.update(_TOKENIZE.findall(text))
        words = [w for w, c in freq.items() if c > cutoff and w != "<unk>"]
        words.sort(key=lambda w: (-freq[w], w))
        return {w: i for i, w in enumerate(words)}

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram set (reference text/datasets/imikolov.py): n-grams from
    simple-examples with <s>/<e> markers."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        data_file = _require_file(data_file, IMIKOLOV_URL, "Imikolov")
        self.window_size = window_size
        self.data_type = data_type.upper()
        name = f"./simple-examples/data/ptb.{ 'train' if mode == 'train' else 'valid'}.txt"
        from collections import Counter

        with tarfile.open(data_file) as tf:
            trn = tf.extractfile(
                "./simple-examples/data/ptb.train.txt").read().decode()
            txt = tf.extractfile(name).read().decode()
        freq = Counter(trn.split())
        freq = {w: c for w, c in freq.items() if c >= min_word_freq}
        words = sorted(freq, key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(words)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for line in txt.splitlines():
            toks = ["<s>"] + line.split() + ["<e>"]
            ids = [self.word_idx.get(w, unk) for w in toks]
            if self.data_type == "NGRAM":
                for i in range(len(ids) - window_size + 1):
                    self.data.append(
                        np.asarray(ids[i:i + window_size], "int64"))
            else:  # SEQ
                self.data.append((np.asarray(ids[:-1], "int64"),
                                  np.asarray(ids[1:], "int64")))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)
