"""``paddle.text``-role namespace: NLP model builders (datasets live in
``paddle_tpu.text.datasets`` when present).

Role parity: the reference ships its transformer/BERT workloads as fluid
builder scripts (python/paddle/fluid/tests/unittests/dist_transformer.py,
contrib ERNIE configs) plus a ``paddle.text`` dataset package.  The static
BERT builder here is the BASELINE.json config-3 flagship workload.
"""
from . import datasets  # noqa: F401
from . import decode  # noqa: F401
from . import static_models  # noqa: F401
from .decode import beam_search, dynamic_decode, greedy_search  # noqa: F401
from .static_models import bert_base_pretrain_program, bert_encoder  # noqa: F401
