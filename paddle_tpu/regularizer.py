"""Weight-decay regularizers (reference python/paddle/fluid/regularizer.py)."""
from __future__ import annotations

from .framework.program import default_main_program
from .framework import unique_name


class WeightDecayRegularizer:
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def _append(self, block, param, grad):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def _append(self, block, param, grad):
        out = block.create_var(
            name=unique_name.generate(grad.name + "_l2"), shape=grad.shape, dtype=grad.dtype
        )
        scaled = block.create_var(
            name=unique_name.generate(param.name + "_scaled"), shape=param.shape, dtype=param.dtype
        )
        block.append_op("scale", {"X": param}, {"Out": scaled}, {"scale": self._coeff})
        block.append_op("sum", {"X": [grad.name, scaled.name]}, {"Out": out})
        return out


class L1Decay(WeightDecayRegularizer):
    def _append(self, block, param, grad):
        sign = block.create_var(
            name=unique_name.generate(param.name + "_sign"), shape=param.shape, dtype=param.dtype
        )
        scaled = block.create_var(
            name=unique_name.generate(param.name + "_l1"), shape=param.shape, dtype=param.dtype
        )
        out = block.create_var(
            name=unique_name.generate(grad.name + "_l1out"), shape=grad.shape, dtype=grad.dtype
        )
        block.append_op("sign", {"X": param}, {"Out": sign})
        block.append_op("scale", {"X": sign}, {"Out": scaled}, {"scale": self._coeff})
        block.append_op("sum", {"X": [grad.name, scaled.name]}, {"Out": out})
        return out


# reference spelling aliases
L2DecayRegularizer = L2Decay
L1DecayRegularizer = L1Decay


def append_regularization_ops(params_grads, regularization=None):
    """Add decay terms to gradients (per-param regularizer overrides global)."""
    out = []
    block = default_main_program().global_block
    for p, g in params_grads:
        reg = getattr(p, "regularizer", None) or regularization
        if reg is None or g is None:
            out.append((p, g))
        else:
            out.append((p, reg._append(block, p, g)))
    return out
