"""LayerHelper: shared machinery for layer functions.

Role parity: reference python/paddle/fluid/layer_helper.py — creates
parameters in BOTH the main program (metadata) and the startup program
(initializer op), temp vars, and appends ops to the main program.
"""
from __future__ import annotations

from .framework import unique_name
from .framework.program import default_main_program, default_startup_program
from .initializer import (
    ConstantInitializer,
    XavierInitializer,
)
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs
        self.name = kwargs.get("name") or unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    # ------------------------------------------------------------------
    def create_parameter(
        self,
        attr,
        shape,
        dtype="float32",
        is_bias=False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        name = attr.name or unique_name.generate(f"{self.name}.w" if not is_bias else f"{self.name}.b")
        init = attr.initializer or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()
        # main program: metadata
        param = self.main_program.global_block.create_parameter(
            name, shape, dtype=dtype, trainable=attr.trainable
        )
        param.regularizer = attr.regularizer
        param.optimize_attr = {"learning_rate": attr.learning_rate}
        param.need_clip = attr.need_clip
        param.initializer = init
        # startup program: var + init op
        sb = self.startup_program.global_block
        sv = sb.create_var(name=name, shape=shape, dtype=dtype, persistable=True)
        init(sv, sb)
        return param

    def create_variable_for_type_inference(self, dtype="float32", stop_gradient=False):
        return self.block.create_var(
            name=unique_name.generate(f"{self.name}.tmp"),
            dtype=dtype,
            stop_gradient=stop_gradient,
        )

    def create_global_variable(self, shape, dtype="float32", persistable=True, name=None, initializer=None):
        name = name or unique_name.generate(f"{self.name}.gv")
        v = self.main_program.global_block.create_var(
            name=name, shape=shape, dtype=dtype, persistable=persistable, stop_gradient=True
        )
        sb = self.startup_program.global_block
        sv = sb.create_var(name=name, shape=shape, dtype=dtype, persistable=True)
        (initializer or ConstantInitializer(0.0))(sv, sb)
        return v

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        return self.block.append_op(type, inputs, outputs, attrs)

    def append_activation(self, out_var, act):
        if act is None:
            return out_var
        act_out = self.create_variable_for_type_inference(out_var.dtype)
        act_out.shape = tuple(out_var.shape)
        self.append_op(act, {"X": out_var}, {"Out": act_out})
        return act_out
