"""Statistics API (reference python/paddle/tensor/stat.py)."""
from __future__ import annotations

from . import math as _math


def mean(x, axis=None, keepdim=False, name=None):
    return _math.mean(x, axis, keepdim, name)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    m = _math.mean(x, axis, True)
    sq = _math.mean(_math.square(_math.subtract(x, m)), axis, keepdim)
    if unbiased:
        import numpy as np

        if axis is None:
            n = int(np.prod(x.shape))
        else:
            axes = [axis] if isinstance(axis, int) else list(axis)
            n = int(np.prod([x.shape[a] for a in axes]))
        if n > 1:
            sq = _math.scale(sq, n / (n - 1))
    return sq


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _math.sqrt(var(x, axis, unbiased, keepdim))


def numel(x, name=None):
    import numpy as np

    return int(np.prod(x.shape))


def median(x, axis=None, keepdim=False, name=None):
    from ..dygraph.eager import apply_jax
    import jax.numpy as jnp

    return apply_jax(lambda v: jnp.median(v, axis=axis, keepdims=keepdim), x)
