"""Tensor creation API (reference python/paddle/tensor/creation.py)."""
from __future__ import annotations

import numpy as np

from ..dispatch import op_call
from ..framework import dtypes


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    from ..dygraph.base import to_variable

    t = to_variable(data, dtype=dtype)
    t.stop_gradient = stop_gradient
    return t


def _shape_list(shape):
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s) for s in shape]


def full(shape, fill_value, dtype="float32", name=None):
    return op_call("fill_constant", {},
                   {"shape": _shape_list(shape), "dtype": dtypes.to_enum(dtype),
                    "value": float(fill_value)}, dtype=dtype, name=name)


def zeros(shape, dtype="float32", name=None):
    return full(shape, 0.0, dtype, name)


def ones(shape, dtype="float32", name=None):
    return full(shape, 1.0, dtype, name)


def full_like(x, fill_value, dtype=None, name=None):
    attrs = {"value": float(fill_value)}
    if dtype is not None:
        attrs["dtype"] = dtypes.to_enum(dtype)
    return op_call("fill_any_like", {"X": x}, attrs, dtype=dtype, name=name)


def zeros_like(x, dtype=None, name=None):
    return full_like(x, 0.0, dtype, name)


def ones_like(x, dtype=None, name=None):
    return full_like(x, 1.0, dtype, name)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = ("int64" if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
                 else "float32")
    nd = dtypes.to_np(dtype)
    sv = to_tensor(np.asarray(start, dtype=nd))
    ev = to_tensor(np.asarray(end, dtype=nd))
    pv = to_tensor(np.asarray(step, dtype=nd))
    return op_call("range", {"Start": sv, "End": ev, "Step": pv}, {},
                   dtype=dtype, name=name)


def linspace(start, stop, num, dtype="float32", name=None):
    sv = to_tensor(np.asarray(start, dtype="float32")) if not hasattr(start, "shape") else start
    ev = to_tensor(np.asarray(stop, dtype="float32")) if not hasattr(stop, "shape") else stop
    nv = to_tensor(np.asarray(num, dtype="int32")) if not hasattr(num, "shape") else num
    return op_call("linspace", {"Start": sv, "Stop": ev, "Num": nv},
                   {"dtype": dtypes.to_enum(dtype)}, dtype=dtype, name=name)


def eye(num_rows, num_columns=None, dtype="float32", name=None):
    return op_call("eye", {},
                   {"num_rows": int(num_rows),
                    "num_columns": int(num_columns) if num_columns is not None else -1,
                    "dtype": dtypes.to_enum(dtype)}, dtype=dtype, name=name)


def empty(shape, dtype="float32", name=None):
    return zeros(shape, dtype, name)  # deterministic stand-in


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype, name)


def assign(x, output=None):
    from ..framework.program import Variable
    from ..layer_helper import LayerHelper

    if isinstance(x, (np.ndarray, list, tuple, int, float)):
        arr = np.asarray(x)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        if isinstance(output, Variable):
            # static in-place form with constant data: materialize via
            # assign_value straight into the output var
            from ..framework import dtypes

            key = {"float32": "fp32_values", "int32": "int32_values",
                   "int64": "int64_values", "bool": "bool_values"}.get(
                       str(arr.dtype), "fp32_values")
            LayerHelper("assign").append_op(
                "assign_value", {}, {"Out": [output.name]},
                {"shape": list(arr.shape), "dtype": dtypes.to_enum(str(arr.dtype)),
                 key: arr.ravel().tolist()})
            return output
        x = to_tensor(arr)
    if output is None:
        return op_call("assign", {"X": x}, {})
    if isinstance(output, Variable):
        LayerHelper("assign").append_op("assign", {"X": [x.name]},
                                        {"Out": [output.name]}, {})
        return output
    output._set_raw(op_call("assign", {"X": x}, {})._value)
    return output


def diag(x, offset=0, padding_value=0, name=None):
    from ..dygraph.eager import apply_jax
    import jax.numpy as jnp

    def fn(v):
        out = jnp.diag(v, k=offset)
        if v.ndim == 1 and padding_value != 0:
            n = out.shape[0]
            mask = jnp.eye(n, k=offset, dtype=bool)
            out = jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
        return out

    return apply_jax(fn, x)


def tril(x, diagonal=0, name=None):
    return op_call("tril_triu", {"X": x}, {"diagonal": int(diagonal), "lower": True})


def triu(x, diagonal=0, name=None):
    return op_call("tril_triu", {"X": x}, {"diagonal": int(diagonal), "lower": False})


def meshgrid(*args, **kwargs):
    args = list(args[0]) if len(args) == 1 and isinstance(args[0], (list, tuple)) else list(args)
    return op_call("meshgrid", {"X": args}, {}, outs=("Out",),
                   out_counts={"Out": len(args)})
