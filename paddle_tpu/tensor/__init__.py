"""`paddle.tensor`-equivalent API (reference python/paddle/tensor/).

Every function works in both execution modes: eager Tensors run the op's
lowering rule immediately; graph Variables append the op to the default
program.  Importing this package also patches the functions onto Tensor
and Variable as methods (reference monkey-patch in tensor/__init__.py +
varbase_patch_methods.py).
"""
from . import creation, linalg, logic, manipulation, math, random, search, stat  # noqa: F401
from .creation import (  # noqa: F401
    arange, assign, diag, empty, empty_like, eye, full, full_like, linspace,
    meshgrid, ones, ones_like, to_tensor, tril, triu, zeros, zeros_like,
)
from .linalg import bmm, cholesky, cross, dist, dot, matmul, mm, norm  # noqa: F401
from .logic import (  # noqa: F401
    allclose, equal, equal_all, greater_equal, greater_than, is_empty,
    less_equal, less_than, logical_and, logical_not, logical_or, logical_xor,
    not_equal,
)
from .manipulation import (  # noqa: F401
    broadcast_to, chunk, concat, expand, expand_as, flatten, flip, gather,
    gather_nd, index_select, reshape, roll, scatter, scatter_nd_add, slice,
    split, squeeze, stack, strided_slice, t, take_along_axis, tile, transpose,
    unsqueeze, unstack,
)
from .math import (  # noqa: F401
    abs, acos, acosh, add, add_n, all, any, asin, asinh, atan, atanh, cast,
    ceil, clip, cos, cosh, cumsum, divide, erf, exp, expm1, floor,
    floor_divide, increment, isfinite, isinf, isnan, log, log1p, log2, log10,
    logsumexp, max, maximum, mean, min, minimum, mod, multiply, neg, pow,
    prod, reciprocal, remainder, round, rsqrt, scale, sign, sin, sinh, sqrt,
    square, subtract, sum, tan, tanh, trace, kron,
)
from .random import multinomial, normal, rand, randint, randn, randperm, uniform  # noqa: F401
from .search import (  # noqa: F401
    argmax, argmin, argsort, index_sample, masked_select, nonzero, sort, topk,
    where,
)
from .stat import median, numel, std, var  # noqa: F401

# ---------------------------------------------------------------------------
# method patching (reference: paddle monkey-patches Variable & VarBase)
# ---------------------------------------------------------------------------
_METHODS = dict(
    # math
    add=add, subtract=subtract, multiply=multiply, divide=divide,
    pow=pow, maximum=maximum, minimum=minimum, remainder=remainder,
    exp=exp, log=log, sqrt=sqrt, rsqrt=rsqrt, abs=abs, ceil=ceil, floor=floor,
    round=round, reciprocal=reciprocal, sign=sign, square=square, erf=erf,
    sin=sin, cos=cos, tan=tan, tanh=tanh, scale=scale, clip=clip, cumsum=cumsum,
    prod=prod, isnan=isnan, isinf=isinf, isfinite=isfinite, logsumexp=logsumexp,
    trace=trace,
    # reductions (eager Tensor already has sum/mean/max/min: keep those)
    all=all, any=any,
    # linalg
    matmul=matmul, mm=mm, bmm=bmm, dot=dot, norm=norm, dist=dist, t=t,
    cholesky=cholesky,
    # logic
    equal=equal, not_equal=not_equal, less_than=less_than, less_equal=less_equal,
    greater_than=greater_than, greater_equal=greater_equal,
    logical_and=logical_and, logical_or=logical_or, logical_xor=logical_xor,
    logical_not=logical_not, equal_all=equal_all, allclose=allclose,
    # manipulation
    flatten=flatten, squeeze=squeeze, unsqueeze=unsqueeze, tile=tile,
    expand=expand, expand_as=expand_as, broadcast_to=broadcast_to, flip=flip,
    roll=roll, gather=gather, gather_nd=gather_nd, index_select=index_select,
    scatter=scatter, scatter_nd_add=scatter_nd_add, split=split, chunk=chunk,
    unstack=unstack, take_along_axis=take_along_axis, concat=None,
    # search
    argmax=argmax, argmin=argmin, argsort=argsort, sort=sort, topk=topk,
    nonzero=nonzero, masked_select=masked_select, where=None,
    # creation-ish
    zeros_like=None, ones_like=None, full_like=None,
    # stat
    std=std, var=var, median=median, numel=None,
)


def _patch(cls, override=False):
    for name, fn in _METHODS.items():
        if fn is None:
            continue
        if override or not hasattr(cls, name):
            setattr(cls, name, fn)


def _patch_variable_operators(cls):
    """Static Variables get the same dunders as eager Tensors; python
    scalars are inlined by dispatch._const_to_var."""
    cls.__add__ = lambda s, o: add(s, o)
    cls.__radd__ = cls.__add__
    cls.__sub__ = lambda s, o: subtract(s, o)
    cls.__rsub__ = lambda s, o: subtract(o, s)
    cls.__mul__ = lambda s, o: multiply(s, o)
    cls.__rmul__ = cls.__mul__
    cls.__truediv__ = lambda s, o: divide(s, o)
    cls.__rtruediv__ = lambda s, o: divide(o, s)
    cls.__pow__ = lambda s, o: pow(s, o)
    cls.__neg__ = lambda s: scale(s, -1.0)
    cls.__matmul__ = lambda s, o: matmul(s, o)
    cls.__lt__ = lambda s, o: less_than(s, o)
    cls.__le__ = lambda s, o: less_equal(s, o)
    cls.__gt__ = lambda s, o: greater_than(s, o)
    cls.__ge__ = lambda s, o: greater_equal(s, o)
    cls.astype = lambda s, d: cast(s, d)
    cls.reshape = lambda s, shape, name=None: reshape(s, shape, name)
    cls.transpose = lambda s, perm, name=None: transpose(s, perm, name)
    cls.sum = lambda s, axis=None, keepdim=False, name=None: sum(s, axis, keepdim, name)
    cls.mean = lambda s, axis=None, keepdim=False, name=None: mean(s, axis, keepdim, name)
    cls.max = lambda s, axis=None, keepdim=False, name=None: max(s, axis, keepdim, name)
    cls.min = lambda s, axis=None, keepdim=False, name=None: min(s, axis, keepdim, name)
    cls.cast = cls.astype


def _install():
    from ..dygraph.tensor import Tensor
    from ..framework.program import Variable

    _patch(Tensor)
    _patch(Variable)
    _patch_variable_operators(Variable)
    # reshape in paddle 2.x takes a shape list; Tensor method signature matches


_install()
