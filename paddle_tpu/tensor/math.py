"""Tensor math API (reference python/paddle/tensor/math.py).

Each function is dual-mode via dispatch.op_call: eager on jax arrays or
appended to the static IR, same op either way.
"""
from __future__ import annotations

from ..dispatch import op_call
from ..framework import dtypes


def _ew(op_type, x, y, name=None, axis=-1):
    return op_call(op_type, {"X": x, "Y": y}, {"axis": axis}, name=name)


def add(x, y, name=None):
    return _ew("elementwise_add", x, y, name)


def subtract(x, y, name=None):
    return _ew("elementwise_sub", x, y, name)


def multiply(x, y, name=None):
    return _ew("elementwise_mul", x, y, name)


def divide(x, y, name=None):
    return _ew("elementwise_div", x, y, name)


def floor_divide(x, y, name=None):
    return _ew("elementwise_floordiv", x, y, name)


def remainder(x, y, name=None):
    return _ew("elementwise_mod", x, y, name)


mod = floor_mod = remainder


def pow(x, y, name=None):
    if isinstance(y, (int, float)):
        return op_call("pow", {"X": x}, {"factor": float(y)}, name=name)
    return _ew("elementwise_pow", x, y, name)


def maximum(x, y, name=None):
    return _ew("elementwise_max", x, y, name)


def minimum(x, y, name=None):
    return _ew("elementwise_min", x, y, name)


def _unary(op_type):
    def fn(x, name=None):
        return op_call(op_type, {"X": x}, {}, name=name)

    fn.__name__ = op_type
    return fn


exp = _unary("exp")
expm1 = _unary("expm1")
log = _unary("log")
log2 = _unary("log2")
log10 = _unary("log10")
log1p = _unary("log1p")
sqrt = _unary("sqrt")
rsqrt = _unary("rsqrt")
abs = _unary("abs")
ceil = _unary("ceil")
floor = _unary("floor")
round = _unary("round")
reciprocal = _unary("reciprocal")
sign = _unary("sign")
sin = _unary("sin")
sinh = _unary("sinh")
asin = _unary("asin")
asinh = _unary("asinh")
cos = _unary("cos")
cosh = _unary("cosh")
acos = _unary("acos")
acosh = _unary("acosh")
tan = _unary("tan")
atan = _unary("atan")
atanh = _unary("atanh")
tanh = _unary("tanh")
erf = _unary("erf")
square = _unary("square")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = op_call("scale", {"X": x},
                  {"scale": float(scale), "bias": float(bias),
                   "bias_after_scale": bool(bias_after_scale)}, name=name)
    if act:
        out = op_call(act, {"X": out}, {})
    return out


def neg(x, name=None):
    return scale(x, -1.0)


def increment(x, value=1.0, name=None):
    return op_call("increment", {"X": x}, {"step": float(value)}, name=name)


def _reduce(op_type):
    def fn(x, axis=None, keepdim=False, name=None):
        if axis is None:
            dim, reduce_all = [], True
        else:
            dim = [axis] if isinstance(axis, int) else list(axis)
            reduce_all = False
        return op_call(op_type, {"X": x},
                       {"dim": dim, "keep_dim": bool(keepdim), "reduce_all": reduce_all},
                       name=name)

    fn.__name__ = op_type
    return fn


sum = _reduce("reduce_sum")
mean = _reduce("reduce_mean")
max = _reduce("reduce_max")
min = _reduce("reduce_min")
prod = _reduce("reduce_prod")


def all(x, axis=None, keepdim=False, name=None):
    return _reduce("reduce_all")(x, axis, keepdim, name)


def any(x, axis=None, keepdim=False, name=None):
    return _reduce("reduce_any")(x, axis, keepdim, name)


def cumsum(x, axis=None, dtype=None, name=None):
    attrs = {"axis": -1 if axis is None else int(axis), "flatten": axis is None}
    out = op_call("cumsum", {"X": x}, attrs, name=name)
    if dtype is not None:
        out = cast(out, dtype)
    return out


def clip(x, min=None, max=None, name=None):
    lo = float(min) if min is not None else -3.4e38
    hi = float(max) if max is not None else 3.4e38
    return op_call("clip", {"X": x}, {"min": lo, "max": hi}, name=name)


def cast(x, dtype):
    return op_call("cast", {"X": x},
                   {"out_dtype": dtypes.to_enum(dtype), "in_dtype": 0},
                   dtype=dtype)


def isnan(x, name=None):
    return op_call("isnan_v2", {"X": x}, {}, dtype="bool")


def isinf(x, name=None):
    return op_call("isinf_v2", {"X": x}, {}, dtype="bool")


def isfinite(x, name=None):
    return op_call("isfinite_v2", {"X": x}, {}, dtype="bool")


def add_n(inputs, name=None):
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return op_call("sum", {"X": list(inputs)}, {}, name=name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return op_call("stanh", {"X": x}, {"scale_a": scale_a, "scale_b": scale_b})


def kron(x, y, name=None):
    from ..dygraph.eager import apply_jax
    import jax.numpy as jnp

    return apply_jax(jnp.kron, x, y)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    from ..dygraph.eager import apply_jax
    import jax.numpy as jnp

    return apply_jax(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    from ..dygraph.eager import apply_jax
    import jax.scipy.special as jsp

    ax = None if axis is None else (tuple(axis) if isinstance(axis, (list, tuple)) else axis)
    return apply_jax(lambda v: jsp.logsumexp(v, axis=ax, keepdims=keepdim), x)
