"""Random tensor API (reference python/paddle/tensor/random.py)."""
from __future__ import annotations

from ..dispatch import op_call
from ..framework import dtypes


def _shape_list(shape):
    if isinstance(shape, int):
        return [shape]
    return [int(s) for s in shape]


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    return op_call("uniform_random", {},
                   {"shape": _shape_list(shape), "dtype": dtypes.to_enum(dtype),
                    "min": float(min), "max": float(max), "seed": int(seed)},
                   dtype=dtype, name=name)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    return op_call("gaussian_random", {},
                   {"shape": _shape_list(shape), "dtype": dtypes.to_enum("float32"),
                    "mean": float(mean), "std": float(std), "seed": 0},
                   dtype="float32", name=name)


def randn(shape, dtype="float32", name=None):
    return op_call("gaussian_random", {},
                   {"shape": _shape_list(shape), "dtype": dtypes.to_enum(dtype),
                    "mean": 0.0, "std": 1.0, "seed": 0}, dtype=dtype, name=name)


def rand(shape, dtype="float32", name=None):
    return uniform(shape, dtype, 0.0, 1.0, name=name)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return op_call("randint", {},
                   {"shape": _shape_list(shape), "dtype": dtypes.to_enum(dtype),
                    "low": int(low), "high": int(high), "seed": 0},
                   dtype=dtype, name=name)


def randperm(n, dtype="int64", name=None):
    return op_call("randperm", {}, {"n": int(n), "dtype": dtypes.to_enum(dtype),
                                    "seed": 0}, dtype=dtype, name=name)


def multinomial(x, num_samples=1, replacement=False, name=None):
    from ..dygraph.eager import apply_jax
    from ..dygraph import base
    import jax
    import jax.numpy as jnp

    key = base.next_eager_key()

    def fn(probs):
        logits = jnp.log(jnp.maximum(probs, 1e-30))
        if replacement:
            return jax.random.categorical(key, logits, axis=-1,
                                          shape=probs.shape[:-1] + (num_samples,))
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(key, probs.shape, probs.dtype)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx

    return apply_jax(fn, x)
