"""Comparison / logical API (reference python/paddle/tensor/logic.py)."""
from __future__ import annotations

import numpy as np

from ..dispatch import op_call


def _cmp(op_type):
    def fn(x, y, name=None):
        return op_call(op_type, {"X": x, "Y": y}, {"axis": -1}, dtype="bool", name=name)

    fn.__name__ = op_type
    return fn


equal = _cmp("equal")
not_equal = _cmp("not_equal")
less_than = _cmp("less_than")
less_equal = _cmp("less_equal")
greater_than = _cmp("greater_than")
greater_equal = _cmp("greater_equal")


def _logical(op_type):
    def fn(x, y=None, out=None, name=None):
        if y is None:
            return op_call(op_type, {"X": x}, {}, dtype="bool", name=name)
        return op_call(op_type, {"X": x, "Y": y}, {}, dtype="bool", name=name)

    fn.__name__ = op_type
    return fn


logical_and = _logical("logical_and")
logical_or = _logical("logical_or")
logical_xor = _logical("logical_xor")


def logical_not(x, out=None, name=None):
    return op_call("logical_not", {"X": x}, {}, dtype="bool", name=name)


def equal_all(x, y, name=None):
    from . import math as _math

    return _math.all(equal(x, y))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    from ..dygraph.eager import apply_jax
    import jax.numpy as jnp

    return apply_jax(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                               equal_nan=equal_nan), x, y)


def is_empty(x, name=None):
    return bool(int(np.prod(x.shape)) == 0)
