"""Search / sort API (reference python/paddle/tensor/search.py)."""
from __future__ import annotations

from ..dispatch import op_call


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return op_call("arg_max", {"X": x},
                   {"axis": -1 if axis is None else int(axis),
                    "keepdims": bool(keepdim), "flatten": axis is None},
                   dtype="int64", name=name)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return op_call("arg_min", {"X": x},
                   {"axis": -1 if axis is None else int(axis),
                    "keepdims": bool(keepdim), "flatten": axis is None},
                   dtype="int64", name=name)


def argsort(x, axis=-1, descending=False, name=None):
    _, idx = op_call("argsort", {"X": x},
                     {"axis": int(axis), "descending": bool(descending)},
                     outs=("Out", "Indices"), name=name)
    return idx


def sort(x, axis=-1, descending=False, name=None):
    out, _ = op_call("argsort", {"X": x},
                     {"axis": int(axis), "descending": bool(descending)},
                     outs=("Out", "Indices"), name=name)
    return out


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    return op_call("top_k_v2", {"X": x},
                   {"k": int(k), "axis": -1 if axis is None else int(axis),
                    "largest": bool(largest), "sorted": bool(sorted)},
                   outs=("Out", "Indices"), name=name)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return op_call("where", {"Condition": condition, "X": x, "Y": y}, {}, name=name)


def nonzero(x, as_tuple=False):
    out = op_call("where_index", {"Condition": x}, {}, dtype="int64")
    if as_tuple:
        from .manipulation import unstack

        nd = len(x.shape)
        return tuple(unstack(out, axis=1, num=nd))
    return out


def index_sample(x, index):
    from .manipulation import take_along_axis

    return take_along_axis(x, index, axis=1)


def masked_select(x, mask, name=None):
    from ..dygraph.eager import apply_jax

    # dynamic output shape: eager-only (documented; XLA needs static shapes)
    return apply_jax(lambda v, m: v[m], x, mask)
