"""Linear algebra API (reference python/paddle/tensor/linalg.py)."""
from __future__ import annotations

from ..dispatch import op_call


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return op_call("matmul_v2", {"X": x, "Y": y},
                   {"trans_x": bool(transpose_x), "trans_y": bool(transpose_y)},
                   name=name)


def mm(input, mat2, name=None):
    return matmul(input, mat2, name=name)


def bmm(x, y, name=None):
    return op_call("bmm", {"X": x, "Y": y}, {}, name=name)


def dot(x, y, name=None):
    return op_call("dot", {"X": x, "Y": y}, {}, name=name)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if p == "fro" and axis is None:
        return op_call("frobenius_norm", {"X": x},
                       {"dim": [], "keep_dim": keepdim, "reduce_all": True}, name=name)
    if axis is None:
        axis = -1
    if isinstance(axis, (list, tuple)) and p == "fro":
        return op_call("frobenius_norm", {"X": x},
                       {"dim": list(axis), "keep_dim": keepdim, "reduce_all": False},
                       name=name)
    porder = {"inf": float("inf"), "-inf": float("-inf")}.get(p, p)
    return op_call("p_norm", {"X": x},
                   {"porder": float(porder), "axis": int(axis), "keepdim": keepdim,
                    "epsilon": 1e-12}, name=name)


def dist(x, y, p=2, name=None):
    from . import math as _math

    return norm(_math.subtract(x, y), p=float(p))


def transpose(x, perm, name=None):
    from .manipulation import transpose as _t

    return _t(x, perm, name)


def cross(x, y, axis=None, name=None):
    from ..dygraph.eager import apply_jax
    import jax.numpy as jnp

    ax = -1 if axis is None else axis
    return apply_jax(lambda a, b: jnp.cross(a, b, axis=ax), x, y)


def cholesky(x, upper=False, name=None):
    from ..dygraph.eager import apply_jax
    import jax.numpy as jnp

    def fn(v):
        c = jnp.linalg.cholesky(v)
        return jnp.swapaxes(c, -1, -2) if upper else c

    return apply_jax(fn, x)


def matmul_broadcast(x, y, name=None):
    return matmul(x, y, name=name)
