"""Tensor manipulation API (reference python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import numpy as np

from ..dispatch import op_call


def reshape(x, shape, name=None):
    return op_call("reshape2", {"X": x}, {"shape": [int(s) for s in shape]},
                   outs=("Out",), name=name)


def transpose(x, perm, name=None):
    return op_call("transpose2", {"X": x}, {"axis": [int(p) for p in perm]},
                   outs=("Out",), name=name)


def t(x, name=None):
    nd = len(x.shape)
    if nd <= 1:
        return x
    return transpose(x, [1, 0], name)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return op_call("flatten_contiguous_range", {"X": x},
                   {"start_axis": int(start_axis), "stop_axis": int(stop_axis)},
                   outs=("Out",), name=name)


def squeeze(x, axis=None, name=None):
    axes = [] if axis is None else ([axis] if isinstance(axis, int) else list(axis))
    return op_call("squeeze2", {"X": x}, {"axes": axes}, outs=("Out",), name=name)


def unsqueeze(x, axis, name=None):
    axes = [axis] if isinstance(axis, int) else list(axis)
    return op_call("unsqueeze2", {"X": x}, {"axes": axes}, outs=("Out",), name=name)


def concat(x, axis=0, name=None):
    return op_call("concat", {"X": list(x)}, {"axis": int(axis)}, name=name)


def stack(x, axis=0, name=None):
    return op_call("stack", {"X": list(x)}, {"axis": int(axis)}, outs=("Y",), name=name)


def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else x.shape[axis]
    return op_call("unstack", {"X": x}, {"axis": int(axis), "num": int(n)},
                   outs=("Y",), out_counts={"Y": int(n)}, name=name)


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "axis": int(axis), "sections": []}
    else:
        sections = [int(s) for s in num_or_sections]
        total = x.shape[int(axis)]
        if any(s == -1 for s in sections):
            known = sum(s for s in sections if s != -1)
            sections = [total - known if s == -1 else s for s in sections]
        n = len(sections)
        attrs = {"num": 0, "axis": int(axis), "sections": sections}
    return list(op_call("split", {"X": x}, attrs, outs=("Out",),
                        out_counts={"Out": n}, name=name))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis, name)


def tile(x, repeat_times, name=None):
    return op_call("tile", {"X": x},
                   {"expand_times": [int(r) for r in repeat_times],
                    "repeat_times": [int(r) for r in repeat_times]}, name=name)


def expand(x, shape, name=None):
    return op_call("expand_v2", {"X": x}, {"shape": [int(s) for s in shape]}, name=name)


def expand_as(x, y, name=None):
    return op_call("expand_as_v2", {"X": x, "target_tensor": y},
                   {"target_shape": [int(s) for s in y.shape]}, name=name)


def broadcast_to(x, shape, name=None):
    return expand(x, shape, name)


def flip(x, axis, name=None):
    axes = [axis] if isinstance(axis, int) else list(axis)
    return op_call("flip", {"X": x}, {"axis": axes}, name=name)


def roll(x, shifts, axis=None, name=None):
    shifts = [shifts] if isinstance(shifts, int) else list(shifts)
    axes = ([] if axis is None else ([axis] if isinstance(axis, int) else list(axis)))
    return op_call("roll", {"X": x}, {"shifts": shifts, "axis": axes}, name=name)


def gather(x, index, axis=0, name=None):
    return op_call("gather", {"X": x, "Index": index}, {"axis": int(axis)}, name=name)


def gather_nd(x, index, name=None):
    return op_call("gather_nd", {"X": x, "Index": index}, {}, name=name)


def index_select(x, index, axis=0, name=None):
    return op_call("index_select", {"X": x, "Index": index}, {"dim": int(axis)}, name=name)


def scatter(x, index, updates, overwrite=True, name=None):
    return op_call("scatter", {"X": x, "Ids": index, "Updates": updates},
                   {"overwrite": bool(overwrite)}, name=name)


def scatter_nd_add(x, index, updates, name=None):
    return op_call("scatter_nd_add", {"X": x, "Index": index, "Updates": updates},
                   {}, name=name)


def slice(x, axes, starts, ends, name=None):
    return op_call("slice", {"Input": x},
                   {"axes": [int(a) for a in axes],
                    "starts": [int(s) for s in starts],
                    "ends": [int(e) for e in ends]}, name=name)


def strided_slice(x, axes, starts, ends, strides, name=None):
    return op_call("strided_slice", {"Input": x},
                   {"axes": [int(a) for a in axes], "starts": [int(s) for s in starts],
                    "ends": [int(e) for e in ends], "strides": [int(s) for s in strides]},
                   name=name)


def take_along_axis(arr, indices, axis, name=None):
    return op_call("take_along_axis", {"Input": arr, "Index": indices},
                   {"Axis": int(axis)}, outs=("Result",), name=name)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    from ..dygraph.eager import apply_jax
    import jax.numpy as jnp

    size = index_num // nshards

    def fn(v):
        shard = v // size
        return jnp.where(shard == shard_id, v % size, ignore_value)

    return apply_jax(fn, input)
