"""``paddle.static`` namespace (reference python/paddle/static/__init__.py)
assembled over the existing IR/Executor machinery.

BuildStrategy / ExecutionStrategy / CompiledProgram survive as honest
shims: every pass/fusion/memory knob they carry is XLA's job in this
framework (SURVEY §2.2 TPU equivalent row), so the classes record the
settings for API compatibility and the Executor compiles identically.
"""
from __future__ import annotations

import contextlib

from ..fluid import scope_guard  # noqa: F401
from ..framework import (  # noqa: F401
    Executor,
    Program,
    Scope,
    Variable,
    default_main_program,
    default_startup_program,
    global_scope,
    program_guard,
)
from ..framework.backward import append_backward, calc_gradient  # noqa: F401
from ..framework import unique_name  # noqa: F401
from ..fluid.io import (  # noqa: F401
    load_inference_model,
    save_inference_model,
)
from ..hapi.model import InputSpec  # noqa: F401
from ..layers import data  # noqa: F401
from ..param_attr import WeightNormParamAttr  # noqa: F401
from ..serialization import load, save  # noqa: F401

# static nn layer surface (reference paddle.static.nn)
from .. import layers as nn  # noqa: F401


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference paddle.static.gradients -> fluid calc_gradient."""
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)


@contextlib.contextmanager
def name_scope(prefix=None):
    """Reference fluid.name_scope: prefixes generated var names."""
    with unique_name.guard(prefix + "/" if prefix else None):
        yield


def cpu_places(device_count=None):
    from ..framework.place import CPUPlace

    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    # CUDA does not exist here; map to the TPU place list for script parity
    from ..framework.place import TPUPlace

    ids = device_ids if device_ids is not None else [0]
    return [TPUPlace(i) for i in ids]


def tpu_places(device_ids=None):
    from ..framework.place import TPUPlace

    ids = device_ids if device_ids is not None else [0]
    return [TPUPlace(i) for i in ids]


class BuildStrategy:
    """Tier-2 config shim (reference details/build_strategy.h): pass
    toggles are recorded; XLA owns fusion/memory/scheduling."""

    def __init__(self):
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0
        self.debug_graphviz_path = ""
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True
        self.sync_batch_norm = False
        self.enable_auto_fusion = True


class ExecutionStrategy:
    """Tier-2 config shim (reference execution_strategy.h)."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class CompiledProgram:
    """Reference fluid.compiler.CompiledProgram: wraps a Program with
    build/exec strategies.  The Executor accepts it anywhere a Program
    goes; with_data_parallel maps to the mesh executor (the reference's
    ParallelExecutor role is the shard_map path)."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()
        self._places = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._build_strategy = build_strategy or self._build_strategy
        self._places = places
        return self

    # duck-type as a Program for Executor.run
    def __getattr__(self, name):
        return getattr(self._program, name)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference paddle.static.py_func: embed a host python callable; see
    ops/misc_ops.py py_func lowering (jax.pure_callback)."""
    from ..layer_helper import LayerHelper
    from ..ops import misc_ops

    fid = id(func)
    misc_ops.register_py_func(fid, func)
    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    helper.append_op("py_func", {"X": list(xs)}, {"Out": list(outs)},
                     {"forward_callable_id": fid})
    return out


class Print:  # pragma: no cover - debugging helper
    def __new__(cls, input, *a, **k):
        return input


__all__ = [
    "append_backward", "gradients", "Executor", "global_scope",
    "scope_guard", "BuildStrategy", "CompiledProgram", "ExecutionStrategy",
    "ParallelExecutor", "program_guard", "WeightNormParamAttr",
    "default_main_program", "default_startup_program", "Program", "data",
    "InputSpec", "save", "load", "save_inference_model",
    "load_inference_model", "cpu_places", "cuda_places", "tpu_places",
    "Variable", "name_scope", "py_func", "nn",
]

ParallelExecutor = CompiledProgram  # role collapsed into the mesh Executor
