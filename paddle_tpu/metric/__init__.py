"""`paddle.metric` equivalent (reference python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing of (pred, label) on device; default
        passthrough."""
        return args


def _np(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        maxk = max(self.topk)
        order = np.argsort(-pred_np, axis=-1)[..., :maxk]
        correct = (order == label_np[..., None]).astype("f4")
        return correct

    def update(self, correct):
        correct = _np(correct)
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].sum()
            self.count[i] += correct.shape[0]
        res = self.total / np.maximum(self.count, 1)
        return res[0] if len(self.topk) == 1 else res

    def accumulate(self):
        res = (self.total / np.maximum(self.count, 1)).tolist()
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0.0
        self.fp = 0.0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype("i4").ravel()
        labels = _np(labels).astype("i4").ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0.0
        self.fn = 0.0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype("i4").ravel()
        labels = _np(labels).astype("i4").ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Streaming ROC-AUC via histogram buckets (reference metrics.py Auc /
    operators/metrics/auc_op)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name or "auc"
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.ravel()
        labels = _np(labels).ravel()
        buckets = np.minimum((preds * self.num_thresholds).astype("i8"),
                             self.num_thresholds)
        for b, l in zip(buckets, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = tot_neg = auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            pos, neg = self._stat_pos[i], self._stat_neg[i]
            auc += neg * (tot_pos + pos + tot_pos) / 2.0  # trapezoid
            tot_pos += pos
            tot_neg += neg
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    """Functional accuracy (reference paddle.metric.accuracy)."""
    from ..dispatch import op_call
    from ..tensor.search import topk as _topk

    values, indices = _topk(input, k)
    res = op_call("accuracy", {"Out": values, "Indices": indices,
                               "Label": label}, {},
                  outs=("Accuracy",))
    return res
