"""Model-compression toolkit (reference
python/paddle/fluid/contrib/slim/): quantization-aware training and
post-training quantization over static Programs."""
from .quantization import (  # noqa: F401
    PostTrainingQuantization,
    PostTrainingWeightQuantPass,
    QuantizationTransformPass,
    mark_weight_quant,
    quant_aware,
)
