"""Quantization passes over static Programs.

Role parity: reference python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py:216 (`QuantizationTransformPass` — insert fake
quant/dequant around the weights and activations of quantizable ops)
and post_training_quantization.py:120 (`PostTrainingQuantization` —
calibrate activation scales by running the model over sample data).

TPU-native notes: the reference pass edits an IrGraph and targets int8
CUDA/MKLDNN kernels; here the pass edits the proto Program directly and
the inserted ops (ops/quant_ops.py) simulate the int8 grid in float —
on TPU the win is QAT fidelity + exportable scales, not int arithmetic.
Gradients need no special handling: the qdq emission carries a
straight-through estimator, so `minimize()` AFTER `apply()` trains
through the quantized graph exactly like the reference's QAT flow.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..framework import unique_name
from ..framework.program import Parameter, Program
from ..initializer import ConstantInitializer

# op type -> input slots eligible for quantization (weights + activations)
_QUANT_SLOTS: Dict[str, Sequence[str]] = {
    "conv2d": ("Input", "Filter"),
    "depthwise_conv2d": ("Input", "Filter"),
    "conv2d_transpose": ("Input", "Filter"),
    "mul": ("X", "Y"),
    "matmul": ("X", "Y"),
    "matmul_v2": ("X", "Y"),
}

# weight quant_axis per (op type): conv filters are OIHW -> per-output-
# channel axis 0; mul/matmul weights are [in, out] -> axis 1 (reference
# quantization_pass.py channel-wise rules)
_WEIGHT_AXIS = {"conv2d": 0, "depthwise_conv2d": 0, "conv2d_transpose": 1,
                "mul": 1, "matmul": 1, "matmul_v2": 1}

SKIP_QUANT_ATTR = "skip_quant"


def _insert_weight_qdq(block, index, name, var, out_name, scale_name,
                       weight_quantize_type, weight_bits, axis):
    """Shared weight quant-dequant emitter (used by both the QAT
    transform pass and the PTQ export so the two cannot diverge)."""
    if weight_quantize_type == "channel_wise_abs_max":
        block.create_var(name=scale_name, shape=[int(var.shape[axis])],
                         dtype="float32", stop_gradient=True)
        block._insert_op(
            index, "fake_channel_wise_quantize_dequantize_abs_max",
            inputs={"X": [name]},
            outputs={"Out": [out_name], "OutScale": [scale_name]},
            attrs={"bit_length": weight_bits, "quant_axis": axis})
    else:
        block.create_var(name=scale_name, shape=[1], dtype="float32",
                         stop_gradient=True)
        block._insert_op(
            index, "fake_quantize_dequantize_abs_max",
            inputs={"X": [name]},
            outputs={"Out": [out_name], "OutScale": [scale_name]},
            attrs={"bit_length": weight_bits})


class QuantizationTransformPass:
    """Insert fake quant-dequant ops in front of quantizable ops.

    Weights get `abs_max` or `channel_wise_abs_max` qdq (recomputed from
    the live weight every step, like the reference's weight path);
    activations get `moving_average_abs_max` qdq with persistable
    scale/state/accum accumulators, or stateless `abs_max`.  Run
    ``apply(main, startup)`` BEFORE ``minimize`` so the backward pass
    differentiates through the quantized graph.
    """

    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="channel_wise_abs_max",
                 moving_rate=0.9,
                 quantizable_op_type=("conv2d", "depthwise_conv2d", "mul",
                                      "matmul", "matmul_v2")):
        if activation_quantize_type not in ("abs_max",
                                            "moving_average_abs_max"):
            raise ValueError(
                f"unknown activation_quantize_type "
                f"{activation_quantize_type!r}")
        if weight_quantize_type not in ("abs_max", "channel_wise_abs_max"):
            raise ValueError(
                f"unknown weight_quantize_type {weight_quantize_type!r}")
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.moving_rate = float(moving_rate)
        self.quantizable_op_type = set(quantizable_op_type)

    # -- helpers ---------------------------------------------------------

    def _make_state_var(self, startup, name, shape, fill):
        sb = startup.global_block
        sv = sb.create_var(name=name, shape=list(shape), dtype="float32",
                           persistable=True)
        ConstantInitializer(fill)(sv, sb)

    def _insert_qdq(self, program, startup, block, index, name, is_weight,
                    weight_axis):
        """Insert one qdq chain before ``index``; returns (new_name,
        n_inserted)."""
        var = block.var(name)
        out_name = unique_name.generate(f"{name}.quant_dequant")
        out = block.create_var(name=out_name, shape=list(var.shape),
                               dtype=var.dtype, stop_gradient=False)
        scale_name = unique_name.generate(f"{name}.quant_scale")
        if is_weight:
            _insert_weight_qdq(block, index, name, var, out_name,
                               scale_name, self.weight_quantize_type,
                               self.weight_bits, weight_axis)
            return out_name, 1

        if self.activation_quantize_type == "abs_max":
            block.create_var(name=scale_name, shape=[1], dtype="float32",
                             stop_gradient=True)
            block._insert_op(
                index, "fake_quantize_dequantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [out_name], "OutScale": [scale_name]},
                attrs={"bit_length": self.activation_bits})
            return out_name, 1

        # moving-average: persistable scale/state/accum round-tripped
        # through the op (reference quantization_pass.py:471)
        state_name = unique_name.generate(f"{name}.quant_state")
        accum_name = unique_name.generate(f"{name}.quant_accum")
        for nm, fill in ((scale_name, 1.0), (state_name, 1.0),
                         (accum_name, 1.0)):
            block.create_var(name=nm, shape=[1], dtype="float32",
                             persistable=True, stop_gradient=True)
            self._make_state_var(startup, nm, [1], fill)
        block._insert_op(
            index, "fake_quantize_dequantize_moving_average_abs_max",
            inputs={"X": [name], "InScale": [scale_name],
                    "InState": [state_name], "InAccum": [accum_name]},
            outputs={"Out": [out_name], "OutScale": [scale_name],
                     "OutState": [state_name], "OutAccum": [accum_name]},
            attrs={"bit_length": self.activation_bits,
                   "moving_rate": self.moving_rate, "is_test": False})
        return out_name, 1

    # -- entry points ----------------------------------------------------

    def apply(self, program: Program, startup_program: Program) -> Program:
        """In-place: rewrite ``program`` so every quantizable op consumes
        quant-dequantized inputs."""
        block = program.global_block
        # var name -> qdq output name, shared across consumers; local to
        # this apply() — carrying it across programs would rename vars
        # to qdq outputs that only exist in the earlier program
        dequantized: Dict[str, str] = {}
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if (op.type not in self.quantizable_op_type
                    or op.type not in _QUANT_SLOTS
                    or op.attr(SKIP_QUANT_ATTR, False)):
                i += 1
                continue
            for slot in _QUANT_SLOTS[op.type]:
                for name in list(op.input(slot)):
                    if name in dequantized:
                        op._rename_input(name, dequantized[name])
                        continue
                    var = block._find_var_recursive(name)
                    if var is None:
                        continue
                    is_weight = isinstance(var, Parameter) or (
                        getattr(var, "persistable", False))
                    new_name, n = self._insert_qdq(
                        program, startup_program, block, i, name, is_weight,
                        _WEIGHT_AXIS.get(op.type, 0))
                    i += n
                    dequantized[name] = new_name
                    op._rename_input(name, new_name)
            i += 1
        return program


def quant_aware(program: Program, startup_program: Program,
                config: Optional[dict] = None) -> Program:
    """One-call QAT entry (reference paddleslim.quant.quant_aware)."""
    cfg = dict(config or {})
    return QuantizationTransformPass(**cfg).apply(program, startup_program)


class PostTrainingQuantization:
    """Calibrate activation scales over sample data, then emit a
    quantized inference program with FIXED scales baked in.

    Reference post_training_quantization.py:120: runs the model over
    calibration batches, records the abs-max of every quantizable-op
    input, then inserts quant/dequant with the collected scales.  Here
    the calibration fetch rides the normal Executor (one compiled
    XLA call per batch, activations fetched async) and the emitted
    program uses moving-average qdq ops in is_test mode so the stored
    scale is authoritative.
    """

    def __init__(self, executor, program: Program, feed_list: List[str],
                 fetch_list: List, data_loader=None, scope=None,
                 batch_nums: Optional[int] = None,
                 weight_bits=8, activation_bits=8,
                 weight_quantize_type="channel_wise_abs_max",
                 quantizable_op_type=("conv2d", "depthwise_conv2d", "mul",
                                      "matmul", "matmul_v2")):
        self._exe = executor
        self._program = program
        self._feed_list = list(feed_list)
        self._fetch_list = list(fetch_list)
        self._loader = data_loader
        self._scope = scope
        self._batch_nums = batch_nums
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.weight_quantize_type = weight_quantize_type
        self.quantizable_op_type = set(quantizable_op_type)
        self._act_scales: Dict[str, float] = {}

    def _activation_names(self) -> List[str]:
        block = self._program.global_block
        names, seen = [], set()
        for op in block.ops:
            if op.type not in self.quantizable_op_type or \
                    op.type not in _QUANT_SLOTS:
                continue
            for slot in _QUANT_SLOTS[op.type]:
                for name in op.input(slot):
                    var = block._find_var_recursive(name)
                    if var is None or isinstance(var, Parameter) or \
                            getattr(var, "persistable", False):
                        continue
                    if name not in seen:
                        seen.add(name)
                        names.append(name)
        return names

    def quantize(self) -> Program:
        if self._loader is None:
            raise ValueError("PostTrainingQuantization needs a data_loader "
                             "of calibration batches")
        act_names = self._activation_names()
        maxes = {n: 0.0 for n in act_names}
        n_done = 0
        for batch in self._loader:
            if isinstance(batch, (list, tuple)):
                feed = dict(zip(self._feed_list, batch))
            else:
                feed = dict(batch)
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=act_names, scope=self._scope)
            for name, val in zip(act_names, outs):
                maxes[name] = max(maxes[name],
                                  float(np.max(np.abs(np.asarray(val)))))
            n_done += 1
            if self._batch_nums and n_done >= self._batch_nums:
                break
        if n_done == 0:
            raise ValueError("calibration data_loader yielded no batches")
        self._act_scales = {n: max(v, 1e-8) for n, v in maxes.items()}
        return self._emit_quantized_program()

    def _emit_quantized_program(self) -> Program:
        """Clone the program and insert qdq with the calibrated scales:
        weights use live abs-max qdq (bit-exact with QAT export);
        activations use moving-average qdq in is_test mode whose InScale
        is a constant initialized to the calibrated value."""
        prog = self._program.clone(for_test=True)
        block = prog.global_block
        dequantized: Dict[str, str] = {}
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if (op.type not in self.quantizable_op_type
                    or op.type not in _QUANT_SLOTS):
                i += 1
                continue
            for slot in _QUANT_SLOTS[op.type]:
                for name in list(op.input(slot)):
                    if name in dequantized:
                        op._rename_input(name, dequantized[name])
                        continue
                    var = block._find_var_recursive(name)
                    if var is None:
                        continue
                    is_weight = isinstance(var, Parameter) or \
                        getattr(var, "persistable", False)
                    if not is_weight and name not in self._act_scales:
                        continue
                    out_name = unique_name.generate(f"{name}.ptq_dequant")
                    block.create_var(name=out_name, shape=list(var.shape),
                                     dtype=var.dtype)
                    scale_name = unique_name.generate(f"{name}.ptq_scale")
                    if is_weight:
                        _insert_weight_qdq(
                            block, i, name, var, out_name, scale_name,
                            self.weight_quantize_type, self.weight_bits,
                            _WEIGHT_AXIS.get(op.type, 0))
                        i += 1
                    else:
                        # constant calibrated scale, materialized in-graph
                        block.create_var(name=scale_name, shape=[1],
                                         dtype="float32")
                        block._insert_op(
                            i, "fill_constant",
                            inputs={},
                            outputs={"Out": [scale_name]},
                            attrs={"shape": [1], "dtype": 1,  # DT_FP32
                                   "value": float(
                                       self._act_scales[name])})
                        block._insert_op(
                            i + 1,
                            "fake_quantize_dequantize_moving_average_abs"
                            "_max",
                            inputs={"X": [name], "InScale": [scale_name]},
                            outputs={"Out": [out_name]},
                            attrs={"bit_length": self.activation_bits,
                                   "is_test": True})
                        i += 2
                    dequantized[name] = out_name
                    op._rename_input(name, out_name)
            i += 1
        return prog
