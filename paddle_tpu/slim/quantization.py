"""Quantization passes over static Programs.

Role parity: reference python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py:216 (`QuantizationTransformPass` — insert fake
quant/dequant around the weights and activations of quantizable ops)
and post_training_quantization.py:120 (`PostTrainingQuantization` —
calibrate activation scales by running the model over sample data).

TPU-native notes: the reference pass edits an IrGraph and targets int8
CUDA/MKLDNN kernels; here the pass edits the proto Program directly and
the inserted ops (ops/quant_ops.py) simulate the int8 grid in float —
on TPU the win is QAT fidelity + exportable scales, not int arithmetic.
Gradients need no special handling: the qdq emission carries a
straight-through estimator, so `minimize()` AFTER `apply()` trains
through the quantized graph exactly like the reference's QAT flow.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework import unique_name
from ..framework.passes import Pass, register_pass
from ..framework.program import Operator, Parameter, Program
from ..initializer import ConstantInitializer

# op type -> input slots eligible for quantization (weights + activations)
_QUANT_SLOTS: Dict[str, Sequence[str]] = {
    "conv2d": ("Input", "Filter"),
    "depthwise_conv2d": ("Input", "Filter"),
    "conv2d_transpose": ("Input", "Filter"),
    "mul": ("X", "Y"),
    "matmul": ("X", "Y"),
    "matmul_v2": ("X", "Y"),
}

# weight quant_axis per (op type): conv filters are OIHW -> per-output-
# channel axis 0; mul/matmul weights are [in, out] -> axis 1 (reference
# quantization_pass.py channel-wise rules)
_WEIGHT_AXIS = {"conv2d": 0, "depthwise_conv2d": 0, "conv2d_transpose": 1,
                "mul": 1, "matmul": 1, "matmul_v2": 1}

SKIP_QUANT_ATTR = "skip_quant"


def _insert_weight_qdq(block, index, name, var, out_name, scale_name,
                       weight_quantize_type, weight_bits, axis):
    """Shared weight quant-dequant emitter (used by both the QAT
    transform pass and the PTQ export so the two cannot diverge)."""
    if weight_quantize_type == "channel_wise_abs_max":
        block.create_var(name=scale_name, shape=[int(var.shape[axis])],
                         dtype="float32", stop_gradient=True)
        block._insert_op(
            index, "fake_channel_wise_quantize_dequantize_abs_max",
            inputs={"X": [name]},
            outputs={"Out": [out_name], "OutScale": [scale_name]},
            attrs={"bit_length": weight_bits, "quant_axis": axis})
    else:
        block.create_var(name=scale_name, shape=[1], dtype="float32",
                         stop_gradient=True)
        block._insert_op(
            index, "fake_quantize_dequantize_abs_max",
            inputs={"X": [name]},
            outputs={"Out": [out_name], "OutScale": [scale_name]},
            attrs={"bit_length": weight_bits})


class QuantizationTransformPass:
    """Insert fake quant-dequant ops in front of quantizable ops.

    Weights get `abs_max` or `channel_wise_abs_max` qdq (recomputed from
    the live weight every step, like the reference's weight path);
    activations get `moving_average_abs_max` qdq with persistable
    scale/state/accum accumulators, or stateless `abs_max`.  Run
    ``apply(main, startup)`` BEFORE ``minimize`` so the backward pass
    differentiates through the quantized graph.
    """

    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="channel_wise_abs_max",
                 moving_rate=0.9,
                 quantizable_op_type=("conv2d", "depthwise_conv2d", "mul",
                                      "matmul", "matmul_v2")):
        if activation_quantize_type not in ("abs_max",
                                            "moving_average_abs_max"):
            raise ValueError(
                f"unknown activation_quantize_type "
                f"{activation_quantize_type!r}")
        if weight_quantize_type not in ("abs_max", "channel_wise_abs_max"):
            raise ValueError(
                f"unknown weight_quantize_type {weight_quantize_type!r}")
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.moving_rate = float(moving_rate)
        self.quantizable_op_type = set(quantizable_op_type)

    # -- helpers ---------------------------------------------------------

    def _make_state_var(self, startup, name, shape, fill):
        sb = startup.global_block
        sv = sb.create_var(name=name, shape=list(shape), dtype="float32",
                           persistable=True)
        ConstantInitializer(fill)(sv, sb)

    def _insert_qdq(self, program, startup, block, index, name, is_weight,
                    weight_axis):
        """Insert one qdq chain before ``index``; returns (new_name,
        n_inserted)."""
        var = block.var(name)
        out_name = unique_name.generate(f"{name}.quant_dequant")
        out = block.create_var(name=out_name, shape=list(var.shape),
                               dtype=var.dtype, stop_gradient=False)
        scale_name = unique_name.generate(f"{name}.quant_scale")
        if is_weight:
            _insert_weight_qdq(block, index, name, var, out_name,
                               scale_name, self.weight_quantize_type,
                               self.weight_bits, weight_axis)
            return out_name, 1

        if self.activation_quantize_type == "abs_max":
            block.create_var(name=scale_name, shape=[1], dtype="float32",
                             stop_gradient=True)
            block._insert_op(
                index, "fake_quantize_dequantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [out_name], "OutScale": [scale_name]},
                attrs={"bit_length": self.activation_bits})
            return out_name, 1

        # moving-average: persistable scale/state/accum round-tripped
        # through the op (reference quantization_pass.py:471)
        state_name = unique_name.generate(f"{name}.quant_state")
        accum_name = unique_name.generate(f"{name}.quant_accum")
        for nm, fill in ((scale_name, 1.0), (state_name, 1.0),
                         (accum_name, 1.0)):
            block.create_var(name=nm, shape=[1], dtype="float32",
                             persistable=True, stop_gradient=True)
            self._make_state_var(startup, nm, [1], fill)
        block._insert_op(
            index, "fake_quantize_dequantize_moving_average_abs_max",
            inputs={"X": [name], "InScale": [scale_name],
                    "InState": [state_name], "InAccum": [accum_name]},
            outputs={"Out": [out_name], "OutScale": [scale_name],
                     "OutState": [state_name], "OutAccum": [accum_name]},
            attrs={"bit_length": self.activation_bits,
                   "moving_rate": self.moving_rate, "is_test": False})
        return out_name, 1

    # -- entry points ----------------------------------------------------

    def apply(self, program: Program, startup_program: Program) -> Program:
        """In-place: rewrite ``program`` so every quantizable op consumes
        quant-dequantized inputs."""
        block = program.global_block
        # var name -> qdq output name, shared across consumers; local to
        # this apply() — carrying it across programs would rename vars
        # to qdq outputs that only exist in the earlier program
        dequantized: Dict[str, str] = {}
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if (op.type not in self.quantizable_op_type
                    or op.type not in _QUANT_SLOTS
                    or op.attr(SKIP_QUANT_ATTR, False)):
                i += 1
                continue
            for slot in _QUANT_SLOTS[op.type]:
                for name in list(op.input(slot)):
                    if name in dequantized:
                        op._rename_input(name, dequantized[name])
                        continue
                    var = block._find_var_recursive(name)
                    if var is None:
                        continue
                    is_weight = isinstance(var, Parameter) or (
                        getattr(var, "persistable", False))
                    new_name, n = self._insert_qdq(
                        program, startup_program, block, i, name, is_weight,
                        _WEIGHT_AXIS.get(op.type, 0))
                    i += n
                    dequantized[name] = new_name
                    op._rename_input(name, new_name)
            i += 1
        return program


def quant_aware(program: Program, startup_program: Program,
                config: Optional[dict] = None) -> Program:
    """One-call QAT entry (reference paddleslim.quant.quant_aware)."""
    cfg = dict(config or {})
    return QuantizationTransformPass(**cfg).apply(program, startup_program)


class PostTrainingQuantization:
    """Calibrate activation scales over sample data, then emit a
    quantized inference program with FIXED scales baked in.

    Reference post_training_quantization.py:120: runs the model over
    calibration batches, records the abs-max of every quantizable-op
    input, then inserts quant/dequant with the collected scales.  Here
    the calibration fetch rides the normal Executor (one compiled
    XLA call per batch, activations fetched async) and the emitted
    program uses moving-average qdq ops in is_test mode so the stored
    scale is authoritative.
    """

    def __init__(self, executor, program: Program, feed_list: List[str],
                 fetch_list: List, data_loader=None, scope=None,
                 batch_nums: Optional[int] = None,
                 weight_bits=8, activation_bits=8,
                 weight_quantize_type="channel_wise_abs_max",
                 quantizable_op_type=("conv2d", "depthwise_conv2d", "mul",
                                      "matmul", "matmul_v2")):
        self._exe = executor
        self._program = program
        self._feed_list = list(feed_list)
        self._fetch_list = list(fetch_list)
        self._loader = data_loader
        self._scope = scope
        self._batch_nums = batch_nums
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.weight_quantize_type = weight_quantize_type
        self.quantizable_op_type = set(quantizable_op_type)
        self._act_scales: Dict[str, float] = {}

    def _activation_names(self) -> List[str]:
        block = self._program.global_block
        names, seen = [], set()
        for op in block.ops:
            if op.type not in self.quantizable_op_type or \
                    op.type not in _QUANT_SLOTS:
                continue
            for slot in _QUANT_SLOTS[op.type]:
                for name in op.input(slot):
                    var = block._find_var_recursive(name)
                    if var is None or isinstance(var, Parameter) or \
                            getattr(var, "persistable", False):
                        continue
                    if name not in seen:
                        seen.add(name)
                        names.append(name)
        return names

    def quantize(self) -> Program:
        if self._loader is None:
            raise ValueError("PostTrainingQuantization needs a data_loader "
                             "of calibration batches")
        act_names = self._activation_names()
        maxes = {n: 0.0 for n in act_names}
        n_done = 0
        for batch in self._loader:
            if isinstance(batch, (list, tuple)):
                feed = dict(zip(self._feed_list, batch))
            else:
                feed = dict(batch)
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=act_names, scope=self._scope)
            for name, val in zip(act_names, outs):
                maxes[name] = max(maxes[name],
                                  float(np.max(np.abs(np.asarray(val)))))
            n_done += 1
            if self._batch_nums and n_done >= self._batch_nums:
                break
        if n_done == 0:
            raise ValueError("calibration data_loader yielded no batches")
        self._act_scales = {n: max(v, 1e-8) for n, v in maxes.items()}
        return self._emit_quantized_program()

    def _emit_quantized_program(self) -> Program:
        """Clone the program and insert qdq with the calibrated scales:
        weights use live abs-max qdq (bit-exact with QAT export);
        activations use moving-average qdq in is_test mode whose InScale
        is a constant initialized to the calibrated value."""
        prog = self._program.clone(for_test=True)
        block = prog.global_block
        dequantized: Dict[str, str] = {}
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if (op.type not in self.quantizable_op_type
                    or op.type not in _QUANT_SLOTS):
                i += 1
                continue
            for slot in _QUANT_SLOTS[op.type]:
                for name in list(op.input(slot)):
                    if name in dequantized:
                        op._rename_input(name, dequantized[name])
                        continue
                    var = block._find_var_recursive(name)
                    if var is None:
                        continue
                    is_weight = isinstance(var, Parameter) or \
                        getattr(var, "persistable", False)
                    if not is_weight and name not in self._act_scales:
                        continue
                    out_name = unique_name.generate(f"{name}.ptq_dequant")
                    block.create_var(name=out_name, shape=list(var.shape),
                                     dtype=var.dtype)
                    scale_name = unique_name.generate(f"{name}.ptq_scale")
                    if is_weight:
                        _insert_weight_qdq(
                            block, i, name, var, out_name, scale_name,
                            self.weight_quantize_type, self.weight_bits,
                            _WEIGHT_AXIS.get(op.type, 0))
                        i += 1
                    else:
                        # constant calibrated scale, materialized in-graph
                        block.create_var(name=scale_name, shape=[1],
                                         dtype="float32")
                        block._insert_op(
                            i, "fill_constant",
                            inputs={},
                            outputs={"Out": [scale_name]},
                            attrs={"shape": [1], "dtype": 1,  # DT_FP32
                                   "value": float(
                                       self._act_scales[name])})
                        block._insert_op(
                            i + 1,
                            "fake_quantize_dequantize_moving_average_abs"
                            "_max",
                            inputs={"X": [name], "InScale": [scale_name]},
                            outputs={"Out": [out_name]},
                            attrs={"bit_length": self.activation_bits,
                                   "is_test": True})
                        i += 2
                    dequantized[name] = out_name
                    op._rename_input(name, out_name)
            i += 1
        return prog


# ---------------------------------------------------------------------------
# post-training weight-only quantization (the inference byte-shrinker)
# ---------------------------------------------------------------------------

# per-op marker a program can carry instead of the global flag (stamped
# by mark_weight_quant; an op attr, so it survives clone/proto round
# trips AND joins the program fingerprint — stamping re-keys every
# executor cache automatically, mirroring the __tp_rules__ pattern)
WEIGHT_QUANT_ATTR = "__weight_quant__"

# matmul-family subset of _QUANT_SLOTS eligible for the int8 rewrite
# (the weight slot is "Y" for all three; conv stays on the qdq
# simulation path — its filter layout needs its own kernel story)
_WQ_OPS = ("mul", "matmul", "matmul_v2")

# MoE expert FFNs quantize IN PLACE: the stacked [E, in, out] weights
# become int8 carriers + per-expert [E, out] scales riding new
# W1Scale/W2Scale input slots that the moe_ffn lowering dequantizes at
# the einsum's doorstep (ops/moe_ops.py _dequant_stacked) — no op
# replacement, so the router/combine semantics are untouched and the
# expert-parallel plan spec P('ep', ...) transfers to the carrier
_WQ_MOE_OPS = ("moe_ffn",)
_WQ_MOE_SLOTS = ("W1", "W2")  # output-channel axis 2 for both

_CARRIER_SUFFIX = "@WQ"
_SCALE_SUFFIX = "@WQ_SCALE"


def mark_weight_quant(program: Program, mode: str = "int8") -> Program:
    """Arm PostTrainingWeightQuantPass for ``program`` regardless of
    ``FLAGS_weight_quant``: stamps the mode onto every matmul-family op
    (attr -> fingerprint -> executor caches re-key)."""
    from ..ops.quant_ops import WEIGHT_QUANT_MODES

    if mode not in WEIGHT_QUANT_MODES:
        raise ValueError(
            f"unknown weight-quant mode {mode!r}; expected one of "
            f"{WEIGHT_QUANT_MODES}")
    for op in program.global_block.ops:
        if op.type in _WQ_OPS or op.type in _WQ_MOE_OPS:
            op.attrs[WEIGHT_QUANT_ATTR] = mode
    program._bump()
    return program


@register_pass(before="layer_scan")
class PostTrainingWeightQuantPass(Pass):
    """Rewrite matmul-family weights to int8 / fp8-e4m3 carriers with
    per-output-channel scales, lowered through the dequant-fused
    ``dequant_matmul`` op (ops/quant_ops.py).

    Registered in the framework pass pipeline (framework/passes.py)
    AFTER ShardingPropagationPass — so scale vars can inherit the
    weight's mp spec on the sharded axis — and BEFORE LayerScanPass, so
    repeated layers stay isomorphic after the rewrite and their int8
    carriers + scales get stacked like any other per-layer weight.
    Gated by ``FLAGS_weight_quant`` ('' off, 'int8', 'fp8_e4m3') or
    per-program by :func:`mark_weight_quant`.

    Mechanics per quantizable op (weight slot ``Y`` holding a 2D
    persistable var, resolved through at most one AMP ``cast``):

    - the live scope value is quantized ONCE (``quantize_weight``:
      symmetric per-output-channel, the same grid the QAT export's
      ``fake_channel_wise_quantize_abs_max`` writes, scales clamped
      per channel) into two new persistable vars ``<w>@WQ`` (carrier)
      and ``<w>@WQ_SCALE`` (float32 ``[out_channels]``);
    - the op is replaced by ``dequant_matmul`` carrying the original
      semantics (``orig_type`` + the flattening/transpose attrs);
    - a weight consumed through an AMP cast is rewritten to consume
      the dequant output directly (the dequant lands at X's dtype, so
      numerics match the cast path) — the orphaned cast is then
      RedundantCast/DCE food, which is how the pass composes with the
      AMP cast-elimination;
    - when the program carries a ``TPShardingPlan`` the carrier
      inherits the weight's spec and the scale inherits the sharded
      axis' entry, so GSPMD keeps scale shards beside weight shards.

    The ORIGINAL f32 weight var stays in the block and scope
    (checkpoints and further training still see it); the rewritten
    program simply never reads it, so it drops out of the executable's
    argument footprint — which is where the PR 8 ``hbm_required_bytes``
    accounting sees the bytes halve.
    """

    name = "post_training_weight_quant"

    def __init__(self, mode: Optional[str] = None):
        self._mode_override = mode

    def _mode(self, program) -> Optional[str]:
        if self._mode_override:
            return self._mode_override
        for op in program.global_block.ops:
            m = op.attr(WEIGHT_QUANT_ATTR)
            if m:
                return str(m)
        from ..framework import flags

        return str(flags.flag("weight_quant")) or None

    def should_apply(self, program, ctx) -> bool:
        if ctx.scope is None or self._mode(program) is None:
            return False
        return any(op.type in _WQ_OPS or op.type in _WQ_MOE_OPS
                   for op in program.global_block.ops)

    @staticmethod
    def _resolve_weight(block, ops, idx, name):
        """Resolve op input ``name`` to a persistable 2D weight var:
        either directly, or through ONE dtype cast of one (the AMP
        pattern).  Returns (weight_name, var) or (None, None)."""

        def _weight_var(n):
            v = block._find_var_recursive(n)
            if v is not None and (isinstance(v, Parameter)
                                  or getattr(v, "persistable", False)) \
                    and len(getattr(v, "shape", ())) == 2:
                return v
            return None

        v = _weight_var(name)
        if v is not None:
            return name, v
        for j in range(idx - 1, -1, -1):
            op = ops[j]
            if name in op.output_arg_names():
                if op.type != "cast":
                    return None, None
                xs = op.inputs.get("X", [])
                if len(xs) != 1:
                    return None, None
                v = _weight_var(xs[0])
                return (xs[0], v) if v is not None else (None, None)
        return None, None

    def _quantize_moe(self, op, block, scope, plan, mode,
                      quantized) -> Tuple[int, int]:
        """Quantize one moe_ffn op's stacked expert weights in place:
        W1/W2 -> int8 carrier + per-expert [E, out] scale riding the
        W1Scale/W2Scale input slots the lowering already consumes.
        Returns (n_rewritten_slots, n_skipped_slots)."""
        from ..ops.quant_ops import quantize_weight_stacked

        n_done = n_skip = 0
        for slot in _WQ_MOE_SLOTS:
            names = op.input(slot)
            if len(names) != 1:
                n_skip += 1
                continue
            wname = names[0]
            wvar = block._find_var_recursive(wname)
            if wvar is None or len(getattr(wvar, "shape", ())) != 3 \
                    or not (isinstance(wvar, Parameter)
                            or getattr(wvar, "persistable", False)) \
                    or not scope.has_var(wname):
                n_skip += 1
                continue
            axis = 2  # [E, in, out] for W1 and W2 alike
            cached = quantized.get(wname)
            if cached is None:
                carrier = wname + _CARRIER_SUFFIX
                scale = wname + _SCALE_SUFFIX
                q, s = quantize_weight_stacked(
                    scope.get_var(wname), axis, mode)
                scope.set_var(carrier, q)
                scope.set_var(scale, s)
                block.create_var(
                    name=carrier, shape=list(wvar.shape),
                    dtype="int8", persistable=True, stop_gradient=True)
                block.create_var(
                    name=scale,
                    shape=[int(wvar.shape[0]), int(wvar.shape[axis])],
                    dtype="float32", persistable=True,
                    stop_gradient=True)
                if plan is not None and wname in plan.specs:
                    wspec = tuple(plan.specs[wname])
                    plan.specs[carrier] = wspec
                    # expert axis 0 shards; output channels replicate
                    plan.specs[scale] = (wspec[0], None)
                quantized[wname] = cached = (carrier, scale)
            carrier, scale = cached
            op.inputs[slot] = [carrier]
            op.inputs[slot + "Scale"] = [scale]
            n_done += 1
        if n_done:
            op.attrs["mode"] = mode
        return n_done, n_skip

    def apply(self, program, ctx) -> bool:
        from ..framework import dtypes
        from ..monitor import stat_add
        from ..ops.quant_ops import quantize_weight, resolve_quant_mode

        mode = resolve_quant_mode(self._mode(program))
        block = program.global_block
        scope = ctx.scope
        plan = getattr(program, "_tp_plan", None)
        quantized: Dict[str, Tuple[str, str]] = {}
        n_rewritten = n_skipped = 0
        for i, op in enumerate(list(block.ops)):
            if op.type in _WQ_MOE_OPS:
                nd, ns = self._quantize_moe(op, block, scope, plan, mode,
                                            quantized)
                n_rewritten += nd
                n_skipped += ns
                continue
            if op.type not in _WQ_OPS:
                continue
            ys = op.input("Y")
            if len(ys) != 1:
                n_skipped += 1
                continue
            if op.type != "mul" and bool(
                    op.attr("transpose_Y", op.attr("trans_y", False))):
                n_skipped += 1  # transposed weights flip the channel
                continue        # axis; stay on the unquantized path
            if op.type == "mul" and int(op.attr("y_num_col_dims", 1)) != 1:
                n_skipped += 1
                continue
            wname, wvar = self._resolve_weight(block, block.ops, i, ys[0])
            if wname is None or not scope.has_var(wname):
                n_skipped += 1
                continue
            axis = _WEIGHT_AXIS[op.type]
            cached = quantized.get(wname)
            if cached is None:
                carrier = wname + _CARRIER_SUFFIX
                scale = wname + _SCALE_SUFFIX
                q, s = quantize_weight(scope.get_var(wname), axis, mode)
                scope.set_var(carrier, q)
                scope.set_var(scale, s)
                # the proto dtype enum has no float8 entry, so the
                # carrier is declared int8 in BOTH modes (8-bit
                # payload either way); the scope array — what the
                # executor actually feeds — carries the authoritative
                # dtype, and the op's "mode" attr records the truth
                block.create_var(
                    name=carrier, shape=list(wvar.shape),
                    dtype="int8", persistable=True, stop_gradient=True)
                block.create_var(
                    name=scale, shape=[int(wvar.shape[axis])],
                    dtype="float32", persistable=True,
                    stop_gradient=True)
                if plan is not None and wname in plan.specs:
                    wspec = tuple(plan.specs[wname])
                    plan.specs[carrier] = wspec
                    if axis < len(wspec) and wspec[axis] is not None:
                        plan.specs[scale] = (wspec[axis],)
                quantized[wname] = cached = (carrier, scale)
            carrier, scale = cached
            attrs = {
                "orig_type": op.type,
                "weight_axis": axis,
                "mode": mode,
                "bit_length": 8,
            }
            for k in ("x_num_col_dims", "y_num_col_dims", "transpose_X",
                      "transpose_Y", "trans_x", "trans_y", "alpha",
                      WEIGHT_QUANT_ATTR):
                if op.has_attr(k):
                    attrs[k] = op.attr(k)
            new_op = Operator(
                block, "dequant_matmul",
                inputs={"X": op.input("X"), "Y": [carrier],
                        "Scale": [scale]},
                outputs={k: list(v) for k, v in op.outputs.items()},
                attrs=attrs)
            block.ops[i] = new_op
            n_rewritten += 1
        if not n_rewritten:
            return False
        program._bump()
        stat_add("pass_weight_quant_ops", n_rewritten)
        if n_skipped:
            stat_add("pass_weight_quant_skipped", n_skipped)
        return True
