"""``paddle.jit`` namespace (reference python/paddle/jit/__init__.py,
re-exporting the dygraph jit machinery: fluid/dygraph/jit.py +
dygraph_to_static's to_static entry point — here trace-based, see
dygraph/jit.py)."""
from ..dygraph.jit import (  # noqa: F401
    StaticFunction,
    TracedLayer,
    TranslatedLayer,
    declarative,
    load,
    save,
    to_static,
)

__all__ = ["save", "load", "to_static", "declarative", "TracedLayer",
           "TranslatedLayer", "StaticFunction"]
