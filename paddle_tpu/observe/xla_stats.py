"""XLA introspection: compile telemetry, HBM accounting, budget gate.

PR 7 opened model sizes a single chip's HBM cannot hold, and the
framework was blind on both axes that matter there: how long XLA took
to compile the program (ROADMAP item 5's linear blow-up at depth) and
how many bytes of HBM the program will need per chip — discovered, if
at all, via an opaque RESOURCE_EXHAUSTED after dispatch.  This module
is the reference's ``memory_optimize``/profiler role (SURVEY L1/L11)
rebuilt on what jax actually exposes:

- **Compile telemetry** — the Executor AOT-lowers every fresh entry
  (``jit_fn.lower(...).compile()``) and hands the compiled executable
  to :func:`on_compile`: wall time into the ``compile_seconds``
  histogram, executable size + HLO module stats as ``/metrics`` gauges,
  an ``executor/compile_done`` flight event with the duration, and an
  optional optimized-HLO dump (``FLAGS_hlo_dump_dir``).
- **HBM accounting** — ``compiled.memory_analysis()`` (guarded through
  ``framework/jax_compat.py``; per-chip under SPMD, since the analyzed
  module is the partitioned per-device program) becomes a footprint
  breakdown (arguments / outputs / temporaries / generated code), and
  the :class:`~..framework.passes.TPShardingPlan` + scope var sizes
  join into a top-N per-var attribution table — the thing that says
  *what to shard next*.  ``hbm_required_bytes`` rides ``/metrics``;
  live ``device.memory_stats()`` (``hbm_free_bytes``) rides the
  heartbeat thread (observe/health.py) onto ``/metrics/cluster``.
- **Pre-dispatch budget gate** — when the predicted footprint exceeds
  ``FLAGS_hbm_budget_fraction`` × device memory, the compile raises
  :class:`MemoryBudgetError` *before* the first dispatch, with the
  attribution table in the message; the same data lands in the
  ``memory.json`` section of postmortem bundles.

Everything here is capability-skipped, never fatal: a jax without
``memory_analysis`` records what it can and moves on — only the budget
gate (explicitly armed via the flag) may raise.
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework import flags as _flags
from ..framework import jax_compat as _jc
from . import flight as _flight
from .histogram import stat_time

__all__ = ["COMPILE_SECONDS_HISTOGRAM", "MemoryBudgetError",
           "memory_breakdown", "cost_flops", "var_attribution",
           "format_attribution", "device_memory_stats",
           "device_hbm_capacity", "record_device_memory",
           "check_hbm_budget", "on_compile", "compile_records",
           "last_compile", "memory_report", "clear_compile_records"]

COMPILE_SECONDS_HISTOGRAM = "compile_seconds"

# how many vars the attribution table keeps (the error message shows 3)
TOP_N_VARS = 10

# bounded ring of compile records: memory.json in postmortem bundles
# reads it, /metrics gauges reflect the newest entry
_RECORDS: "collections.deque[dict]" = collections.deque(maxlen=32)
_LOCK = threading.Lock()
_HLO_SEQ = 0

# set once the jax backend is definitionally in use (the Executor's
# first compile; same reasoning as flight.record_device_topology):
# before that, jax.local_devices() ITSELF performs backend init — on a
# dead TPU that is the 240s hang the health plane exists to survive,
# so the heartbeat's device-memory sampling must not be the first call
_BACKEND_IN_USE = False


def mark_backend_in_use() -> None:
    """The Executor calls this at its first compile — the one point
    where probing jax devices cannot introduce a device-init that was
    not already being paid."""
    global _BACKEND_IN_USE

    _BACKEND_IN_USE = True


class MemoryBudgetError(RuntimeError):
    """Predicted per-chip HBM footprint exceeds the configured budget
    (``FLAGS_hbm_budget_fraction`` × device memory).  Raised BEFORE the
    executable is dispatched, with the per-var attribution table
    attached (``.attribution``) and its top rows in the message."""

    def __init__(self, message: str, required_bytes: int = 0,
                 budget_bytes: int = 0, capacity_bytes: int = 0,
                 attribution: Optional[Sequence[dict]] = None):
        super().__init__(message)
        self.required_bytes = int(required_bytes)
        self.budget_bytes = int(budget_bytes)
        self.capacity_bytes = int(capacity_bytes)
        self.attribution = list(attribution or [])


def _mb(nbytes) -> float:
    return round(int(nbytes or 0) / 2 ** 20, 2)


# ---------------------------------------------------------------------------
# compiled-executable readings (all capability-guarded via jax_compat)
# ---------------------------------------------------------------------------


def memory_breakdown(compiled) -> Optional[Dict[str, int]]:
    """Per-chip footprint breakdown from ``compiled.memory_analysis()``
    or None when this jax cannot say.  ``total_bytes`` is the predicted
    live-at-once HBM need: arguments + outputs + temporaries +
    generated code, minus the aliased (donated-in-place) bytes that
    would otherwise count twice."""
    m = _jc.compiled_memory_stats(compiled)
    if m is None:
        return None

    def _get(attr):
        try:
            return max(int(getattr(m, attr, 0) or 0), 0)
        except (TypeError, ValueError):
            return 0

    args = _get("argument_size_in_bytes")
    outs = _get("output_size_in_bytes")
    temps = _get("temp_size_in_bytes")
    code = _get("generated_code_size_in_bytes")
    alias = _get("alias_size_in_bytes")
    return {
        "arguments_bytes": args,
        "outputs_bytes": outs,
        "temporaries_bytes": temps,
        "generated_code_bytes": code,
        "aliased_bytes": alias,
        "total_bytes": max(args + outs + temps + code - alias, 0),
    }


def cost_flops(compiled) -> Optional[float]:
    """FLOPs of one executable call per ``compiled.cost_analysis()``
    (per-chip under SPMD), or None when unavailable."""
    c = _jc.compiled_cost_analysis(compiled)
    if not c:
        return None
    f = c.get("flops")
    try:
        f = float(f)
    except (TypeError, ValueError):
        return None
    return f if f > 0.0 else None


# ---------------------------------------------------------------------------
# per-var attribution: TPShardingPlan x scope var sizes
# ---------------------------------------------------------------------------


def var_attribution(entries: Sequence[Tuple], plan=None, mesh=None,
                    top_n: int = TOP_N_VARS) -> List[dict]:
    """Join var sizes with the sharding plan into the top-N per-chip
    attribution table.

    ``entries`` are ``(name, shape, dtype_str, kind)`` tuples (kind:
    ``"state"`` for scope vars, ``"feed"`` for inputs).  With a
    :class:`~..framework.passes.TPShardingPlan`, per-chip bytes divide
    by :meth:`~..framework.passes.TPShardingPlan.shard_divisor` and the
    spec string names the layout; without one everything is replicated
    (feeds are counted unsharded either way — a conservative bound, and
    params dominate the footprints this table exists to explain)."""
    rows: List[dict] = []
    for name, shape, dtype, kind in entries:
        try:
            itemsize = np.dtype(dtype).itemsize
        except TypeError:
            continue
        n = 1
        for s in shape:
            n *= max(int(s), 1)
        nbytes = n * itemsize
        if plan is not None:
            div = plan.shard_divisor(name, mesh)
            spec = plan.spec_str(name)
        else:
            div, spec = 1, "replicated"
        rows.append({
            "name": str(name),
            "kind": str(kind),
            "dtype": str(dtype),
            "shape": [int(s) for s in shape],
            "global_bytes": int(nbytes),
            "per_chip_bytes": int(nbytes // div),
            "spec": spec,
        })
    rows.sort(key=lambda r: (-r["per_chip_bytes"], r["name"]))
    return rows[:max(int(top_n), 1)]


def format_attribution(rows: Sequence[dict], limit: Optional[int] = None
                       ) -> str:
    """Render attribution rows as an aligned text table (error messages
    and logs; the postmortem CLI has its own pure-stdlib renderer)."""
    rows = list(rows)[:limit] if limit else list(rows)
    if not rows:
        return "  (no per-var attribution available)"
    width = max(len(r["name"]) for r in rows)
    out = [f"  {'var':<{width}}  {'per-chip MB':>12}  {'global MB':>10}  "
           f"{'kind':<5}  spec"]
    for r in rows:
        out.append(
            f"  {r['name']:<{width}}  {_mb(r['per_chip_bytes']):>12}  "
            f"{_mb(r['global_bytes']):>10}  {r['kind']:<5}  {r['spec']}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# live device memory (heartbeat thread -> /metrics + /metrics/cluster)
# ---------------------------------------------------------------------------


def device_memory_stats(device=None) -> Optional[dict]:
    """Live ``device.memory_stats()`` as a plain dict, or None where
    the backend has none (CPU)."""
    return _jc.device_memory_stats(device)


def device_hbm_capacity(device=None) -> Optional[int]:
    """Per-device memory capacity in bytes for the budget gate:
    ``FLAGS_hbm_bytes_per_device`` when set, else the device's reported
    ``bytes_limit``, else None (gate capability-skips)."""
    override = int(_flags.flag("hbm_bytes_per_device"))
    if override > 0:
        return override
    ms = device_memory_stats(device)
    if ms:
        try:
            limit = int(ms.get("bytes_limit", 0))
        except (TypeError, ValueError):
            limit = 0
        if limit > 0:
            return limit
    return None


def record_device_memory(devices=None) -> dict:
    """One live HBM sample across the local devices, mirrored to
    ``/metrics`` gauges (``hbm_free_bytes`` = the MIN free — the chip
    that OOMs first — plus ``hbm_used_bytes``/``hbm_limit_bytes``) and
    returned as heartbeat payload fields for ``/metrics/cluster``.
    Returns {} where no device reports memory stats (CPU backend):
    the capability skip, not an error.  With no explicit ``devices``,
    nothing is probed until :func:`mark_backend_in_use` — the heartbeat
    thread calls this, and ``jax.local_devices()`` on a backend nobody
    initialized yet IS the device-init hang the health plane must
    survive (the PR 6 topology-probe rule)."""
    if devices is None:
        if not _BACKEND_IN_USE:
            return {}
        try:
            import jax

            devices = jax.local_devices()
        except Exception:  # noqa: BLE001 - a dead backend is not a crash
            return {}
    free = used = limit = None
    for d in devices:
        ms = device_memory_stats(d)
        if not ms:
            continue
        try:
            d_limit = int(ms.get("bytes_limit", 0))
            d_used = int(ms.get("bytes_in_use", 0))
        except (TypeError, ValueError):
            continue
        if d_limit <= 0:
            continue
        d_free = max(d_limit - d_used, 0)
        free = d_free if free is None else min(free, d_free)
        used = d_used if used is None else max(used, d_used)
        limit = d_limit if limit is None else max(limit, d_limit)
    if free is None:
        return {}
    from ..monitor import stat_set

    stat_set("hbm_free_bytes", free)
    stat_set("hbm_used_bytes", used)
    stat_set("hbm_limit_bytes", limit)
    return {"hbm_free_bytes": free, "hbm_used_bytes": used,
            "hbm_limit_bytes": limit}


# ---------------------------------------------------------------------------
# the pre-dispatch budget gate
# ---------------------------------------------------------------------------


def check_hbm_budget(required_bytes: int,
                     attribution: Sequence[dict] = (),
                     device=None, fingerprint: str = "") -> dict:
    """Judge a predicted per-chip footprint against the configured
    budget.  Returns a verdict record (``disabled`` / ``skipped`` /
    ``pass``); raises :class:`MemoryBudgetError` on rejection — the
    caller (Executor first-dispatch introspection) has NOT launched the
    executable yet, so the failure is a report, not a dead device."""
    from ..monitor import stat_add

    fraction = float(_flags.flag("hbm_budget_fraction"))
    if fraction <= 0.0:
        return {"verdict": "disabled"}
    capacity = device_hbm_capacity(device)
    if capacity is None:
        # no way to know this device's memory: skip LOUDLY (counter +
        # flight event) rather than pretend the program fits
        stat_add("hbm_budget_gate_skipped")
        _flight.record("xla/hbm_budget_skipped",
                       reason="device memory capacity unknown "
                              "(no memory_stats and no "
                              "FLAGS_hbm_bytes_per_device)")
        return {"verdict": "skipped", "fraction": fraction}
    budget = int(fraction * capacity)
    rec = {"fraction": fraction, "capacity_bytes": int(capacity),
           "budget_bytes": budget, "required_bytes": int(required_bytes)}
    if int(required_bytes) <= budget:
        stat_add("hbm_budget_gate_passed")
        rec["verdict"] = "pass"
        return rec
    stat_add("hbm_budget_gate_rejections")
    top = list(attribution)[:3]
    _flight.record("xla/hbm_budget_reject", fingerprint=fingerprint[:16],
                   required_bytes=int(required_bytes),
                   budget_bytes=budget, capacity_bytes=int(capacity),
                   top_vars=[r.get("name") for r in top])
    raise MemoryBudgetError(
        f"predicted per-chip HBM footprint {_mb(required_bytes)} MB "
        f"exceeds the budget {_mb(budget)} MB "
        f"(FLAGS_hbm_budget_fraction={fraction} x {_mb(capacity)} MB "
        f"device memory); rejected BEFORE dispatch.  Largest per-chip "
        f"allocations:\n"
        + format_attribution(attribution, limit=TOP_N_VARS)
        + "\nShard the top vars (DistributedStrategy.tensor_parallel "
          "partition_rules), shrink the batch, or raise "
          "FLAGS_hbm_budget_fraction.  Full breakdown: memory.json in "
          "the postmortem bundle / observe.xla_stats.memory_report().",
        required_bytes=int(required_bytes), budget_bytes=budget,
        capacity_bytes=int(capacity), attribution=attribution)


# ---------------------------------------------------------------------------
# the per-compile entry point (Executor._introspect_first_compile)
# ---------------------------------------------------------------------------


def _dump_hlo(hlo_text: Optional[str], fingerprint: str) -> Optional[str]:
    """FLAGS_hlo_dump_dir: save the optimized HLO module text beside
    the postmortem bundles; returns the path or None.  Best-effort — a
    full disk must not fail a compile."""
    global _HLO_SEQ

    d = _flags.flag("hlo_dump_dir")
    if not d or not hlo_text:
        return None
    try:
        os.makedirs(d, exist_ok=True)
        with _LOCK:
            _HLO_SEQ += 1
            seq = _HLO_SEQ
        path = os.path.join(
            d, f"hlo_{fingerprint[:16] or 'unknown'}_{seq:03d}.txt")
        with open(path, "w") as f:
            f.write(hlo_text)
        return path
    except OSError:
        return None


def on_compile(compiled, *, fingerprint: str = "", seconds: float = 0.0,
               size_entries: Sequence[Tuple] = (), plan=None, mesh=None,
               n_steps: int = 1, program_flops: float = 0.0,
               device=None) -> dict:
    """Record one Executor compile: telemetry, HBM accounting, and the
    budget gate (which may raise :class:`MemoryBudgetError` — the ONLY
    exception this function lets escape, and only when the gate is
    armed).  Returns the compile record (also kept in the bounded ring
    behind :func:`compile_records`/``memory.json``); the caller reads
    ``xla_flops_per_step`` off it for the MFU cross-check."""
    from ..monitor import stat_add, stat_set

    stat_time(COMPILE_SECONDS_HISTOGRAM, max(float(seconds), 0.0))

    rec: dict = {
        "ts": time.time(),
        "fingerprint": str(fingerprint)[:16],
        "compile_seconds": round(float(seconds), 6),
        "n_steps": int(n_steps),
    }
    if mesh is not None:
        try:
            rec["mesh"] = {str(a): int(mesh.shape[a])
                           for a in mesh.axis_names}
        except Exception:  # noqa: BLE001 - telemetry only
            pass

    # -- executable size + HLO module stats --------------------------------
    breakdown = memory_breakdown(compiled)
    exec_size = 0
    if breakdown:
        exec_size = breakdown["generated_code_bytes"]
    if exec_size <= 0:
        exec_size = _jc.executable_code_bytes(compiled)
    # the optimized-HLO text is rendered ONLY when something needs it —
    # a dump dir, or a backend that reports no code size (the text
    # length is then the honest proxy for "how big did this program
    # get", the ROADMAP item 5 blow-up signal).  For a large model the
    # text is tens of MB of string; unconditional as_text() on the
    # first-dispatch path would tax exactly the workloads this PR
    # exists to observe.
    hlo_text = None
    if exec_size <= 0 or _flags.flag("hlo_dump_dir"):
        hlo_text = _jc.compiled_text(compiled)
    if exec_size <= 0 and hlo_text:
        exec_size = len(hlo_text)
        rec["executable_size_is_hlo_text"] = True
    rec["executable_size_bytes"] = int(exec_size)
    stat_set("executable_size_bytes", int(exec_size))
    if hlo_text:
        rec["hlo_text_bytes"] = len(hlo_text)
        rec["hlo_ops"] = hlo_text.count(" = ")
        stat_set("executable_hlo_bytes", len(hlo_text))
        stat_set("executable_hlo_ops", rec["hlo_ops"])
        hlo_path = _dump_hlo(hlo_text, str(fingerprint))
        if hlo_path:
            rec["hlo_dump_path"] = hlo_path

    # -- HBM accounting ----------------------------------------------------
    attribution = var_attribution(size_entries, plan=plan, mesh=mesh)
    rec["attribution"] = attribution
    required = 0
    if breakdown is None:
        stat_add("xla_memory_analysis_unavailable")
    else:
        rec["memory"] = breakdown
        required = breakdown["total_bytes"]
        stat_set("hbm_required_bytes", required)

    # -- MFU honesty cross-check -------------------------------------------
    # hapi/model_stat.py program_flops vs XLA's own count.  Only where
    # the two count the SAME thing: single-step (a run_steps scan's
    # cost analysis may or may not fold the trip count depending on the
    # XLA version) and single-device (on a mesh the analyzed module is
    # the per-chip partition while the IR estimate is global/mp — they
    # disagree by design, not by mispricing).
    if int(n_steps) == 1 and mesh is None:
        xla = cost_flops(compiled)
        if xla is not None:
            rec["xla_flops"] = xla
            if program_flops and program_flops > 0.0:
                ratio = xla / float(program_flops)
                rec["flops_ratio_xla_over_ir"] = round(ratio, 4)
                if ratio > 2.0 or ratio < 0.5:
                    # the hand-rolled IR count misprices fused ops (and
                    # on sharded meshes counts global, not per-chip,
                    # work): XLA's number wins the MFU denominator
                    stat_add("mfu_flops_mismatch")
                    rec["flops_source"] = "xla"
                    rec["xla_flops_per_step"] = xla
            else:
                # no IR estimate at all: XLA is the only source
                rec["flops_source"] = "xla"
                rec["xla_flops_per_step"] = xla

    _flight.record("executor/compile_done",
                   fingerprint=rec["fingerprint"],
                   seconds=rec["compile_seconds"],
                   executable_size_bytes=rec["executable_size_bytes"],
                   hbm_required_bytes=required,
                   n_steps=int(n_steps))

    # the budget verdict is computed BEFORE the record is published:
    # once appended, rec is shared with concurrent memory_report()
    # readers (the stall watchdog's dump thread), and a post-append
    # key insert would race their serialization — while a REJECTED
    # compile must still land in the ring with its full numbers
    # (memory.json in the failure's postmortem shows the why)
    budget_exc = None
    if breakdown is not None:
        try:
            rec["budget"] = check_hbm_budget(
                required, attribution, device=device,
                fingerprint=str(fingerprint))
        except MemoryBudgetError as e:
            # the rejection's numbers matter MOST in memory.json: keep
            # the full verdict off the exception, not a stub
            rec["budget"] = {
                "verdict": "rejected",
                "fraction": float(_flags.flag("hbm_budget_fraction")),
                "required_bytes": e.required_bytes,
                "budget_bytes": e.budget_bytes,
                "capacity_bytes": e.capacity_bytes,
            }
            budget_exc = e
    with _LOCK:
        _RECORDS.append(rec)
    if budget_exc is not None:
        raise budget_exc
    return rec


# ---------------------------------------------------------------------------
# reading back (postmortem memory.json, tests, dashboards)
# ---------------------------------------------------------------------------


def compile_records() -> List[dict]:
    with _LOCK:
        return list(_RECORDS)


def last_compile() -> Optional[dict]:
    with _LOCK:
        return _RECORDS[-1] if _RECORDS else None


def clear_compile_records() -> None:
    with _LOCK:
        _RECORDS.clear()


def memory_report(probe_devices: bool = False) -> dict:
    """The ``memory.json`` postmortem section: every recorded compile
    (footprint breakdown + attribution + budget verdicts) plus the
    heartbeat's CACHED hbm gauges.  Pure data — ``tools/postmortem.py``
    renders it without importing the framework.

    Live device probing is opt-in (``probe_devices=True``): the dump
    path fires exactly when a device call is hung, and a
    ``memory_stats()`` against the same wedged PJRT runtime would hang
    the watchdog thread mid-bundle — the per-section error capture
    handles exceptions, not hangs.  The cached gauges (last heartbeat
    sample) are the safe default."""
    from ..monitor import stat_get

    report: dict = {"ts": time.time(), "compiles": compile_records()}
    gauges = {k: stat_get(k) for k in
              ("hbm_free_bytes", "hbm_used_bytes", "hbm_limit_bytes")}
    if any(gauges.values()):
        report["hbm_gauges"] = gauges
    devices = []
    if probe_devices and _BACKEND_IN_USE:
        try:
            import jax

            for d in jax.local_devices():
                ms = device_memory_stats(d)
                if ms:
                    devices.append({"device": str(d), **ms})
        except Exception:  # noqa: BLE001 - a dead backend still reports
            pass
    report["device_memory"] = devices
    return report
