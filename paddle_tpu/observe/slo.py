"""SLO objectives, multi-window burn rates, and goodput for serving.

Role: the Google SRE-workbook control plane over the per-request
timelines in ``observe/request_trace.py``.  Raw ``decode_tokens_per_sec``
can rise while users suffer — tokens streamed after a blown deadline
are waste.  This module makes "did users feel it" first-class:

- **Objectives** are declarative per-request predicates with an error
  budget: ``ttft p99 <= X ms`` (budget 1%: up to 1% of requests may
  exceed X), ``tpot p50 <= Y ms`` (budget 50%, against the request's
  MEAN time-per-output-token), ``error-rate <= Z`` (budget Z: a
  request is bad when its outcome is not ``completed``).  Defaults
  come from ``FLAGS_slo_*``; :func:`configure` replaces them at
  runtime (bench/tests/deployment).
- **Burn rate** (the SRE-workbook multi-window formulation): for each
  objective and each rolling window (``FLAGS_slo_windows_s``, default
  60s and 300s), ``burn = bad_fraction / budget_fraction`` — 1.0 means
  exactly consuming budget, 14.4 on a 1h window is the classic
  page-now threshold.  The emitted gauge is the MAX across windows
  (short window catches fast burn, long window catches slow bleed):
  ``slo_burn_rate_<name>_ppm`` (parts-per-million fixed point) plus a
  rounded integer ``slo_burn_rate_<name>``, and
  ``slo_budget_remaining_<name>_ppm`` (fraction of the long window's
  budget still unspent; 0 when exhausted).
- **Goodput**: ``decode_goodput_rps`` (+ ``_ppm`` float precision) =
  completions meeting ALL objectives per second over the short window
  — the number capacity work should optimize once raw tokens/sec stops
  being what users feel.  ``decode_slo_violations`` counts objective
  violations (one per objective per request).

Gauges refresh on every terminal request observation and on
:func:`snapshot` (so a ``/metrics`` scrape after a quiet period still
reads internally consistent values from the last refresh).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..framework import flags as _flags
from ..monitor import stat_add, stat_set

__all__ = ["Objective", "SLOEngine", "get_slo_engine", "configure",
           "observe_request", "snapshot", "refresh_gauges",
           "default_objectives"]


class Objective:
    """One declarative objective: ``metric`` in ``{"ttft", "tpot",
    "latency", "error"}``, ``threshold_s`` (None for ``error``), and
    the error-budget fraction (p99 latency objective -> 0.01)."""

    __slots__ = ("name", "metric", "threshold_s", "budget")

    def __init__(self, name: str, metric: str,
                 threshold_s: Optional[float], budget: float):
        if metric not in ("ttft", "tpot", "latency", "error"):
            raise ValueError(f"unknown SLO metric {metric!r}")
        if not 0.0 < float(budget) <= 1.0:
            raise ValueError("budget must be a fraction in (0, 1]")
        if metric != "error" and threshold_s is None:
            raise ValueError(
                f"a {metric!r} objective needs a threshold_s (only "
                f"'error' objectives are threshold-free)")
        self.name = str(name)
        self.metric = metric
        self.threshold_s = None if threshold_s is None \
            else float(threshold_s)
        self.budget = float(budget)

    def is_violated(self, summary: dict) -> bool:
        """Judge one terminal request summary (keys: ``outcome``,
        ``ttft_s``, ``tpot_s``, ``latency_s``).  A ttft/latency
        objective treats a request that never produced the measured
        signal (died before first token) as violated — a blown
        deadline must not read as 'fast'.  A missing ``tpot_s`` is NOT
        a violation: a normal 1-token completion has no
        time-per-output-token at all."""
        if self.metric == "error":
            return summary.get("outcome") != "completed"
        v = summary.get(f"{self.metric}_s")
        if v is None:
            return self.metric != "tpot"
        return float(v) > self.threshold_s

    def to_dict(self) -> dict:
        return {"name": self.name, "metric": self.metric,
                "threshold_ms": None if self.threshold_s is None
                else round(self.threshold_s * 1e3, 3),
                "budget": self.budget}


def default_objectives() -> List[Objective]:
    """Objectives from the ``FLAGS_slo_*`` registry (0 disables a
    latency objective; the error-rate objective is always on so
    goodput/burn gauges exist out of the box)."""
    out: List[Objective] = []
    try:
        ttft_ms = float(_flags.flag("slo_ttft_p99_ms"))
        tpot_ms = float(_flags.flag("slo_tpot_p50_ms"))
        err_ppm = int(_flags.flag("slo_error_rate_ppm"))
    except KeyError:  # pragma: no cover - partial installs
        ttft_ms, tpot_ms, err_ppm = 0.0, 0.0, 10000
    if ttft_ms > 0:
        out.append(Objective("ttft_p99", "ttft", ttft_ms / 1e3, 0.01))
    if tpot_ms > 0:
        out.append(Objective("tpot_p50", "tpot", tpot_ms / 1e3, 0.50))
    if err_ppm > 0:
        out.append(Objective("error_rate", "error", None, err_ppm / 1e6))
    return out


def _windows() -> tuple:
    try:
        raw = str(_flags.flag("slo_windows_s"))
    except KeyError:  # pragma: no cover - partial installs
        raw = "60,300"
    ws = []
    for part in raw.split(","):
        part = part.strip()
        if part:
            ws.append(max(float(part), 1e-3))
    return tuple(sorted(ws)) or (60.0, 300.0)


class SLOEngine:
    """Rolling multi-window evaluator.  ``observe(summary)`` is called
    once per terminal request (any replica — the gauges are fleet-wide
    per process, like every StatRegistry series) and returns the list
    of violated objective names, which the trace store uses for tail
    retention."""

    def __init__(self, objectives: Optional[Sequence[Objective]] = None,
                 windows: Optional[Sequence[float]] = None,
                 gauge_prefix: str = "decode"):
        self._objectives = list(objectives) if objectives is not None \
            else default_objectives()
        self._windows = tuple(sorted(windows)) if windows else _windows()
        self._prefix = str(gauge_prefix)
        # (t, tuple(violated names), good_completion)
        self._events: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._violations_total = 0
        self._t_gauges = 0.0  # last gauge refresh (throttle)

    @property
    def objectives(self) -> List[Objective]:
        return list(self._objectives)

    @property
    def windows(self) -> tuple:
        return self._windows

    # -- observation ------------------------------------------------------
    def observe(self, summary: dict) -> List[str]:
        violated = [o.name for o in self._objectives
                    if o.is_violated(summary)]
        good = (not violated) and summary.get("outcome") == "completed"
        now = time.monotonic()
        with self._lock:
            self._events.append((now, tuple(violated), good))
            self._violations_total += len(violated)
            self._update_gauges_locked(now)
        if violated:
            stat_add(f"{self._prefix}_slo_violations", len(violated))
        return violated

    # -- evaluation (ONE implementation behind gauges AND snapshot) -------
    def _evaluate_locked(self, now: float) -> Dict:
        """Prune beyond the long window and compute per-objective
        burn-per-window + long-window budget remaining + short-window
        goodput.  Called with the lock held."""
        long_w = self._windows[-1]
        while self._events and self._events[0][0] < now - long_w:
            self._events.popleft()
        evs = self._events
        # one pass per window over the time-ordered deque (windows are
        # sorted ascending, so iterate from the right and cut early)
        per_window: Dict[float, Dict] = {}
        for w in self._windows:
            cutoff = now - w
            n = 0
            bad: Dict[str, int] = {}
            good = 0
            for t, violated, is_good in reversed(evs):
                if t < cutoff:
                    break
                n += 1
                good += is_good
                for name in violated:
                    bad[name] = bad.get(name, 0) + 1
            per_window[w] = {"n": n, "bad": bad, "good": good}
        out: Dict = {"burn": {}, "remaining": {}}
        for o in self._objectives:
            burn = 0.0
            remaining = 1.0
            rates = {}
            for w in self._windows:
                pw = per_window[w]
                frac = (pw["bad"].get(o.name, 0) / pw["n"]) \
                    if pw["n"] else 0.0
                rate = frac / o.budget
                rates[f"{int(w)}s"] = rate
                burn = max(burn, rate)
                if w == long_w:
                    remaining = max(1.0 - rate, 0.0)
            out["burn"][o.name] = {"max": burn, "windows": rates}
            out["remaining"][o.name] = remaining
        # goodput over the SHORT window, against time actually elapsed
        # (a 3-second-old process must not divide 3s of completions by
        # a 60s window)
        short_w = self._windows[0]
        span = min(short_w, max(now - self._t0, 1e-3))
        out["goodput_rps"] = per_window[short_w]["good"] / span
        out["observed"] = len(evs)
        return out

    def _update_gauges_locked(self, now: float,
                              force: bool = False) -> Optional[Dict]:
        # throttled: observe() runs on the engine thread per terminal
        # request — at high request rates the window scan must not run
        # per completion (snapshot() always forces a fresh view).
        # Returns the evaluation dict when it ran, so snapshot() does
        # not pay the window scan twice.
        if not force and now - self._t_gauges < 0.5:
            return None
        self._t_gauges = now
        ev = self._evaluate_locked(now)
        for o in self._objectives:
            burn = ev["burn"][o.name]["max"]
            stat_set(f"slo_burn_rate_{o.name}", int(round(burn)))
            stat_set(f"slo_burn_rate_{o.name}_ppm", int(burn * 1e6))
            stat_set(f"slo_budget_remaining_{o.name}_ppm",
                     int(ev["remaining"][o.name] * 1e6))
        rps = ev["goodput_rps"]
        stat_set(f"{self._prefix}_goodput_rps", int(round(rps)))
        stat_set(f"{self._prefix}_goodput_rps_ppm", int(rps * 1e6))
        return ev

    def snapshot(self) -> Dict:
        """Objectives + current burn/budget/goodput numbers (refreshes
        the gauges); the ``/debug/slo`` route and postmortem
        ``requests.json`` serve this."""
        now = time.monotonic()
        with self._lock:
            ev = self._update_gauges_locked(now, force=True)
            violations_total = self._violations_total
        return {
            "objectives": [o.to_dict() for o in self._objectives],
            "windows_s": list(self._windows),
            "observed": ev["observed"],
            "violations_total": violations_total,
            "burn_rates": {
                name: {w: round(r, 6) for w, r in b["windows"].items()}
                for name, b in ev["burn"].items()},
            "budget_remaining": {
                name: round(r, 6) for name, r in ev["remaining"].items()},
            "goodput_rps": round(ev["goodput_rps"], 6),
        }

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._violations_total = 0
            self._t0 = time.monotonic()


_ENGINE = SLOEngine()
_ENGINE_LOCK = threading.Lock()


def get_slo_engine() -> SLOEngine:
    return _ENGINE


def configure(objectives: Optional[Sequence[Objective]] = None,
              windows: Optional[Sequence[float]] = None) -> SLOEngine:
    """Replace the process SLO engine (``None`` objectives: rebuild
    from the ``FLAGS_slo_*`` defaults).  Returns the new engine."""
    global _ENGINE
    with _ENGINE_LOCK:
        _ENGINE = SLOEngine(objectives, windows)
    return _ENGINE


def observe_request(summary: dict) -> List[str]:
    """Feed one terminal request summary; returns violated objective
    names (the trace store's tail-retention signal)."""
    return _ENGINE.observe(summary)


def snapshot() -> Dict:
    return _ENGINE.snapshot()


def refresh_gauges() -> None:
    """Force-refresh the burn/budget/goodput gauges against the
    current window contents.  The fleet KV HTTP server calls this per
    ``/metrics`` scrape: without it a burst of violations followed by
    silence would freeze the gauges at their peak forever (they
    otherwise refresh only on terminal-request observations)."""
    now = time.monotonic()
    eng = _ENGINE
    with eng._lock:
        eng._update_gauges_locked(now, force=True)
