"""``paddle_tpu.observe`` — always-on in-process telemetry.

The reference ships a full observability stack (CUPTI ``DeviceTracer``
→ ``profiler.proto`` → ``tools/timeline.py``, plus ``StatRegistry``
counters); the TPU-native port previously covered only the thin ends.
This package is the middle:

- ``tracer``     — host-side span ring buffer (``FLAGS_enable_tracer``),
  fed by the Executor phases, graph passes, collective lowerings, the
  serving batch lifecycle, and every ``profiler.RecordEvent``.
- ``timeline``   — Chrome trace-event JSON export of that buffer
  (Perfetto/chrome://tracing), plus a
  ``python -m paddle_tpu.observe.timeline`` CLI.
- ``histogram``  — log-bucketed ``stat_time`` latency histograms with
  p50/p95/p99, and the Prometheus text exposition behind the fleet KV
  HTTP server's ``/metrics`` route.
- ``step_stats`` — ``StepTimer``: step-time distribution, examples/sec,
  compile-vs-execute split, allreduce bytes/step, and the MFU estimate
  (FLOPs from ``hapi/model_stat.py`` over the program IR).
- ``flight``     — always-on bounded ring of structured lifecycle
  events (run metadata, executor dispatch/drain, ckpt save/restore,
  serving start/stop), gated by ``FLAGS_flight_recorder``, with an
  optional JSONL file sink (``FLAGS_flight_recorder_file``).
- ``health``     — stall watchdog (``FLAGS_stall_timeout_s``) dumping
  postmortem bundles (all-thread stacks, Chrome trace, metrics
  snapshot, flight tail, flags), crash/atexit hooks, and cluster-wide
  health telemetry (per-rank heartbeats over the fleet KV server +
  the aggregated ``/metrics/cluster`` route on rank 0).
- ``request_trace`` — Dapper-style per-request serving timelines:
  trace ids minted at submit, structured lifecycle events (enqueue,
  admission, prefill chunks, decode steps, CoW copies, speculative
  rounds, terminal outcome), head-sampling
  (``FLAGS_request_trace_sample``) with tail retention of every SLO
  violator and abnormal ending; served on ``/debug/requests`` +
  ``/debug/request/<id>``, exported to Chrome trace JSON, embedded in
  postmortem bundles as ``requests.json``.
- ``slo``        — declarative objectives (ttft p99 / tpot p50 /
  error rate) evaluated on rolling multi-windows (SRE-workbook burn
  rates): ``slo_burn_rate_*`` / ``slo_budget_remaining_*`` gauges and
  the ``decode_goodput_rps`` metric (completions meeting ALL
  objectives per second).
- ``phases``     — step-phase attribution: decomposes each drained
  step's wall time into compute / exposed-collective / host-blocked /
  input-wait buckets, backed by an HLO cost model (deterministic
  *predicted* fractions on backends without device tracing) and a
  per-collective ledger keyed by FuseAllReducePass bucket /
  collective-matmul chunk identity (``comm_exposed_seconds`` vs
  ``comm_hidden_seconds`` per collective).
- ``profiler_capture`` — anomaly-triggered + continuous
  ``jax.profiler`` capture: step-time spikes past
  ``FLAGS_prof_trigger_ratio`` x rolling baseline (or an SLO burn-rate
  trip) fire one bounded trace window + phase snapshot into a
  postmortem bundle; ``FLAGS_prof_continuous_s`` runs a low-duty-cycle
  always-on mode with 2-deep directory rotation.
- ``metrics_catalog`` — the authoritative name → (type, unit,
  subsystem) catalog behind ``METRICS.md``; a tier-1 drift gate keeps
  every ``/metrics`` series documented.
- ``xla_stats``  — XLA introspection: per-compile wall time
  (``compile_seconds``), executable size, per-chip HBM footprint from
  ``compiled.memory_analysis()`` joined with the tensor-parallel
  sharding plan into a per-var attribution table, live
  ``device.memory_stats()`` on the heartbeat, and the pre-dispatch
  memory budget gate (``FLAGS_hbm_budget_fraction`` →
  :class:`~.xla_stats.MemoryBudgetError` before dispatch).
"""
from . import (flight, health, metrics_catalog, phases, profiler_capture,
               request_trace, slo, xla_stats)
from .flight import FlightRecorder, get_flight_recorder
from .phases import (PhaseEngine, PhasePlan, build_phase_plan,
                     collective_inventory, phase_engine, phases_report,
                     reset_phases)
from .profiler_capture import (CaptureEngine, capture_engine,
                               parse_trace_dir, reset_capture)
from .request_trace import (RequestTrace, TraceStore,
                            export_request_chrome_trace, get_trace_store)
from .slo import Objective, SLOEngine, get_slo_engine
from .health import (HealthReporter, StallWatchdog, cluster_health,
                     dump_postmortem, executor_progress,
                     install_crash_handler, serve_cluster_health,
                     start_watchdog, stop_watchdog)
from .histogram import (Histogram, HistogramRegistry, export_histograms,
                        histogram, prometheus_text, stat_time)
from .step_stats import (StepTimer, mfu_estimate, reset_step_stats,
                         step_timer)
from .xla_stats import (MemoryBudgetError, check_hbm_budget,
                        device_memory_stats, memory_breakdown,
                        memory_report, var_attribution)
from .tracer import (SpanRecord, Tracer, begin, clear, disable, enable,
                     enabled, end, get_tracer, set_span_args, snapshot,
                     span)
from .timeline import chrome_trace, export_chrome_trace

__all__ = [
    # tracer
    "SpanRecord", "Tracer", "get_tracer", "enabled", "enable", "disable",
    "span", "begin", "end", "set_span_args", "snapshot", "clear",
    # timeline
    "chrome_trace", "export_chrome_trace",
    # histograms
    "Histogram", "HistogramRegistry", "histogram", "stat_time",
    "export_histograms", "prometheus_text",
    # step telemetry
    "StepTimer", "step_timer", "reset_step_stats", "mfu_estimate",
    # flight recorder
    "flight", "FlightRecorder", "get_flight_recorder",
    # health plane
    "health", "StallWatchdog", "HealthReporter", "executor_progress",
    "dump_postmortem", "start_watchdog", "stop_watchdog",
    "install_crash_handler", "cluster_health", "serve_cluster_health",
    # XLA introspection
    "xla_stats", "MemoryBudgetError", "memory_breakdown",
    "var_attribution", "check_hbm_budget", "device_memory_stats",
    "memory_report",
    # phase attribution + profiler capture + metrics catalog
    "phases", "PhasePlan", "PhaseEngine", "build_phase_plan",
    "collective_inventory", "phase_engine", "phases_report",
    "reset_phases", "profiler_capture", "CaptureEngine",
    "capture_engine", "parse_trace_dir", "reset_capture",
    "metrics_catalog",
    # per-request tracing + SLO plane
    "request_trace", "RequestTrace", "TraceStore", "get_trace_store",
    "export_request_chrome_trace", "slo", "Objective", "SLOEngine",
    "get_slo_engine",
]
