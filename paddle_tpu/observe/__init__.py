"""``paddle_tpu.observe`` — always-on in-process telemetry.

The reference ships a full observability stack (CUPTI ``DeviceTracer``
→ ``profiler.proto`` → ``tools/timeline.py``, plus ``StatRegistry``
counters); the TPU-native port previously covered only the thin ends.
This package is the middle:

- ``tracer``     — host-side span ring buffer (``FLAGS_enable_tracer``),
  fed by the Executor phases, graph passes, collective lowerings, the
  serving batch lifecycle, and every ``profiler.RecordEvent``.
- ``timeline``   — Chrome trace-event JSON export of that buffer
  (Perfetto/chrome://tracing), plus a
  ``python -m paddle_tpu.observe.timeline`` CLI.
- ``histogram``  — log-bucketed ``stat_time`` latency histograms with
  p50/p95/p99, and the Prometheus text exposition behind the fleet KV
  HTTP server's ``/metrics`` route.
- ``step_stats`` — ``StepTimer``: step-time distribution, examples/sec,
  compile-vs-execute split, allreduce bytes/step, and the MFU estimate
  (FLOPs from ``hapi/model_stat.py`` over the program IR).
"""
from .histogram import (Histogram, HistogramRegistry, export_histograms,
                        histogram, prometheus_text, stat_time)
from .step_stats import (StepTimer, mfu_estimate, reset_step_stats,
                         step_timer)
from .tracer import (SpanRecord, Tracer, begin, clear, disable, enable,
                     enabled, end, get_tracer, set_span_args, snapshot,
                     span)
from .timeline import chrome_trace, export_chrome_trace

__all__ = [
    # tracer
    "SpanRecord", "Tracer", "get_tracer", "enabled", "enable", "disable",
    "span", "begin", "end", "set_span_args", "snapshot", "clear",
    # timeline
    "chrome_trace", "export_chrome_trace",
    # histograms
    "Histogram", "HistogramRegistry", "histogram", "stat_time",
    "export_histograms", "prometheus_text",
    # step telemetry
    "StepTimer", "step_timer", "reset_step_stats", "mfu_estimate",
]
