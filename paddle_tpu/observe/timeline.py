"""Chrome trace-event export of the span tracer's ring buffer.

Role parity: reference ``tools/timeline.py`` — it parses the CUPTI
``profiler.proto`` dump and emits chrome://tracing JSON.  Here there is
no proto hop: ``chrome_trace()`` renders the live in-process buffer
(``observe/tracer.py``) directly into the Trace Event Format
(``ph: "X"`` complete events, microsecond timestamps), one lane per
thread, loadable in Perfetto (https://ui.perfetto.dev) or
chrome://tracing.

CLI (no code changes needed to trace any script)::

    python -m paddle_tpu.observe.timeline out.json train.py --epochs 1

runs ``train.py`` under ``FLAGS_enable_tracer=1`` and writes the trace
on exit (including exceptional exit — the partial trace is exactly what
you want when debugging a hang/crash).  With no script argument it
dumps the current process's buffer (useful from a REPL or atexit hook).
"""
from __future__ import annotations

import json
from typing import List, Optional

from . import tracer as _tracer

__all__ = ["chrome_trace", "export_chrome_trace", "main"]


def chrome_trace(records: Optional[List] = None) -> dict:
    """Trace Event Format dict for ``records`` (default: the live
    buffer).  Spans become ``X`` (complete) events; thread lanes get
    ``M`` (metadata) names so Perfetto labels them."""
    t = _tracer.get_tracer()
    if records is None:
        records = t.snapshot()
    pid = t.pid
    events = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "paddle_tpu"},
    }]
    seen_tids = {}
    for r in records:
        if r.tid not in seen_tids:
            seen_tids[r.tid] = r.thread_name
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": r.tid, "args": {"name": r.thread_name},
            })
        ev = {
            "name": r.name,
            "cat": r.name.split("/", 1)[0],
            "ph": "X",
            "pid": pid,
            "tid": r.tid,
            "ts": round(r.t_begin * 1e6, 3),
            "dur": round((r.t_end - r.t_begin) * 1e6, 3),
        }
        args = dict(r.args or {})
        if r.parent is not None:
            args.setdefault("parent", r.parent)
        if args:
            ev["args"] = args
        events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "paddle_tpu.observe",
            "spans": len(records),
            "dropped_spans": t.dropped,
        },
    }


def export_chrome_trace(path: Optional[str] = None,
                        records: Optional[List] = None):
    """Write the trace JSON to ``path`` (or return the dict when
    ``path`` is None)."""
    doc = chrome_trace(records)
    if path is None:
        return doc
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def main(argv: Optional[List[str]] = None) -> int:
    import runpy
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m paddle_tpu.observe.timeline OUT.json "
              "[script.py [args...]]\n"
              "  With a script: run it under FLAGS_enable_tracer=1 and "
              "write the Chrome trace to OUT.json on exit.\n"
              "  Without: dump this process's current span buffer.",
              file=sys.stderr)
        return 0 if argv else 2
    out, rest = argv[0], argv[1:]
    if not rest:
        export_chrome_trace(out)
        print(f"wrote {out} "
              f"({len(_tracer.snapshot())} spans)", file=sys.stderr)
        return 0
    from ..framework import flags as _flags

    _flags.set_flags({"enable_tracer": True})
    script, script_args = rest[0], rest[1:]
    old_argv = sys.argv
    sys.argv = [script] + script_args
    try:
        runpy.run_path(script, run_name="__main__")
    finally:
        sys.argv = old_argv
        export_chrome_trace(out)
        print(f"wrote {out} ({len(_tracer.snapshot())} spans)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
