"""Per-request distributed tracing for the serving stack (Dapper role).

The aggregate telemetry the serving stack already ships (``ttft_seconds``
histograms, ``decode_*`` counters) can say p99 regressed; it cannot say
WHICH request blew its deadline or WHY — queued behind a six-chunk
long-prompt adversary?  a copy-on-write storm?  every speculative round
rejected?  This module is the per-request half: every
``DecodeRequest``/batcher request gets a **trace id** minted at submit
and a structured timeline of lifecycle events with attributes —
enqueue, admission (pages claimed, prefix pages hit, CoW spare held),
each prefill chunk, each decode step that advanced it, CoW copies,
speculative propose/verify rounds with accept counts, token emissions,
and the terminal outcome (completed(eos/budget) / deadline / abandoned /
rejected / cancelled / error, with reason).

Retention (the Dapper/production compromise):

- **Recording is always on and cheap** (one monotonic read + a tuple
  append per event, no device work, no numerics impact): the in-flight
  timeline must exist for EVERY request, because whether a request is
  interesting is only known at its end.
- **Head sampling** (``FLAGS_request_trace_sample`` in [0, 1], exact
  deterministic rate) decides which *normal* completions are kept in
  the bounded finished-trace ring.
- **Tail retention**: a request that violates an SLO objective
  (``observe/slo.py``) or ends abnormally (deadline / abandoned /
  rejected / error / cancelled) is ALWAYS kept, even at sample = 0 —
  the traces you need at 3am are exactly the ones head sampling would
  have dropped.

Surfaces: ``/debug/requests`` (live in-flight table) and
``/debug/request/<id>`` (full timeline JSON) on any fleet KV HTTP
server a ``Server``/``DecodeServer`` runs; :func:`chrome_trace` renders
one request's timeline through ``observe/timeline.py`` for
Perfetto/chrome://tracing; postmortem bundles embed the retained
violators as ``requests.json`` (``observe/health.py``), pretty-printed
by ``python -m tools.reqtrace``.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..framework import flags as _flags
from ..monitor import stat_add, stat_set

__all__ = ["RequestTrace", "TraceStore", "get_trace_store",
           "chrome_trace", "export_request_chrome_trace",
           "ABNORMAL_OUTCOMES", "MAX_EVENTS_PER_TRACE"]

# per-trace event cap: a max_new_tokens=64 request emits ~70 events;
# the cap only bites pathological requests, and the drop is counted
MAX_EVENTS_PER_TRACE = 1024

# outcomes that bypass head sampling (tail retention)
ABNORMAL_OUTCOMES = frozenset(
    ("deadline", "abandoned", "rejected", "cancelled", "error"))


class RequestTrace:
    """One request's timeline: bounded event list + terminal verdict.

    Events are ``(t_rel_seconds, name, attrs)`` relative to the mint
    time; ``event()`` is the hot path and must stay allocation-light
    (the engine calls it once per emitted token)."""

    __slots__ = ("trace_id", "kind", "replica", "sampled", "attrs",
                 "events", "t_start", "t_unix", "outcome", "reason",
                 "violations", "summary", "dropped_events", "_done")

    def __init__(self, trace_id: str, kind: str, replica: str,
                 sampled: bool, attrs: Optional[dict] = None):
        self.trace_id = trace_id
        self.kind = kind
        self.replica = replica
        self.sampled = sampled
        self.attrs = dict(attrs or {})
        self.events: List[tuple] = []
        self.t_start = time.monotonic()
        self.t_unix = time.time()
        self.outcome: Optional[str] = None
        self.reason: Optional[str] = None
        self.violations: tuple = ()
        self.summary: dict = {}
        self.dropped_events = 0
        self._done = False

    # -- recording (engine/client hot path) ------------------------------
    def event(self, name: str, **attrs) -> None:
        # post-terminal events are accepted on purpose: a client-side
        # deadline reap finishes the trace while the engine's in-flight
        # step still lands (those trailing tokens ARE the diagnosis),
        # and page registration happens at slot release
        if len(self.events) >= MAX_EVENTS_PER_TRACE:
            self.dropped_events += 1
            return
        self.events.append((time.monotonic() - self.t_start, name,
                            attrs or None))

    def finish(self, outcome: str, reason: Optional[str],
               violations: Sequence[str], summary: dict) -> bool:
        """First finish wins (the engine reap and a client-side
        deadline self-reap can race through ``RequestBase._complete``)."""
        if self._done:
            return False
        self._done = True
        self.outcome = str(outcome)
        self.reason = reason if reason is None else str(reason)
        self.violations = tuple(violations)
        self.summary = dict(summary)
        self.events.append((time.monotonic() - self.t_start, "finish",
                            {"outcome": self.outcome,
                             **({"reason": self.reason}
                                if self.reason else {}),
                             **({"violations": list(self.violations)}
                                if self.violations else {})}))
        return True

    @property
    def done(self) -> bool:
        return self._done

    @property
    def duration_s(self) -> float:
        if self.events:
            return self.events[-1][0]
        return time.monotonic() - self.t_start

    # -- reading ---------------------------------------------------------
    def to_dict(self, events: bool = True) -> dict:
        d = {
            "trace_id": self.trace_id,
            "kind": self.kind,
            "replica": self.replica,
            "sampled": self.sampled,
            "t_unix": round(self.t_unix, 6),
            "attrs": dict(self.attrs),
            "outcome": self.outcome,
            "reason": self.reason,
            "violations": list(self.violations),
            "summary": dict(self.summary),
            "duration_ms": round(self.duration_s * 1e3, 3),
            "n_events": len(self.events),
            "dropped_events": self.dropped_events,
        }
        if events:
            d["events"] = [
                {"t_ms": round(t * 1e3, 3), "name": name,
                 **(attrs or {})}
                for t, name, attrs in list(self.events)]
        return d


class TraceStore:
    """In-flight map + bounded finished-trace ring with head-sampling
    and tail retention.  The module singleton is what the serving stack
    feeds; tests may build their own with a small capacity."""

    def __init__(self, capacity: Optional[int] = None):
        # an explicit capacity is authoritative; only a flag-derived
        # one tracks FLAGS_request_trace_ring live (resized at
        # retention time — the singleton is built at import, before an
        # operator can set the flag)
        self._cap_from_flag = capacity is None
        if capacity is None:
            try:
                capacity = int(_flags.flag("request_trace_ring"))
            except KeyError:  # pragma: no cover - partial installs
                capacity = 512
        self._ring: collections.deque = collections.deque(
            maxlen=max(int(capacity), 1))
        self._inflight: Dict[str, RequestTrace] = {}
        self._lock = threading.Lock()
        self._seq = 0
        self._sample_acc = 0.0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- lifecycle --------------------------------------------------------
    def start(self, kind: str, replica: str = "", **attrs) -> RequestTrace:
        """Mint a trace id and begin an in-flight timeline.  Sampling is
        deterministic-exact-rate (an accumulator, not a coin flip), so a
        10% sample of 100 requests keeps exactly 10 normal ones."""
        try:
            sample = float(_flags.flag("request_trace_sample"))
        except KeyError:  # pragma: no cover - partial installs
            sample = 1.0
        sample = min(max(sample, 0.0), 1.0)
        with self._lock:
            self._seq += 1
            self._sample_acc += sample
            sampled = self._sample_acc >= 1.0 - 1e-12
            if sampled:
                self._sample_acc -= 1.0
            tr = RequestTrace(f"{kind}-{self._seq:06d}", kind, replica,
                              sampled, attrs)
            self._inflight[tr.trace_id] = tr
        stat_add("request_traces_started")
        stat_set("request_traces_inflight", len(self._inflight))
        return tr

    def finish(self, trace: RequestTrace, outcome: str,
               reason: Optional[str] = None,
               violations: Sequence[str] = (), **summary) -> bool:
        """Terminal: first caller wins; the trace is retained in the
        ring when head-sampled in, OR on any SLO violation, OR on an
        abnormal outcome (tail retention)."""
        if not trace.finish(outcome, reason, violations, summary):
            return False
        keep = (trace.sampled or bool(violations)
                or outcome in ABNORMAL_OUTCOMES)
        cap = self._ring.maxlen
        if self._cap_from_flag:
            try:
                cap = max(int(_flags.flag("request_trace_ring")), 1)
            except KeyError:  # pragma: no cover - partial installs
                pass
        with self._lock:
            self._inflight.pop(trace.trace_id, None)
            if cap != self._ring.maxlen:
                # the flag is live: resize at retention time (deque
                # maxlen is immutable, so rebuild — rare)
                self._ring = collections.deque(self._ring, maxlen=cap)
            if keep:
                self._ring.append(trace)
            n_inflight = len(self._inflight)
        stat_add("request_traces_retained" if keep
                 else "request_traces_sampled_out")
        stat_set("request_traces_inflight", n_inflight)
        return True

    def drop(self, trace: RequestTrace) -> None:
        """Forget an in-flight trace without retaining it (tests)."""
        with self._lock:
            self._inflight.pop(trace.trace_id, None)

    # -- reading ----------------------------------------------------------
    def get(self, trace_id: str) -> Optional[RequestTrace]:
        with self._lock:
            tr = self._inflight.get(trace_id)
            if tr is not None:
                return tr
            for tr in reversed(self._ring):
                if tr.trace_id == trace_id:
                    return tr
        return None

    def inflight(self) -> List[RequestTrace]:
        with self._lock:
            return list(self._inflight.values())

    def retained(self, n: Optional[int] = None) -> List[RequestTrace]:
        with self._lock:
            out = list(self._ring)
        return out if n is None else out[-int(n):]

    def violators(self, n: Optional[int] = None) -> List[RequestTrace]:
        """Retained traces that violated an SLO or died abnormally."""
        out = [t for t in self.retained()
               if t.violations or t.outcome in ABNORMAL_OUTCOMES]
        return out if n is None else out[-int(n):]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._inflight.clear()
            self._sample_acc = 0.0


_STORE = TraceStore()


def get_trace_store() -> TraceStore:
    return _STORE


# ---------------------------------------------------------------------------
# Chrome trace-event export (one request's timeline in Perfetto)
# ---------------------------------------------------------------------------


def chrome_trace(trace_or_id) -> dict:
    """Render ONE request's timeline as Chrome trace-event JSON through
    the ``observe/timeline.py`` machinery: each lifecycle event becomes
    a complete-span lasting until the next event, so the lane reads as
    'where did this request's milliseconds go' (queued, prefill chunks,
    token cadence) in Perfetto/chrome://tracing."""
    tr = trace_or_id
    if not isinstance(tr, RequestTrace):
        tr = _STORE.get(str(trace_or_id))
        if tr is None:
            raise KeyError(f"no trace {trace_or_id!r} in flight or "
                           f"retained")
    from .timeline import chrome_trace as _chrome
    from .tracer import SpanRecord

    evs = list(tr.events)
    lane = f"{tr.replica or tr.kind}:{tr.trace_id}"
    recs = []
    for i, (t, name, attrs) in enumerate(evs):
        t_end = evs[i + 1][0] if i + 1 < len(evs) else t
        recs.append(SpanRecord(f"request/{name}", t, t_end, 1, lane, 0,
                               None, dict(attrs or {})))
    doc = _chrome(recs)
    doc["otherData"]["trace_id"] = tr.trace_id
    doc["otherData"]["outcome"] = tr.outcome
    return doc


def export_request_chrome_trace(trace_or_id, path: Optional[str] = None):
    """Write one request's Chrome trace to ``path`` (or return the
    dict when ``path`` is None)."""
    doc = chrome_trace(trace_or_id)
    if path is None:
        return doc
    import json

    with open(path, "w") as f:
        json.dump(doc, f)
    return path
