"""Flight recorder: always-on bounded ring of structured run events.

Role: the black box for the failure modes the in-process tracer cannot
see.  The span tracer (``observe/tracer.py``) and histograms observe a
*healthy* hot path; when the device itself dies ("device init did not
complete within 240s", BENCH rounds 4-5) all that survives is whatever
was written down *before* the hang.  This module keeps a bounded
in-memory ring of structured JSONL events — run metadata (jax/jaxlib
versions, device topology, FLAGS snapshot, rank/world size) and
lifecycle events (Executor dispatch/drain, checkpoint save/restore,
serving start/stop, postmortem dumps) — cheap enough to leave on in
production (one dict + deque append per event, ~µs), gated by
``FLAGS_flight_recorder`` (default ON).

``FLAGS_flight_recorder_file`` adds an always-on file sink: every event
is appended as one JSON line and flushed immediately, so a process that
dies without running any handler still leaves its tail on disk (the
Dapper-style "postmortem dump" half of always-on tracing).  The
postmortem bundle (``observe/health.py``) embeds ``tail()`` regardless.

Events are plain dicts::

    {"ts": <epoch seconds>, "seq": <monotone int>, "event": "ckpt/commit",
     ...event fields...}

Event names are slash-namespaced like span names (``executor/…``,
``ckpt/…``, ``serving/…``, ``run/…``, ``health/…``, ``postmortem/…``).
"""
from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from ..framework import flags as _flags

__all__ = ["FlightRecorder", "get_flight_recorder", "record",
           "record_run_metadata", "record_device_topology", "run_metadata",
           "snapshot_events", "tail", "dump", "clear_events"]

DEFAULT_CAPACITY = 4096


def _jsonable(v):
    """Best-effort conversion so record() never raises on an odd field
    value (instrumentation must not take the process down)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return repr(v)


class FlightRecorder:
    """Thread-safe bounded ring of structured events + optional file
    sink.  The module singleton is what the framework feeds; tests may
    build their own with a small capacity."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._buf = collections.deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._seq = 0
        self._dropped = 0
        self._meta_recorded = False
        self._topology_recorded = False
        self._sink = None
        self._sink_path: Optional[str] = None
        self._sink_failed_path: Optional[str] = None
        self._rotations = 0

    # -- recording -------------------------------------------------------
    def record(self, event: str, **fields) -> Optional[dict]:
        """Append one event.  Never raises: a sink write failure or an
        unserializable field degrades, it does not propagate into the
        training loop."""
        rec = {"ts": time.time(), "seq": 0, "event": str(event)}
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            if len(self._buf) == self._buf.maxlen:
                self._dropped += 1
            self._buf.append(rec)
            self._write_sink(rec)
        return rec

    def _write_sink(self, rec: dict) -> None:
        """File sink (called under the lock): follows
        ``FLAGS_flight_recorder_file`` live — set/clear/retarget the
        flag at any time.  Each line is flushed so a dying process
        keeps its tail."""
        try:
            path = _flags.flag("flight_recorder_file")
        except KeyError:  # pragma: no cover - partial installs
            path = ""
        try:
            if not path:
                if self._sink is not None:
                    self._sink.close()
                    self._sink = None
                    self._sink_path = None
                self._sink_failed_path = None
                return
            if path == self._sink_failed_path:
                return  # latched: don't pay two failing syscalls per
                # hot-path event; retargeting the flag re-tries
            if self._sink is None or self._sink_path != path:
                if self._sink is not None:
                    self._sink.close()
                d = os.path.dirname(path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._sink = open(path, "a")
                self._sink_path = path
                self._sink_failed_path = None
            self._sink.write(json.dumps(rec) + "\n")
            self._sink.flush()
            self._maybe_rotate(path)
        except OSError:  # sink trouble must never fail the caller
            self._sink = None
            self._sink_path = None
            self._sink_failed_path = path

    def _maybe_rotate(self, path: str) -> None:
        """Size-based sink rotation (``FLAGS_flight_recorder_max_mb``,
        called under the lock right after a flushed write): when the
        active segment passes the cap it becomes ``<path>.1`` (the one
        previous segment kept — two segments bound disk at 2x the cap
        on an unbounded run) and a fresh segment opens.  The rotated
        file is complete JSONL, so a post-SIGKILL reader concatenating
        ``<path>.1`` + ``<path>`` always has at least one full cap of
        tail history."""
        try:
            max_mb = float(_flags.flag("flight_recorder_max_mb") or 0.0)
        except KeyError:  # pragma: no cover - partial installs
            return
        if max_mb <= 0.0 or self._sink is None:
            return
        if self._sink.tell() < max_mb * 1024.0 * 1024.0:
            return
        self._sink.close()
        self._sink = None
        os.replace(path, path + ".1")  # atomic; drops any older .1
        self._sink = open(path, "a")
        self._sink_path = path
        self._rotations += 1
        try:
            from ..monitor import stat_add

            stat_add("flight_sink_rotations")
        except ImportError:  # pragma: no cover
            pass

    # -- reading ---------------------------------------------------------
    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._buf)

    def tail(self, n: Optional[int] = None) -> List[dict]:
        evs = self.snapshot()
        return evs if n is None else evs[-int(n):]

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def dump(self, path: str, n: Optional[int] = None) -> str:
        """Write the (tail of the) ring as JSONL to ``path``."""
        evs = self.tail(n)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            for rec in evs:
                f.write(json.dumps(rec) + "\n")
        return path

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._dropped = 0
            self._meta_recorded = False
            self._topology_recorded = False


_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _RECORDER


def enabled() -> bool:
    return bool(_flags.flag("flight_recorder"))


def record(event: str, **fields) -> Optional[dict]:
    """Record one event on the process recorder; no-op (one flag read)
    when ``FLAGS_flight_recorder`` is off."""
    if not _flags.flag("flight_recorder"):
        return None
    return _RECORDER.record(event, **fields)


# ---------------------------------------------------------------------------
# run metadata
# ---------------------------------------------------------------------------


def _rank_world() -> tuple:
    """(rank, world_size) best-effort — shared by run metadata and
    postmortem meta (observe/health.py) so rank discovery changes in
    one place."""
    try:
        from ..distributed.parallel_env import get_rank

        rank = get_rank()
    except Exception:  # noqa: BLE001 - metadata only
        rank = 0
    return rank, int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)


def run_metadata(include_devices: bool = False) -> Dict:
    """The who/what/where of this process: versions, rank/world, FLAGS
    snapshot, argv.  ``include_devices=True`` additionally queries jax
    for the device topology — callers must only pass it once the
    backend is (being) initialized; ``jax.devices()`` on a dead TPU is
    exactly the 240s hang this recorder exists to diagnose."""
    import platform

    meta: Dict = {
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "python": platform.python_version(),
        "host": platform.node(),
    }
    try:
        import jax

        meta["jax_version"] = jax.__version__
        try:
            import jaxlib

            meta["jaxlib_version"] = jaxlib.version.__version__
        except Exception:  # noqa: BLE001 - version probing only
            pass
    except ImportError:  # pragma: no cover - jax is a hard dep in practice
        pass
    meta["rank"], meta["world_size"] = _rank_world()
    meta["flags"] = _flags.flags_snapshot()
    if include_devices:
        meta.update(_device_topology())
    return meta


def _device_topology() -> Dict:
    try:
        import jax

        devs = jax.devices()
        return {
            "platform": devs[0].platform if devs else "none",
            "device_count": len(devs),
            "local_device_count": jax.local_device_count(),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "devices": [str(d) for d in devs[:16]],
        }
    except Exception as e:  # noqa: BLE001 - a dead backend is an EVENT
        return {"device_probe_error": f"{type(e).__name__}: {e}"}


def record_run_metadata(force: bool = False, **extra) -> Optional[dict]:
    """Record the ``run/metadata`` event once per process (the first
    Executor construction calls this; later calls are no-ops unless
    ``force``)."""
    if not _flags.flag("flight_recorder"):
        return None
    with _RECORDER._lock:
        if _RECORDER._meta_recorded and not force:
            return None
        _RECORDER._meta_recorded = True
    return _RECORDER.record("run/metadata", **run_metadata(), **extra)


def record_device_topology(force: bool = False) -> Optional[dict]:
    """Record the ``run/devices`` event once per process.  Called from
    the Executor's first compile — the one point where the backend is
    definitionally in use, so the jax.devices() probe cannot introduce
    a device-init it wasn't already paying for."""
    if not _flags.flag("flight_recorder"):
        return None
    with _RECORDER._lock:
        if _RECORDER._topology_recorded and not force:
            return None
        _RECORDER._topology_recorded = True
    return _RECORDER.record("run/devices", **_device_topology())


# ---------------------------------------------------------------------------
# module-level conveniences over the singleton
# ---------------------------------------------------------------------------


def snapshot_events() -> List[dict]:
    return _RECORDER.snapshot()


def tail(n: Optional[int] = None) -> List[dict]:
    return _RECORDER.tail(n)


def dump(path: str, n: Optional[int] = None) -> str:
    return _RECORDER.dump(path, n)


def clear_events() -> None:
    _RECORDER.clear()


def _atexit_flush():  # pragma: no cover - interpreter teardown
    r = _RECORDER
    with r._lock:
        if r._sink is not None:
            try:
                r._sink.flush()
            except OSError:
                pass


import atexit  # noqa: E402

atexit.register(_atexit_flush)
