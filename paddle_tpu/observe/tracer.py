"""In-process span tracer: always-available, low-overhead host timeline.

Role parity: the reference's CUPTI ``DeviceTracer`` + ``RecordEvent``
host annotations feeding ``profiler.proto`` (platform/device_tracer.cc,
platform/profiler.cc:53).  TPU-native framing: XLA owns the device
timeline (``jax.profiler`` captures it when asked), but a heavyweight
XLA capture is the wrong tool for "where did THIS step's milliseconds
go" in a serving process at 3am — so this tracer records *host-side*
named spans into a bounded in-memory ring buffer, always compiled in,
gated by ``FLAGS_enable_tracer``, and exportable at any moment as
Chrome trace-event JSON (``observe/timeline.py``) without restarting or
re-running anything.

Design constraints:
- **Disabled cost ~ zero**: ``span()`` with the flag off is one dict
  lookup and a shared no-op context manager — no allocation, no lock.
- **Enabled cost is bounded**: finished spans land in a
  ``deque(maxlen=capacity)`` (old spans fall off; a long-lived server
  cannot leak), two ``perf_counter`` calls + one lock per span.
- **Thread-correct nesting**: the open-span stack is thread-local, so
  concurrent serving clients / executor callers each get a properly
  nested lane, keyed by thread id in the export.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional

from ..framework import flags as _flags

__all__ = ["SpanRecord", "Tracer", "get_tracer", "enabled", "enable",
           "disable", "span", "begin", "end", "snapshot", "clear",
           "NULL_SPAN"]

DEFAULT_CAPACITY = 65536

# perf_counter origin for the whole process: every span timestamp is
# relative to this, so spans from different threads share one timeline
_EPOCH = time.perf_counter()


class SpanRecord(NamedTuple):
    """One finished span (times are seconds since the tracer epoch)."""

    name: str
    t_begin: float
    t_end: float
    tid: int
    thread_name: str
    depth: int          # 0 = top-level on its thread
    parent: Optional[str]
    args: Optional[dict]

    @property
    def duration(self) -> float:
        return self.t_end - self.t_begin


class Tracer:
    """Ring buffer of finished spans + per-thread open-span stacks."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        import collections

        self._buf = collections.deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._local = threading.local()
        self._dropped = 0
        self.pid = os.getpid()

    # -- recording -------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def begin(self, name: Optional[str], args: Optional[dict] = None) -> None:
        """``name=None`` pushes a DISCARD sentinel: the matching end()
        pops it without recording.  The module-level begin() pushes it
        when the tracer is disabled, so a begin/end pair stays balanced
        even if ``FLAGS_enable_tracer`` flips between the two calls."""
        if name is None:
            self._stack().append((None, 0.0, None))
            return
        self._stack().append((name, time.perf_counter() - _EPOCH, args))

    def end(self) -> None:
        st = self._stack()
        if not st:  # unbalanced end(): drop silently (never raise in
            return  # instrumentation paths)
        if st[-1][0] is None:  # disabled-begin sentinel
            st.pop()
            return
        t1 = time.perf_counter() - _EPOCH
        name, t0, args = st.pop()
        th = threading.current_thread()
        # sentinels are invisible to nesting: depth/parent only count
        # real open spans
        depth = sum(1 for e in st if e[0] is not None)
        parent = next((e[0] for e in reversed(st) if e[0] is not None),
                      None)
        rec = SpanRecord(name, t0, t1, th.ident or 0, th.name, depth,
                         parent, args)
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self._dropped += 1
            self._buf.append(rec)

    def set_args(self, **kwargs) -> None:
        """Attach/extend args on the INNERMOST open span of this thread
        (e.g. byte counts known only after the span body ran)."""
        st = self._stack()
        if not st or st[-1][0] is None:  # no open span / sentinel
            return
        name, t0, args = st[-1]
        merged = dict(args or {})
        merged.update(kwargs)
        st[-1] = (name, t0, merged)

    # -- reading ---------------------------------------------------------
    def snapshot(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._buf)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._dropped = 0


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def enabled() -> bool:
    """Single source of truth is ``FLAGS_enable_tracer`` (so
    ``paddle_tpu.set_flags`` and the env var both just work)."""
    return bool(_flags.flag("enable_tracer"))


def enable() -> None:
    _flags.set_flags({"enable_tracer": True})


def disable() -> None:
    _flags.set_flags({"enable_tracer": False})


class _Span:
    """Context manager for one live span (only built when enabled)."""

    __slots__ = ("_name", "_args")

    def __init__(self, name, args):
        self._name = name
        self._args = args

    def __enter__(self):
        _TRACER.begin(self._name, self._args or None)
        return self

    def __exit__(self, *exc):
        _TRACER.end()
        return False


class _NullSpan:
    """Shared no-op: the disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()

# the shared no-op, exported for instrumentation sites that need an
# "either a span or nothing" slot (e.g. the Executor's first-call
# compile wrapper) without growing their own null context manager
NULL_SPAN = _NULL


def span(name: str, **attrs):
    """``with observe.span("executor/run", bytes=n):`` — no-op unless
    ``FLAGS_enable_tracer`` is set."""
    if not _flags.flag("enable_tracer"):
        return _NULL
    return _Span(name, attrs)


def begin(name: str, **attrs) -> None:
    """Explicit begin/end pair (``RecordEvent`` dual-feed path).  The
    caller must guarantee LIFO order per thread.  Gated by
    ``FLAGS_enable_tracer`` like ``span()`` — a disabled begin pushes
    only a discard sentinel so the pair stays balanced across flag
    flips."""
    if _flags.flag("enable_tracer"):
        _TRACER.begin(name, attrs or None)
    else:
        _TRACER.begin(None)


def end() -> None:
    _TRACER.end()


def set_span_args(**kwargs) -> None:
    if _flags.flag("enable_tracer"):
        _TRACER.set_args(**kwargs)


def snapshot() -> List[SpanRecord]:
    return _TRACER.snapshot()


def clear() -> None:
    _TRACER.clear()
