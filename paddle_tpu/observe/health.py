"""Stall watchdog, postmortem bundles, and cluster-wide health telemetry.

Three pieces, one purpose: make a device-level failure *diagnosable
after the fact* (ROADMAP open item 4 — at production scale preemption
and device loss are the steady state, and a one-line "device init did
not complete within 240s" is not a diagnosis).

- **Stall watchdog** (:class:`StallWatchdog`): a daemon thread that
  samples executor progress — steps dispatched vs drained and the age
  of the oldest in-flight window entry — and, on no-progress past
  ``FLAGS_stall_timeout_s``, dumps a postmortem bundle.  It re-arms
  only after progress resumes, so one stall produces one bundle.
- **Postmortem bundle** (:func:`dump_postmortem`): a directory with
  all-thread Python stacks (``faulthandler`` + ``sys._current_frames``
  with thread names), the tracer ring as a Chrome trace, a Prometheus
  metrics snapshot, the flight-recorder tail, the FLAGS snapshot, and
  a ``meta.json`` (reason, progress, exception).  Also installable as
  a crash hook (:func:`install_crash_handler`): an uncaught exception
  dumps the same bundle, and ``faulthandler`` is armed for fatal
  signals so even a segfaulting process leaves its stacks.
- **Cluster health** (:class:`HealthReporter` +
  :func:`serve_cluster_health`): each rank publishes periodic
  heartbeat+metrics snapshots to the fleet KV HTTP server; rank 0
  serves an aggregated ``/metrics/cluster`` route with per-rank
  last-heartbeat age, step-time skew (the straggler gauge), and
  rank-liveness counters — the signal plane the elastic supervisor
  (ROADMAP item 4) acts on.

Locking discipline: everything the watchdog samples is read WITHOUT
taking executor/window locks — the stalled thread may be blocked *while
holding* the window lock, and a watchdog that deadlocks on the very
hang it is meant to report is worse than none.  ``len(deque)`` and
``deque[0]`` are GIL-atomic; a rare torn read costs one poll interval.
"""
from __future__ import annotations

import faulthandler
import json
import os
import re
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from ..framework import flags as _flags
from . import flight as _flight

__all__ = ["StallWatchdog", "HealthReporter", "executor_progress",
           "dump_postmortem", "start_watchdog", "stop_watchdog",
           "get_watchdog", "maybe_start_watchdog", "install_crash_handler",
           "uninstall_crash_handler", "cluster_health",
           "serve_cluster_health", "HEALTH_KEY_PREFIX"]

HEALTH_KEY_PREFIX = "health/rank/"

_BUNDLE_FILES = ("meta.json", "stacks.txt", "trace.json", "metrics.prom",
                 "flight.jsonl", "flags.json", "memory.json",
                 "phases.json")


# ---------------------------------------------------------------------------
# executor progress sampling
# ---------------------------------------------------------------------------


def executor_progress() -> Dict:
    """One sample of process-wide executor progress: cumulative steps
    dispatched/drained (monitor counters fed by framework/executor.py),
    total in-flight window entries, the age in seconds of the OLDEST
    undrained entry (None when nothing is in flight), whether EVERY
    live window's next-to-drain entry is already device-complete
    (``oldest_ready`` via the non-blocking ``jax.Array.is_ready`` probe
    — completed-but-unread work is an idle host, not a hung device;
    judged per window so one idle executor cannot mask another's
    hang), and whether a
    first-call trace+XLA-compile is in flight (``compiling`` +
    ``compile_age_s`` — compiles legitimately take minutes).  Lock-free
    by design — see the module docstring."""
    from ..monitor import stat_get

    out = {
        "dispatched": stat_get("executor_steps_dispatched"),
        "drained": stat_get("executor_steps_drained"),
        "inflight": 0,
        "oldest_inflight_age_s": None,
        "oldest_ready": None,
        "compiling": False,
    }
    try:
        from ..framework.executor import _ACTIVE_COMPILES, _LIVE_EXECUTORS

        now = time.perf_counter()
        ages = []
        ready_flags = []
        inflight = 0
        for exe in list(_LIVE_EXECUTORS):
            entries = exe._window._entries  # no lock: GIL-atomic reads
            n = len(entries)
            inflight += n
            if not n:
                continue
            try:
                e = entries[0]
                age = now - e.t_dispatch
            except IndexError:  # drained between len() and [0]
                continue
            ages.append(age)
            # readiness of the NEXT-TO-DRAIN entry of THIS window
            # (drains are FIFO per window; aggregating across windows
            # must be per-window, or one idle-but-complete executor
            # would mask another executor's genuine hang)
            ready = None
            try:
                refs = [r for r in e.sync_refs if hasattr(r, "is_ready")]
                if refs:
                    ready = all(r.is_ready() for r in refs)
            except Exception:  # noqa: BLE001 - deleted buffer etc.
                ready = None
            ready_flags.append(ready)
        out["inflight"] = inflight
        if ages:
            out["oldest_inflight_age_s"] = round(max(ages), 3)
        if ready_flags:
            # True only when EVERY window's next drain is verifiably
            # device-complete; an unknown probe counts as not-ready (a
            # mocked/hung buffer without is_ready must read as a hang)
            out["oldest_ready"] = all(f is True for f in ready_flags)
        compiles = list(_ACTIVE_COMPILES.values())
        out["compiling"] = bool(compiles)
        if compiles:
            out["compile_age_s"] = round(now - min(compiles), 3)
    except ImportError:  # pragma: no cover - partial installs
        pass
    return out


# ---------------------------------------------------------------------------
# postmortem bundle
# ---------------------------------------------------------------------------


def _format_all_stacks() -> str:
    """All-thread stacks with THREAD NAMES (faulthandler prints only
    ids; the names — 'ckpt-writer', 'serving-batcher', 'MainThread' —
    are what make a hang readable)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    lines: List[str] = []
    for tid, frame in sorted(sys._current_frames().items()):
        lines.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        lines.extend(
            ln.rstrip() for ln in traceback.format_stack(frame))
        lines.append("")
    return "\n".join(lines)


def dump_postmortem(reason: str, directory: Optional[str] = None,
                    exc: Optional[tuple] = None,
                    extra: Optional[dict] = None) -> str:
    """Write a postmortem bundle and return its directory path.

    Bundle layout (every section best-effort — one broken exporter
    must not lose the rest; failures are recorded in ``meta.json``):

    - ``meta.json``    reason, timestamps, pid/rank, executor progress,
      exception (when given), per-section errors
    - ``stacks.txt``   all-thread Python stacks (named + faulthandler)
    - ``trace.json``   tracer ring as Chrome trace-event JSON
    - ``metrics.prom`` Prometheus text exposition snapshot
    - ``flight.jsonl`` flight-recorder tail
    - ``flags.json``   FLAGS snapshot
    - ``memory.json``  XLA compile records (per-chip HBM footprint
      breakdown + per-var attribution + budget verdicts) and a live
      per-device memory sample (observe/xla_stats.py)
    - ``requests.json`` per-request serving traces: retained SLO
      violators + abnormal endings (full timelines), the live
      in-flight table, and the SLO verdict snapshot (burn rates,
      budget remaining, goodput) — observe/request_trace.py +
      observe/slo.py; pretty-print with ``python -m tools.reqtrace``
    - ``phases.json`` step-phase attribution snapshot
      (observe/phases.py): measured compute / exposed-comm / host /
      input-wait split, the predicted cost-model fractions, and the
      per-collective exposed-vs-hidden ledger
    """
    directory = directory or _flags.flag("postmortem_dir") or "postmortem"
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", str(reason))[:48] or "unknown"
    stamp = time.strftime("%Y%m%d_%H%M%S")
    path = os.path.join(str(directory),
                        f"bundle_{stamp}_{os.getpid()}_{safe}")
    # a second dump in the same second (watchdog + crash hook racing)
    # must not interleave into one directory
    base, i = path, 1
    while os.path.exists(path):
        path = f"{base}.{i}"
        i += 1
    os.makedirs(path, exist_ok=True)

    errors: Dict[str, str] = {}

    def section(name: str, fn: Callable[[str], None]) -> None:
        try:
            fn(os.path.join(path, name))
        except Exception as e:  # noqa: BLE001 - keep dumping
            errors[name] = f"{type(e).__name__}: {e}"

    def _stacks(p):
        with open(p, "w") as f:
            f.write(_format_all_stacks())
            f.write("\n=== faulthandler ===\n")
            f.flush()
            faulthandler.dump_traceback(file=f, all_threads=True)

    def _trace(p):
        from .timeline import export_chrome_trace

        export_chrome_trace(p)

    def _metrics(p):
        from .histogram import prometheus_text

        with open(p, "w") as f:
            f.write(prometheus_text())

    def _flight_tail(p):
        _flight.get_flight_recorder().dump(p)

    def _flags_json(p):
        with open(p, "w") as f:
            json.dump(_flags.flags_snapshot(), f, indent=2, sort_keys=True,
                      default=repr)

    def _memory_json(p):
        from . import xla_stats

        with open(p, "w") as f:
            json.dump(xla_stats.memory_report(), f, indent=2, default=repr)

    def _requests_json(p):
        from . import request_trace as _rt
        from . import slo as _slo

        store = _rt.get_trace_store()
        doc = {
            "slo": _slo.snapshot(),
            "violators": [t.to_dict() for t in store.violators(50)],
            "retained": [t.to_dict(events=False)
                         for t in store.retained(100)],
            "inflight": [t.to_dict(events=False)
                         for t in store.inflight()],
        }
        with open(p, "w") as f:
            json.dump(doc, f, indent=2, default=repr)

    def _phases_json(p):
        from . import phases as _phases

        with open(p, "w") as f:
            json.dump(_phases.phases_report(), f, indent=2, default=repr)

    section("stacks.txt", _stacks)
    section("trace.json", _trace)
    section("metrics.prom", _metrics)
    section("flight.jsonl", _flight_tail)
    section("flags.json", _flags_json)
    section("memory.json", _memory_json)
    section("requests.json", _requests_json)
    section("phases.json", _phases_json)

    meta = {
        "reason": str(reason),
        "ts": time.time(),
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "pid": os.getpid(),
        "progress": executor_progress(),
        "section_errors": errors,
    }
    meta["rank"], meta["world_size"] = _flight._rank_world()
    if exc is not None:
        tp, val, tb = (exc + (None, None, None))[:3]
        meta["exception"] = {
            "type": getattr(tp, "__name__", str(tp)),
            "value": str(val),
            "traceback": "".join(
                traceback.format_exception(tp, val, tb))[-8000:],
        }
    if extra:
        meta["extra"] = _flight._jsonable(extra)
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2, default=repr)

    from ..monitor import stat_add

    stat_add("postmortem_bundles")
    _flight.record("postmortem/dump", reason=str(reason), path=path)
    return path


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------


class StallWatchdog:
    """Daemon thread: trips when work is pending but nothing drains.

    Stall definition: ``dispatched > drained`` (or any window entry in
    flight) AND neither counter has moved for ``timeout_s`` —
    equivalently, the oldest in-flight entry is older than the timeout.
    Three things are explicitly NOT stalls:

    - an *idle* process (nothing pending) never trips;
    - a *failing* process (drains raising) never trips, because a
      failed drain still advances the drained counter — a raise is
      progress, a hang is not;
    - an in-flight entry whose buffers are already device-complete
      (``oldest_ready``) never trips — the device finished, the host
      just hasn't read it yet (e.g. an interactive session between
      steps);
    - while a first-call trace+XLA-compile is in flight the timeout is
      scaled by ``compile_grace`` (default 10x): a multi-minute compile
      is legitimate, but a compile hung 10x past the stall timeout is
      itself the failure (e.g. XLA compiling against a dead device).

    On a stall: dump a postmortem bundle, record a flight event, bump
    ``watchdog_stalls`` on ``/metrics``, call ``on_stall(bundle_path)``
    if given, and latch until progress resumes (one bundle per stall,
    not one per poll)."""

    def __init__(self, timeout_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 directory: Optional[str] = None,
                 progress_fn: Optional[Callable[[], Dict]] = None,
                 on_stall: Optional[Callable[[str], None]] = None,
                 compile_grace: float = 10.0):
        t = timeout_s if timeout_s is not None \
            else float(_flags.flag("stall_timeout_s"))
        if t <= 0:
            raise ValueError(
                "StallWatchdog needs timeout_s > 0 (set it or "
                "FLAGS_stall_timeout_s)")
        self.timeout_s = float(t)
        self.poll_s = float(poll_s) if poll_s is not None \
            else max(min(self.timeout_s / 4.0, 10.0), 0.05)
        self.directory = directory
        self.compile_grace = max(float(compile_grace), 1.0)
        self._progress_fn = progress_fn or executor_progress
        self._on_stall = on_stall
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.bundles: List[str] = []
        self.stalls = 0
        self._tripped = False

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "StallWatchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="stall-watchdog", daemon=True)
        self._thread.start()
        _flight.record("health/watchdog_start", timeout_s=self.timeout_s,
                       poll_s=self.poll_s)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- the loop --------------------------------------------------------
    def _loop(self) -> None:
        last_sig = None
        last_progress = time.perf_counter()
        while not self._stop.wait(self.poll_s):
            try:
                p = self._progress_fn()
            except Exception:  # noqa: BLE001 - keep watching
                continue
            now = time.perf_counter()
            pending = (p.get("inflight", 0) or 0) > 0 or \
                p.get("dispatched", 0) > p.get("drained", 0)
            if p.get("oldest_ready") is True:
                # the next-to-drain step is device-complete: the host
                # simply hasn't read it — idle, not hung (drains are
                # FIFO, so the oldest entry gates everything)
                pending = False
            sig = (p.get("drained", 0), p.get("dispatched", 0))
            if sig != last_sig or not pending:
                last_sig = sig
                last_progress = now
                self._tripped = False  # progress resumed: re-arm
                continue
            grace = self.compile_grace if p.get("compiling") else 1.0
            eff = self.timeout_s * grace
            age = p.get("oldest_inflight_age_s")
            stalled = (now - last_progress) >= eff or \
                (age is not None and age >= eff)
            if stalled and not self._tripped:
                self._tripped = True
                self.stalls += 1
                self._handle_stall(p)

    def _handle_stall(self, progress: Dict) -> None:
        from ..monitor import stat_add

        stat_add("watchdog_stalls")
        _flight.record("health/stall", **progress,
                       timeout_s=self.timeout_s)
        try:
            bundle = dump_postmortem(
                "stall", directory=self.directory,
                extra={"progress": progress,
                       "stall_timeout_s": self.timeout_s})
        except Exception:  # noqa: BLE001 - the dump must not kill the dog
            return
        self.bundles.append(bundle)
        if self._on_stall is not None:
            try:
                self._on_stall(bundle)
            except Exception:  # noqa: BLE001
                pass


_WATCHDOG: Optional[StallWatchdog] = None
_WATCHDOG_LOCK = threading.Lock()


def get_watchdog() -> Optional[StallWatchdog]:
    return _WATCHDOG


def start_watchdog(**kwargs) -> StallWatchdog:
    """Start (or return) the process-wide watchdog singleton."""
    global _WATCHDOG
    with _WATCHDOG_LOCK:
        if _WATCHDOG is not None and _WATCHDOG.running:
            return _WATCHDOG
        _WATCHDOG = StallWatchdog(**kwargs)
        return _WATCHDOG.start()


def stop_watchdog() -> None:
    global _WATCHDOG
    with _WATCHDOG_LOCK:
        wd, _WATCHDOG = _WATCHDOG, None
    if wd is not None:
        wd.stop()


def maybe_start_watchdog() -> Optional[StallWatchdog]:
    """Auto-start hook (Executor construction): a watchdog when
    ``FLAGS_stall_timeout_s`` > 0, else nothing."""
    try:
        if float(_flags.flag("stall_timeout_s")) <= 0:
            return None
    except KeyError:  # pragma: no cover
        return None
    return start_watchdog()


# ---------------------------------------------------------------------------
# crash / atexit hook
# ---------------------------------------------------------------------------

_CRASH_STATE: Dict = {"installed": False, "prev_hook": None,
                      "fh_file": None, "dir": None, "atexit_dump": False,
                      "dumped_at_exit": False}


def install_crash_handler(directory: Optional[str] = None,
                          dump_at_exit: bool = False) -> None:
    """Arm the process so a death leaves a bundle:

    - ``sys.excepthook`` wrapper: an uncaught exception dumps a
      ``crash`` bundle (then chains to the previous hook).
    - ``faulthandler`` on fatal signals (SIGSEGV/SIGABRT/...) writing
      all-thread stacks to ``<dir>/fatal_<pid>.log`` — a hard crash
      can't run Python, but the pre-registered dump still fires.
    - with ``dump_at_exit=True``, an atexit hook dumps a final
      ``exit`` bundle unconditionally (supervisor mode: always leave
      last-known state).

    Idempotent; :func:`uninstall_crash_handler` undoes it (tests)."""
    if _CRASH_STATE["installed"]:
        return
    directory = directory or _flags.flag("postmortem_dir") or "postmortem"
    _CRASH_STATE["dir"] = directory
    _CRASH_STATE["atexit_dump"] = bool(dump_at_exit)
    prev = sys.excepthook

    def hook(tp, val, tb):
        try:
            dump_postmortem("crash", directory=_CRASH_STATE["dir"],
                            exc=(tp, val, tb))
        except Exception:  # noqa: BLE001 - never mask the real error
            pass
        prev(tp, val, tb)

    sys.excepthook = hook
    _CRASH_STATE["prev_hook"] = prev
    try:
        os.makedirs(directory, exist_ok=True)
        f = open(os.path.join(directory, f"fatal_{os.getpid()}.log"), "w")
        faulthandler.enable(file=f, all_threads=True)
        _CRASH_STATE["fh_file"] = f
    except OSError:
        _CRASH_STATE["fh_file"] = None
    _CRASH_STATE["installed"] = True
    _flight.record("health/crash_handler_installed", dir=str(directory))


def uninstall_crash_handler() -> None:
    if not _CRASH_STATE["installed"]:
        return
    if _CRASH_STATE["prev_hook"] is not None:
        sys.excepthook = _CRASH_STATE["prev_hook"]
    if _CRASH_STATE["fh_file"] is not None:
        try:
            faulthandler.disable()
            _CRASH_STATE["fh_file"].close()
        except (OSError, ValueError):
            pass
    _CRASH_STATE.update(installed=False, prev_hook=None, fh_file=None,
                        dir=None, atexit_dump=False)


def _atexit_bundle():  # pragma: no cover - interpreter teardown
    if _CRASH_STATE["installed"] and _CRASH_STATE["atexit_dump"] \
            and not _CRASH_STATE["dumped_at_exit"]:
        _CRASH_STATE["dumped_at_exit"] = True
        try:
            dump_postmortem("exit", directory=_CRASH_STATE["dir"])
        except Exception:  # noqa: BLE001
            pass


import atexit  # noqa: E402

atexit.register(_atexit_bundle)


# ---------------------------------------------------------------------------
# cluster health: per-rank heartbeats over the fleet KV server
# ---------------------------------------------------------------------------


# default cross-scrape rank-epoch book for bare cluster_health() calls
# (serve_cluster_health keeps a per-route book instead)
_CLUSTER_BOOK: Dict[int, Dict] = {}


def _default_rank_stats() -> Dict:
    """What a rank puts in its heartbeat: progress counters + the raw
    step-time p50.  Reads the histogram DIRECTLY (not
    ``StepTimer.summary()``, which quiesces every executor — a
    heartbeat thread must never force drains under the training
    loop)."""
    from .histogram import histogram
    from .step_stats import STEP_TIME_HISTOGRAM

    out = executor_progress()
    h = histogram(STEP_TIME_HISTOGRAM)
    if h.count:
        out["step_time_p50_s"] = round(h.percentile(50), 6)
        out["steps_timed"] = h.count
    try:
        # per-rank comm-exposure share (observe/phases.py): reads the
        # engine's own ledger under its own lock — no drains forced —
        # and gives the cluster straggler gauge a CAUSE column
        from . import phases as _phases

        eng = _phases.phase_engine()
        if eng.steps:
            out["comm_exposed_share"] = round(eng.comm_exposed_share(), 6)
    except Exception:  # noqa: BLE001 - heartbeat must never die here
        pass
    try:
        # live per-chip HBM sample (observe/xla_stats.py): sets the
        # hbm_free/used/limit gauges on /metrics and rides the heartbeat
        # onto /metrics/cluster; {} where the backend has no memory
        # stats (CPU) — the heartbeat itself must never die on a probe
        from . import xla_stats

        out.update(xla_stats.record_device_memory())
    except Exception:  # noqa: BLE001
        pass
    return out


class HealthReporter:
    """Publishes this rank's heartbeat to the fleet KV HTTP server.

    Each beat PUTs one JSON document to ``health/rank/<rank>`` —
    ``{"rank", "ts", "pid", "interval_s", ...stats}`` — overwriting the
    previous one (the KV holds only latest-state; history belongs to
    the flight recorder).  Publish failures are counted
    (``health_heartbeat_failures``) and retried on the next beat: a
    down aggregator must never stall a training rank."""

    def __init__(self, endpoint: str, rank: int,
                 world_size: Optional[int] = None,
                 interval_s: Optional[float] = None,
                 stats_fn: Optional[Callable[[], Dict]] = None,
                 timeout_s: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        if not self.endpoint.startswith("http"):
            self.endpoint = "http://" + self.endpoint
        self.rank = int(rank)
        self.world_size = world_size
        self.interval_s = float(interval_s) if interval_s is not None \
            else float(_flags.flag("heartbeat_interval_s"))
        self.timeout_s = float(timeout_s)
        self._stats_fn = stats_fn or _default_rank_stats
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.beats = 0
        self.failures = 0

    # -- one beat --------------------------------------------------------
    def payload(self) -> Dict:
        p = {"rank": self.rank, "ts": time.time(), "pid": os.getpid(),
             "interval_s": self.interval_s}
        if self.world_size is not None:
            p["world_size"] = int(self.world_size)
        try:
            p.update(_flight._jsonable(self._stats_fn() or {}))
        except Exception as e:  # noqa: BLE001 - beat anyway
            p["stats_error"] = f"{type(e).__name__}: {e}"
        return p

    def publish_once(self) -> bool:
        """PUT one heartbeat; returns success.  Never raises."""
        import urllib.request

        # chaos hook (fleet.elastic.chaos "heartbeat_blackhole"): drop
        # this rank's beats so the health plane dead-lists a live
        # process — consulted ONLY when the chaos module is already
        # loaded (an unimported armory holds no armed faults)
        _chaos = sys.modules.get(
            "paddle_tpu.distributed.fleet.elastic.chaos")
        if _chaos is not None and \
                _chaos.take("heartbeat_blackhole", rank=self.rank):
            self.failures += 1
            from ..monitor import stat_add

            stat_add("health_heartbeat_blackholed")
            return False
        try:
            body = json.dumps(self.payload()).encode()
            url = f"{self.endpoint}/{HEALTH_KEY_PREFIX}{self.rank}"
            req = urllib.request.Request(url, data=body, method="PUT")
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
        except Exception:  # noqa: BLE001 - URLError, BadStatusLine, a
            # garbage non-HTTP responder, ...: ANY failure is one missed
            # beat, retried next interval — a surprising exception type
            # must not kill the daemon thread and falsely dead-list the
            # rank
            self.failures += 1
            from ..monitor import stat_add

            stat_add("health_heartbeat_failures")
            return False
        self.beats += 1
        return True

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "HealthReporter":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"health-reporter-r{self.rank}",
            daemon=True)
        self._thread.start()
        _flight.record("health/reporter_start", rank=self.rank,
                       endpoint=self.endpoint,
                       interval_s=self.interval_s)
        return self

    def _loop(self) -> None:
        self.publish_once()  # first beat immediately, not one interval in
        while not self._stop.wait(self.interval_s):
            self.publish_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def cluster_health(kv: Dict, world_size: Optional[int] = None,
                   now: Optional[float] = None,
                   book: Optional[Dict] = None) -> Dict:
    """Aggregate raw KV heartbeat entries into the cluster-health view
    (pure function: testable without HTTP).

    ``kv`` maps key -> bytes/str as stored by the KV server.  A rank is
    *alive* when its last heartbeat is younger than 3x its own reported
    interval — recomputed per scrape, so a dead-listed rank that
    RESUMES heartbeating re-enters ``alive_ranks`` and leaves
    ``dead_ranks`` on the very next aggregation.  The straggler gauge
    is relative step-time skew among alive ranks: ``(max_p50 - min_p50)
    / min_p50`` — 0.0 when balanced, 1.0 when the slowest rank takes
    twice the fastest's step time.  Liveness/skew are mirrored to
    StatRegistry gauges so the plain ``/metrics`` exposition carries
    them too.

    ``book`` is the cross-scrape bookkeeping dict (``serve_cluster_
    health`` keeps one per route; ``None`` uses a module-global): it
    carries each rank's MONOTONIC restart epoch.  A rank whose pid
    changed or whose cumulative ``dispatched`` counter went BACKWARDS
    has restarted — its epoch bumps, the entry is flagged
    ``restarted`` for this scrape, and it is excluded from the
    straggler-skew computation until its counters move forward again
    (a fresh process's reset step-time histogram is not a straggler
    going backwards; the elastic supervisor reads ``rank_epochs`` to
    tell a restarted rank from a stuck one)."""
    now = time.time() if now is None else now
    book = _CLUSTER_BOOK if book is None else book
    ranks: Dict[int, Dict] = {}
    for key, raw in kv.items():
        m = re.fullmatch(re.escape(HEALTH_KEY_PREFIX) + r"(\d+)", key)
        if not m:
            continue
        try:
            payload = json.loads(
                raw.decode() if isinstance(raw, (bytes, bytearray)) else raw)
        except (ValueError, UnicodeDecodeError):
            continue
        r = int(m.group(1))
        age = max(now - float(payload.get("ts", 0.0)), 0.0)
        interval = float(payload.get("interval_s", 0.0)) or \
            float(_flags.flag("heartbeat_interval_s"))
        entry = dict(payload)
        entry["last_heartbeat_age_s"] = round(age, 3)
        entry["alive"] = age < 3.0 * interval
        # monotonic rank-epoch bookkeeping (see docstring)
        pid = payload.get("pid")
        disp = payload.get("dispatched")
        rec = book.get(r)
        if rec is None:
            book[r] = rec = {"epoch": 0, "pid": pid, "dispatched": disp}
        else:
            new_pid = (pid is not None and rec.get("pid") is not None
                       and pid != rec["pid"])
            went_back = (isinstance(disp, (int, float))
                         and isinstance(rec.get("dispatched"),
                                        (int, float))
                         and disp < rec["dispatched"])
            if new_pid or went_back:
                rec["epoch"] += 1
                # sticky until the fresh process's counters move
                # FORWARD — a restarted rank that has not dispatched
                # a step yet must stay out of the skew gauge on every
                # scrape in between, not only the detection scrape
                rec["cooling"] = True
                from ..monitor import stat_add

                stat_add("cluster_rank_restarts")
            elif rec.get("cooling") and isinstance(disp, (int, float)) \
                    and isinstance(rec.get("dispatched"), (int, float)) \
                    and disp > rec["dispatched"]:
                rec.pop("cooling", None)
            if rec.get("cooling"):
                entry["restarted"] = True
            if pid is not None:
                rec["pid"] = pid
            if disp is not None:
                rec["dispatched"] = disp
        entry["epoch"] = rec["epoch"]
        ranks[r] = entry
        if world_size is None and "world_size" in payload:
            world_size = int(payload["world_size"])
    world = int(world_size) if world_size else \
        (max(ranks) + 1 if ranks else 0)

    alive = sorted(r for r, e in ranks.items() if e["alive"])
    dead = sorted(set(range(world)) - set(alive))
    out: Dict = {
        "ts": now,
        "world_size": world,
        "ranks": {str(r): ranks[r] for r in sorted(ranks)},
        "alive_ranks": len(alive),
        "dead_ranks": dead,
        "max_heartbeat_age_s": round(
            max((ranks[r]["last_heartbeat_age_s"] for r in ranks),
                default=0.0), 3),
    }
    out["rank_epochs"] = {str(r): ranks[r]["epoch"] for r in sorted(ranks)}
    # a just-restarted rank's step-time histogram restarted with it —
    # its p50 must not read as the fleet's fastest (or slowest) rank
    p50s = {r: float(ranks[r]["step_time_p50_s"]) for r in alive
            if float(ranks[r].get("step_time_p50_s") or 0.0) > 0.0
            and not ranks[r].get("restarted")}
    if len(p50s) >= 2:
        lo, hi = min(p50s.values()), max(p50s.values())
        out["step_time_skew"] = round((hi - lo) / lo, 4)
        straggler = max(p50s, key=p50s.get)
        out["straggler_rank"] = straggler
        # the CAUSE column (observe/phases.py heartbeat field): how
        # much of the straggler's priced comm is exposed — "rank 3:
        # 41% exposed-allreduce" instead of a bare rank number
        share = ranks[straggler].get("comm_exposed_share")
        if share is not None:
            from ..monitor import stat_set as _stat_set

            out["straggler_comm_exposed_share"] = float(share)
            out["straggler_cause"] = (
                f"rank {straggler}: {float(share) * 100:.0f}% "
                f"exposed-collective")
            _stat_set("cluster_straggler_comm_exposed_ppm",
                      int(float(share) * 1e6))
    else:
        out["step_time_skew"] = 0.0
    # HBM headroom across the fleet (heartbeat fields fed by
    # xla_stats.record_device_memory): the MIN free — the rank that
    # OOMs first — is the number the budget gate and the sharding
    # planner care about
    frees = {r: int(ranks[r]["hbm_free_bytes"]) for r in alive
             if ranks[r].get("hbm_free_bytes") is not None}
    if frees:
        out["min_hbm_free_bytes"] = min(frees.values())
        out["min_hbm_free_rank"] = min(frees, key=frees.get)

    from ..monitor import stat_set

    stat_set("cluster_ranks_expected", world)
    stat_set("cluster_ranks_alive", len(alive))
    stat_set("cluster_ranks_dead", len(dead))
    stat_set("cluster_step_time_skew_ppm",
             int(out["step_time_skew"] * 1e6))
    stat_set("cluster_max_heartbeat_age_ms",
             int(out["max_heartbeat_age_s"] * 1e3))
    if "min_hbm_free_bytes" in out:
        stat_set("cluster_min_hbm_free_bytes", out["min_hbm_free_bytes"])
    return out


def serve_cluster_health(kv_server, world_size: Optional[int] = None):
    """Register the aggregated ``GET /metrics/cluster`` route on a
    fleet ``KVServer`` (rank 0's).  Heartbeats arrive as ordinary KV
    PUTs under ``health/rank/<k>``; the route aggregates the live
    store on every scrape, so there is no aggregation thread to die.
    The rank-epoch book lives in the route closure — one per server,
    so restart detection survives across scrapes without leaking
    between servers (tests run many)."""
    book: Dict = {}

    def route():
        return cluster_health(kv_server.kv_snapshot(HEALTH_KEY_PREFIX),
                              world_size=world_size, book=book)

    kv_server.add_route("/metrics/cluster", route)
    _flight.record("health/cluster_route", world_size=world_size)
    return route
