"""Step-phase attribution: where a drained step's wall time goes.

The overlap schedule (PR 15) and fused kernels (PR 17) are judged by
one headline number each (``overlap_step_time_ratio``, MFU) — neither
says *where* a step's wall time actually went.  This module decomposes
every drained step into four buckets that sum exactly to its wall time:

- **compute**      — device time spent in the program's math,
- **comm_exposed** — device time stalled on collectives NOT hidden
  under compute (the number the overlap schedule exists to shrink),
- **host**         — dispatch-side host work (pass pipeline, state
  analysis, feed conversion) measured on the dispatch path,
- **input_wait**   — everything else between drains: the data loader
  and user code between ``run`` calls.

Two sources feed the split, and both are reported:

- **Measured** (``phase_*``): the window-drain timestamps the executor
  already takes (PR 5) — ``host`` is the dispatch-side host seconds
  carried on the in-flight entry, the drain's blocking time is the
  device-bound share, and the remainder of the inter-drain wall is
  input wait.  The device-bound share is split compute : exposed-comm
  by the cost model's predicted ratio (a host cannot see inside one
  ``block_until_ready``; a ``jax.profiler`` capture — see
  ``observe/profiler_capture.py`` — is the ground-truth refinement on
  real devices).
- **Predicted** (``phase_predicted_*``): a deterministic compile-time
  cost model — FLOPs (``hapi/model_stat`` or XLA's own
  ``cost_analysis`` count) over ``FLAGS_device_peak_tflops``, plus
  per-collective byte transfer times over
  ``FLAGS_phase_interconnect_gbps``.  Collectives stamped
  ``__comm_overlap__`` by FuseAllReducePass's stretch (and every
  collective-matmul chunk reduce except the last) hide under the
  remaining compute budget; the rest are exposed.  Static inputs only,
  so CPU/tier-1 runs get the same fractions every time.

The **collective ledger** prices every collective individually, keyed
by the FuseAllReducePass bucket / collective-matmul chunk identity
(``__comm_id__`` op attr): per-key ``exposed_s`` vs ``hidden_s``, so
``overlap_step_time_ratio`` finally has a per-bucket explanation and
``/metrics/cluster`` can say *why* a rank straggles ("rank 3: 41%
exposed-allreduce").  Cumulative totals ride ``/metrics`` as
``comm_exposed_seconds_micro`` / ``comm_hidden_seconds_micro`` /
``comm_exposed_share_ppm``.

Pure observer: gated by ``FLAGS_phase_attribution`` (no lowering
effect), fed only from timestamps the drain path already takes, and
proven bitwise-neutral + <=5% overhead by ``bench.py``'s phases leg.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..framework import flags as _flags
from ..monitor import stat_add, stat_set

__all__ = ["PhasePlan", "PhaseEngine", "phase_engine", "build_phase_plan",
           "collective_inventory", "on_step_drained", "phases_report",
           "reset_phases"]

_MICRO = 1e6

# measured bucket names, in report order; fractions are published as
# phase_<bucket>_fraction_ppm and totals as phase_<bucket>_seconds_micro
BUCKETS = ("compute", "comm_exposed", "host", "input_wait")


# ---------------------------------------------------------------------------
# compile-time collective inventory
# ---------------------------------------------------------------------------


def collective_inventory(block, op_list, mesh=None, tp_plan=None,
                         cm_chunks: int = 0,
                         moe_chunks: int = 0) -> List[dict]:
    """Per-collective entries from the post-pass op stream, in program
    order: ``{"id", "op", "dtype", "bytes", "overlap"}``.

    Byte accounting mirrors the executor's static telemetry
    (``_program_allreduce_bytes`` / ``_collective_span_args``): a
    LayerScanPass-stacked collective moves ``__layer_stack__`` x its
    var's declared bytes, and an mp-sharded grad reduce moves only its
    shard over dp.  A collective-matmul candidate op (``cm_chunks`` >
    1, single partial-sum anchor on its own single output) contributes
    one mp-reduce entry per chunk — every chunk's reduce except the
    last overlaps the next chunk's matmul, so only the tail chunk is
    exposed (ops/collective_matmul.py's latency model).

    Pure-GSPMD programs whose dp grad reduces are implicit (no
    allreduce ops in the stream) fall back to the sharding plan's
    ``grad_reduce`` table; when explicit allreduce ops exist they ARE
    the grad payload and the plan entries are skipped (no double
    count).
    """
    import math

    import numpy as np

    from ..framework import dtypes as _dtypes
    from ..framework.passes import (COMM_ID_ATTR, COMM_OVERLAP_ATTR,
                                    LAYER_STACK_ATTR, MOE_EP_ATTR,
                                    TP_CONSTRAINT_ATTR, TP_SPEC_ATTR,
                                    decode_anchor)

    mp_degree = 1
    if mesh is not None and "mp" in getattr(mesh, "axis_names", ()):
        mp_degree = int(mesh.shape["mp"])
    ep_degree = 1
    if mesh is not None and "ep" in getattr(mesh, "axis_names", ()):
        ep_degree = int(mesh.shape["ep"])

    def _var_bytes(name):
        var = block._find_var_recursive(name)
        if var is None or not var.shape or any(int(s) <= 0
                                               for s in var.shape):
            return 0, ""
        try:
            np_dt = _dtypes.to_np(var.dtype)
            itemsize = np.dtype(np_dt).itemsize
        except (KeyError, ValueError, TypeError):
            return 0, ""
        n = 1
        for s in var.shape:
            n *= int(s)
        return n * itemsize, str(np.dtype(np_dt))

    from ..framework.executor import COLLECTIVE_OPS

    entries: List[dict] = []
    saw_allreduce = False
    for op in op_list:
        if cm_chunks > 1 and mesh is not None and mp_degree > 1 \
                and op.has_attr(TP_CONSTRAINT_ATTR):
            anchors = [decode_anchor(e)
                       for e in op.attr(TP_CONSTRAINT_ATTR, [])]
            partial = [a for a in anchors if a[2]]
            outs = op.output_arg_names()
            if len(anchors) == 1 and len(partial) == 1 and len(outs) == 1 \
                    and partial[0][0] == outs[0]:
                nbytes, dt = _var_bytes(outs[0])
                if nbytes:
                    per_chunk = nbytes // cm_chunks
                    for i in range(cm_chunks):
                        entries.append({
                            "id": f"chunk:{outs[0]}@{i}",
                            "op": "mp_psum_chunk",
                            "dtype": dt,
                            "bytes": per_chunk,
                            # chunk k's reduce overlaps chunk k+1's
                            # matmul; only the LAST chunk is exposed
                            "overlap": i < cm_chunks - 1,
                        })
                continue
        if ep_degree > 1 and op.type in ("moe_ffn", "moe_ffn_grad") \
                and op.attr(MOE_EP_ATTR):
            # expert-parallel dispatch + combine all-to-all pair over
            # the [E, capacity, D] buffer (ops/moe_ops.py).  Capacity
            # is re-derived from the DECLARED shapes (symbolic batch
            # dims price per-sample — the same convention as the IR
            # FLOP estimate); with FLAGS_moe_alltoall_chunks on, each
            # all-to-all splits into capacity chunks where every chunk
            # but the last overlaps the next chunk's expert compute.
            w1 = op.inputs.get("W1", [None])[0]
            xn = op.inputs.get("X", [None])[0]
            wvar = block._find_var_recursive(w1) if w1 else None
            xvar = block._find_var_recursive(xn) if xn else None
            if wvar is None or xvar is None or len(wvar.shape) != 3:
                continue
            e, d = int(wvar.shape[0]), int(wvar.shape[1])
            tokens = 1
            symbolic = False
            for s in xvar.shape[:-1]:
                if int(s) < 0:
                    symbolic = True
                tokens *= max(int(s), 1)
            k_top = int(op.attr("top_k", 1) or 1)
            cf = float(op.attr("capacity_factor", 1.0) or 1.0)
            cap = max(1, int(math.ceil(tokens * k_top * cf / e)))
            try:
                np_dt = _dtypes.to_np(xvar.dtype)
                itemsize = np.dtype(np_dt).itemsize
                dt = str(np.dtype(np_dt))
            except (KeyError, ValueError, TypeError):
                continue
            total = e * cap * d * itemsize
            # Symbolic batch prices per-sample (cap collapses to ~1), so
            # the runtime divisibility test is meaningless here: trust
            # the flag and let the moe_alltoall_fallback counter record
            # whether the traced capacity actually engaged chunking.
            k = moe_chunks if (moe_chunks and moe_chunks > 1
                               and (symbolic or cap % moe_chunks == 0)) \
                else 1
            base = str(op.attr(COMM_ID_ATTR, "") or "") \
                or f"moe:{op.type}"
            for leg in ("dispatch", "combine"):
                for i in range(k):
                    entries.append({
                        "id": f"{base}:a2a_{leg}@{i}",
                        "op": "ep_alltoall",
                        "dtype": dt,
                        "bytes": total // k,
                        "overlap": i < k - 1,
                    })
            continue
        if op.type not in COLLECTIVE_OPS:
            continue
        names = op.input_arg_names()
        if not names:
            continue
        nbytes, dt = _var_bytes(names[0])
        if not nbytes:
            continue
        stack = max(int(op.attr(LAYER_STACK_ATTR, 0) or 0), 1)
        nbytes *= stack
        tp_spec = str(op.attr(TP_SPEC_ATTR, "") or "")
        if tp_spec and mp_degree > 1 and "mp" in tp_spec.split(","):
            nbytes //= mp_degree
        comm_id = str(op.attr(COMM_ID_ATTR, "") or "") \
            or f"{op.type}:{names[0]}"
        entries.append({
            "id": comm_id,
            "op": op.type,
            "dtype": dt,
            "bytes": int(nbytes),
            "overlap": bool(op.attr(COMM_OVERLAP_ATTR, False)),
        })
        saw_allreduce = True
    if not saw_allreduce and tp_plan is not None \
            and getattr(tp_plan, "grad_reduce", None):
        # implicit GSPMD dp grad reduces: no ops to walk, the plan's
        # per-grad payload table is the inventory
        for name, rec in sorted(tp_plan.grad_reduce.items()):
            b = int(rec.get("bytes", 0) or 0)
            if b:
                entries.append({"id": f"grad:{name}", "op": "gspmd_reduce",
                                "dtype": "", "bytes": b, "overlap": False})
    return entries


class PhasePlan:
    """Deterministic per-step cost model for one compiled program:
    predicted compute seconds + per-collective exposed/hidden seconds.

    The overlap model is a single hide-under-compute walk in program
    order: an overlap-stamped collective hides ``min(its transfer
    time, remaining compute budget)``; everything else (and any
    overflow) is exposed.  Inputs are all static — IR FLOPs, declared
    var bytes, two flags — so tier-1 CPU runs reproduce the same
    fractions every time (the "deterministic predicted phases" half of
    the contract; real-device refinement is the profiler capture's
    job)."""

    def __init__(self, flops_per_step: float, collectives: List[dict]):
        self.flops_per_step = float(flops_per_step or 0.0)
        self.collectives = list(collectives)
        self._recost()

    def _recost(self) -> None:
        peak = float(_flags.flag("device_peak_tflops") or 0.0) * 1e12
        bw = float(_flags.flag("phase_interconnect_gbps") or 0.0) * 1e9
        self.compute_s = (self.flops_per_step / peak) if peak > 0 else 0.0
        budget = self.compute_s
        self.comm_exposed_s = 0.0
        self.comm_hidden_s = 0.0
        self.ledger: List[dict] = []
        per_id: Dict[str, dict] = {}
        for c in self.collectives:
            t = (c["bytes"] / bw) if bw > 0 else 0.0
            if c.get("overlap"):
                hidden = min(t, budget)
                budget -= hidden
            else:
                hidden = 0.0
            exposed = t - hidden
            self.comm_exposed_s += exposed
            self.comm_hidden_s += hidden
            row = per_id.get(c["id"])
            if row is None:
                row = per_id[c["id"]] = {
                    "id": c["id"], "op": c["op"], "dtype": c["dtype"],
                    "bytes_per_step": 0, "exposed_s": 0.0, "hidden_s": 0.0,
                    "overlap": bool(c.get("overlap"))}
                self.ledger.append(row)
            row["bytes_per_step"] += int(c["bytes"])
            row["exposed_s"] += exposed
            row["hidden_s"] += hidden

    def update_flops(self, flops_per_step: float) -> None:
        """Re-cost with XLA's own FLOP count when
        ``_introspect_first_compile`` replaces the IR estimate (the
        same MFU-honesty correction, applied to the phase model)."""
        self.flops_per_step = float(flops_per_step or 0.0)
        self._recost()

    # -- reading ---------------------------------------------------------
    @property
    def predicted_step_s(self) -> float:
        return self.compute_s + self.comm_exposed_s

    def predicted_fractions(self) -> Dict[str, float]:
        total = self.predicted_step_s
        if total <= 0.0:
            return {"compute": 0.0, "comm_exposed": 0.0}
        return {"compute": self.compute_s / total,
                "comm_exposed": self.comm_exposed_s / total}

    def to_dict(self) -> Dict:
        return {
            "flops_per_step": self.flops_per_step,
            "compute_s": self.compute_s,
            "comm_exposed_s": self.comm_exposed_s,
            "comm_hidden_s": self.comm_hidden_s,
            "predicted_step_s": self.predicted_step_s,
            "predicted_fractions": self.predicted_fractions(),
            "ledger": [dict(r) for r in self.ledger],
        }


def build_phase_plan(block, op_list, mesh=None, tp_plan=None,
                     flops_per_step: float = 0.0,
                     cm_chunks: int = 0,
                     moe_chunks: int = 0) -> Optional["PhasePlan"]:
    """Build a :class:`PhasePlan` for one compiled program (called from
    ``Executor._compile``); None when attribution is off.  Never raises
    — a cost-model failure must not fail a compile."""
    if not _flags.flag("phase_attribution"):
        return None
    try:
        inv = collective_inventory(block, op_list, mesh=mesh,
                                   tp_plan=tp_plan, cm_chunks=cm_chunks,
                                   moe_chunks=moe_chunks)
        return PhasePlan(flops_per_step, inv)
    except Exception:  # noqa: BLE001 - telemetry only
        stat_add("phase_plan_errors")
        return None


# ---------------------------------------------------------------------------
# the engine: per-drain decomposition + cumulative ledger
# ---------------------------------------------------------------------------


class PhaseEngine:
    """Accumulates the four-bucket split + collective ledger across
    drained steps; one instance per process (the executor drain feeds
    the module singleton; tests may build their own)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._zero()

    def _zero(self):
        self.steps = 0
        self.totals = {b: 0.0 for b in BUCKETS}
        self.ledger: Dict[str, dict] = {}
        self.last_plan: Optional[PhasePlan] = None

    # -- feeding (executor window drain) ---------------------------------
    def on_step_drained(self, wall_s: float, sync_s: float, host_s: float,
                        steps: int = 1, plan: Optional[PhasePlan] = None,
                        compiled: bool = False) -> Optional[Dict[str, float]]:
        """Decompose one drained step's inter-drain wall time; returns
        the per-bucket seconds (None when skipped).  First-call steps
        (``compiled``) are skipped like the StepTimer's histogram — a
        trace+XLA-compile is not a phase profile.  The four buckets sum
        exactly to ``wall_s`` by construction."""
        if not _flags.flag("phase_attribution") or compiled:
            return None
        wall = max(float(wall_s), 0.0)
        host = min(max(float(host_s), 0.0), wall)
        rest = wall - host
        sync = min(max(float(sync_s), 0.0), rest)
        input_wait = rest - sync
        # the drain block is device-bound time; split it compute vs
        # exposed comm by the model's predicted ratio (all-compute when
        # the model has nothing to say — no collectives, no flags)
        comm_frac = 0.0
        if plan is not None and plan.predicted_step_s > 0.0:
            comm_frac = plan.comm_exposed_s / plan.predicted_step_s
        comm = sync * comm_frac
        compute = sync - comm
        split = {"compute": compute, "comm_exposed": comm, "host": host,
                 "input_wait": input_wait}
        with self._lock:
            self.steps += int(steps)
            for k, v in split.items():
                self.totals[k] += v
            if plan is not None:
                self.last_plan = plan
                n = max(int(steps), 1)
                for row in plan.ledger:
                    agg = self.ledger.get(row["id"])
                    if agg is None:
                        agg = self.ledger[row["id"]] = {
                            "id": row["id"], "op": row["op"],
                            "dtype": row["dtype"],
                            "bytes_per_step": row["bytes_per_step"],
                            "overlap": row["overlap"],
                            "calls": 0, "exposed_s": 0.0, "hidden_s": 0.0}
                    agg["calls"] += n
                    agg["exposed_s"] += row["exposed_s"] * n
                    agg["hidden_s"] += row["hidden_s"] * n
            self._publish_locked()
        stat_add("phase_steps_attributed", int(steps))
        return split

    def _publish_locked(self) -> None:
        wall = sum(self.totals.values())
        for b in BUCKETS:
            stat_set(f"phase_{b}_seconds_micro",
                     int(self.totals[b] * _MICRO))
            stat_set(f"phase_{b}_fraction_ppm",
                     int(self.totals[b] / wall * 1e6) if wall > 0 else 0)
        if self.last_plan is not None:
            pf = self.last_plan.predicted_fractions()
            stat_set("phase_predicted_compute_fraction_ppm",
                     int(pf["compute"] * 1e6))
            stat_set("phase_predicted_comm_fraction_ppm",
                     int(pf["comm_exposed"] * 1e6))
        exposed = sum(r["exposed_s"] for r in self.ledger.values())
        hidden = sum(r["hidden_s"] for r in self.ledger.values())
        stat_set("comm_exposed_seconds_micro", int(exposed * _MICRO))
        stat_set("comm_hidden_seconds_micro", int(hidden * _MICRO))
        total = exposed + hidden
        stat_set("comm_exposed_share_ppm",
                 int(exposed / total * 1e6) if total > 0 else 0)

    # -- reading ---------------------------------------------------------
    def report(self) -> Dict:
        """The ``phases.json`` document: measured totals + fractions,
        the latest plan's predicted split, and the cumulative
        per-collective ledger sorted by exposed seconds."""
        with self._lock:
            wall = sum(self.totals.values())
            out: Dict = {
                "steps": self.steps,
                "wall_s": round(wall, 6),
                "measured_s": {b: round(self.totals[b], 6)
                               for b in BUCKETS},
                "measured_fractions": {
                    b: round(self.totals[b] / wall, 6) if wall > 0 else 0.0
                    for b in BUCKETS},
                "ledger": sorted(
                    (dict(r) for r in self.ledger.values()),
                    key=lambda r: -r["exposed_s"]),
            }
            exposed = sum(r["exposed_s"] for r in self.ledger.values())
            hidden = sum(r["hidden_s"] for r in self.ledger.values())
            out["comm_exposed_s"] = round(exposed, 6)
            out["comm_hidden_s"] = round(hidden, 6)
            out["comm_exposed_share"] = round(
                exposed / (exposed + hidden), 6) \
                if (exposed + hidden) > 0 else 0.0
            if self.last_plan is not None:
                out["predicted"] = self.last_plan.to_dict()
        return out

    def comm_exposed_share(self) -> float:
        """Exposed fraction of all priced comm, 0..1 (the heartbeat
        field behind the cluster straggler *cause* column)."""
        with self._lock:
            exposed = sum(r["exposed_s"] for r in self.ledger.values())
            hidden = sum(r["hidden_s"] for r in self.ledger.values())
        total = exposed + hidden
        return exposed / total if total > 0 else 0.0

    def reset(self) -> None:
        with self._lock:
            self._zero()
            self._publish_locked()


_ENGINE = PhaseEngine()


def phase_engine() -> PhaseEngine:
    return _ENGINE


def on_step_drained(wall_s: float, sync_s: float, host_s: float,
                    steps: int = 1, plan: Optional[PhasePlan] = None,
                    compiled: bool = False) -> None:
    """Drain-path hook (framework/executor.py): never raises — the
    attribution plane must not be able to fail a training step."""
    try:
        _ENGINE.on_step_drained(wall_s, sync_s, host_s, steps=steps,
                                plan=plan, compiled=compiled)
    except Exception:  # noqa: BLE001 - observer only
        stat_add("phase_attribution_errors")


def phases_report() -> Dict:
    return _ENGINE.report()


def reset_phases() -> None:
    _ENGINE.reset()
