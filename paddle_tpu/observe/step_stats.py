"""Per-step telemetry: step-time distribution, throughput, MFU.

Role parity: the reference scatters this across ``STAT_ADD`` counters,
the benchmark flag's per-op timing, and out-of-tree scripts; here the
Executor feeds ONE ``StepTimer`` per process from ``_dispatch`` — every
``run``/``run_steps`` call records wall time, step count, example
count, the compiled program's static FLOPs (hapi/model_stat.py
accounting over the program IR) and allreduce payload bytes (the PR 2
fused-bucket accounting, re-derived from the post-pass op stream).

Out the other end:
- ``step_time_seconds`` histogram (p50/p95/p99 via observe/histogram,
  exported to ``/stats``, ``/metrics``, and ``export_stats()``),
- ``summary()``: examples/sec, compile-vs-execute wall split,
  allreduce bytes/step, and an **MFU estimate** =
  achieved FLOP/s ÷ ``FLAGS_device_peak_tflops`` — the single number
  that says how far from "as fast as the hardware allows" a step is.

Timing honesty: jax arrays are async, so a run's wall time is dispatch
time unless something blocks.  Under pipelined dispatch
(``FLAGS_max_inflight_steps`` > 0, the default) the Executor records
each step at its window-DRAIN point with the inter-drain wall time — in
a steady loop drains fire once per dispatch (backpressure), so the
recorded number is the training loop's true per-step period, input wait
included.  ``summary()`` drains every live Executor first so it only
reports completed steps.  ``FLAGS_benchmark`` forces an immediate drain
per call (the reference meaning of that flag); multi-step ``run_steps``
calls amortize the launch so their per-step number is accurate either
way.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from ..framework import flags as _flags
from .histogram import histogram, stat_time

__all__ = ["STEP_TIME_HISTOGRAM", "StepTimer", "step_timer",
           "reset_step_stats", "mfu_estimate"]

STEP_TIME_HISTOGRAM = "step_time_seconds"


def mfu_estimate(flops_per_step: float, step_time_s: float,
                 peak_tflops: Optional[float] = None) -> float:
    """Model FLOPs utilization: achieved / peak.  ``peak_tflops``
    defaults to ``FLAGS_device_peak_tflops``."""
    if step_time_s <= 0.0 or flops_per_step <= 0.0:
        return 0.0
    peak = peak_tflops if peak_tflops is not None \
        else float(_flags.flag("device_peak_tflops"))
    if peak <= 0.0:
        return 0.0
    return (flops_per_step / step_time_s) / (peak * 1e12)


class StepTimer:
    """Accumulates per-run telemetry; one instance per process (the
    Executor feeds the module singleton; tests may build their own)."""

    def __init__(self, hist_name: str = STEP_TIME_HISTOGRAM):
        self._lock = threading.Lock()
        self._hist_name = hist_name
        histogram(hist_name)  # pre-register: /metrics shows the (empty)
        self._zero()          # histogram before the first step runs

    def _zero(self):
        self.runs = 0
        self.steps = 0
        self.examples = 0
        self.compiles = 0
        self.compile_time = 0.0
        self.execute_time = 0.0
        self.flops = 0.0
        self.allreduce_bytes = 0

    # -- feeding (Executor._dispatch) ------------------------------------
    def record_run(self, duration_s: float, steps: int = 1,
                   examples: int = 0, compiled: bool = False,
                   flops_per_step: float = 0.0,
                   allreduce_bytes_per_step: int = 0) -> None:
        steps = max(int(steps), 1)
        with self._lock:
            self.runs += 1
            if compiled:
                # first call traces + XLA-compiles + executes: charge it
                # all to the compile side so steady-state numbers stay
                # clean (the split IS the compile-storm detector)
                self.compiles += 1
                self.compile_time += duration_s
            else:
                self.execute_time += duration_s
                self.steps += steps
                self.examples += int(examples)
                self.flops += flops_per_step * steps
                self.allreduce_bytes += int(allreduce_bytes_per_step) * steps
        if not compiled:
            stat_time(self._hist_name, duration_s / steps)

    # -- reading ---------------------------------------------------------
    def summary(self, peak_tflops: Optional[float] = None) -> Dict:
        # pipelined dispatch moves per-step accounting to window-drain
        # points: a summary is a read point, so quiesce every live
        # Executor first — the numbers then reflect completed steps
        # only.  raise_errors=False: a step failure hit here is PARKED
        # on its window and re-raised at the next raising drain point
        # (next dispatch, handle read, drain/close, ckpt snapshot) —
        # telemetry never raises, but it never eats the error either
        try:
            from ..framework.executor import drain_all as _drain_all

            _drain_all(raise_errors=False)
        except ImportError:  # pragma: no cover - partial installs
            pass
        with self._lock:
            runs, steps, examples = self.runs, self.steps, self.examples
            compiles = self.compiles
            ct, et = self.compile_time, self.execute_time
            flops, ar_bytes = self.flops, self.allreduce_bytes
        out = {
            "runs": runs,
            "steps": steps,
            "compiles": compiles,
            "compile_time_s": round(ct, 6),
            "execute_time_s": round(et, 6),
            "step_time_s": histogram(self._hist_name).summary(),
        }
        # XLA introspection (observe/xla_stats.py): the AOT-measured
        # trace+compile wall times and the newest executable's size —
        # compile_time_s above is the first-CALL wall split, this is
        # the compiler's own bill (ROADMAP item 5's acceptance metric)
        ch = histogram("compile_seconds")
        if ch.count:
            out["xla_compile_seconds"] = ch.summary()
        from ..monitor import stat_get

        size = stat_get("executable_size_bytes")
        if size:
            out["executable_size_bytes"] = size
        if et > 0.0 and steps:
            out["steps_per_sec"] = round(steps / et, 3)
            if examples:
                out["examples_per_sec"] = round(examples / et, 3)
            out["allreduce_bytes_per_step"] = ar_bytes // steps
            if flops:
                out["flops_per_step"] = int(flops / steps)
                peak = peak_tflops if peak_tflops is not None \
                    else float(_flags.flag("device_peak_tflops"))
                if peak > 0.0:
                    # significant digits, not decimal places: a toy
                    # model's 1e-6 MFU must not round to a dead zero
                    out["mfu"] = float(
                        f"{mfu_estimate(flops / steps, et / steps, peak):.4g}")
                else:
                    # FLAGS_device_peak_tflops unset/zero: there is no
                    # denominator — null, not a misleading 0.0
                    out["mfu"] = None
        return out

    def reset(self) -> None:
        with self._lock:
            self._zero()
        histogram(self._hist_name).reset()


_STEP_TIMER = StepTimer()


def step_timer() -> StepTimer:
    return _STEP_TIMER


def reset_step_stats() -> None:
    _STEP_TIMER.reset()
