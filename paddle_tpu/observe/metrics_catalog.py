"""Authoritative metrics catalog: every ``/metrics`` series documented.

The stat plane grew organically (executor counters, pass stats, serving
outcomes, SLO burn gauges, phase attribution...) and the only inventory
was grep.  This module is the registry of record: an ordered list of
prefix rules mapping a series name (namespace stripped) to its type,
unit convention, and owning subsystem.  Two consumers:

- ``METRICS.md`` is *generated* from these rules
  (``python -m paddle_tpu.observe.metrics_catalog --write``); the
  checked-in copy is a drift gate — tier-1 fails when the file and the
  rules disagree.
- ``tests/test_metrics_catalog.py`` scrapes a clean-process
  ``prometheus_text()`` and asserts every exported series matches a
  rule, so a PR adding a stat without a catalog row fails loudly.

Units are suffix-encoded by convention (the registry stores ints only,
PR 4): ``_seconds`` (histogram, float seconds), ``_seconds_micro``
(gauge, integer microseconds), ``_bytes``, ``_ppm`` (parts-per-million
of a ratio), ``_ms``, ``_rps``; bare names are event/object counts.
``unit_of`` resolves a concrete name's unit from its suffix.

Matching is first-rule-wins over the authoring order below, with exact
rules (``exact=True``) checked as whole-name equality and prefix rules
as ``startswith``.
"""
from __future__ import annotations

import sys
from typing import List, NamedTuple, Optional

__all__ = ["Rule", "RULES", "lookup", "unit_of", "catalog_markdown",
           "check_file", "write_file", "main"]


class Rule(NamedTuple):
    prefix: str       # name prefix (or whole name when exact=True)
    type: str         # "gauge" | "histogram" (counters export as gauges)
    subsystem: str    # owning module / plane
    description: str  # one line: what the family measures
    exact: bool = False


# Ordered: exact histogram names first (several share a prefix with
# gauge families), then gauge/counter families grouped by subsystem.
RULES = (
    # -- latency histograms (HistogramRegistry, stat_time) ---------------
    Rule("step_time_seconds", "histogram", "step_stats",
         "Per-step wall time distribution (drained, post-compile)",
         exact=True),
    Rule("compile_seconds", "histogram", "xla_stats",
         "Program compile wall time per cache-miss", exact=True),
    Rule("xla_compile_seconds", "histogram", "xla_stats",
         "XLA-side compile time where introspection exposes it",
         exact=True),
    Rule("input_wait_seconds", "histogram", "io",
         "Executor blocked waiting on the input pipeline", exact=True),
    Rule("fetch_sync_seconds", "histogram", "io",
         "Host-blocking fetch/device-sync sections", exact=True),
    Rule("ckpt_save_blocking_seconds", "histogram", "checkpoint",
         "Train-loop time blocked by a checkpoint save", exact=True),
    Rule("ckpt_write_seconds", "histogram", "checkpoint",
         "Checkpoint shard write+fsync time", exact=True),
    Rule("serving_latency_seconds", "histogram", "serving",
         "End-to-end serving request latency", exact=True),
    Rule("decode_request_latency_seconds", "histogram", "serving",
         "Decode-engine request latency (submit to terminal)",
         exact=True),
    Rule("decode_prefill_seconds", "histogram", "serving",
         "Prefill dispatch time per request/chunk", exact=True),
    Rule("decode_step_seconds", "histogram", "serving",
         "One batched decode step", exact=True),
    Rule("ttft_seconds", "histogram", "slo",
         "Time to first token (SLO input)", exact=True),
    Rule("tpot_seconds", "histogram", "slo",
         "Time per output token (SLO input)", exact=True),
    Rule("emb_lookup_seconds", "histogram", "embedding",
         "Sharded-embedding lookup (gather+alltoall)", exact=True),
    Rule("migrate_seconds", "histogram", "disagg",
         "One KV-page migration install (gather->scatter)",
         exact=True),
    # -- executor / compile plane ---------------------------------------
    Rule("executor_", "gauge", "executor",
         "Dispatch/drain/cache counters of the Executor hot path"),
    Rule("executable_", "gauge", "xla_stats",
         "Compiled-executable size and HLO op counts"),
    Rule("remat_", "gauge", "executor",
         "Rematerialization policy availability/fallbacks"),
    Rule("mfu_", "gauge", "step_stats",
         "Model-FLOPs-utilization estimate bookkeeping"),
    Rule("h2d_", "gauge", "io",
         "Host-to-device transfer bytes (feed path)"),
    # -- graph passes / parallelism -------------------------------------
    Rule("pass_", "gauge", "passes",
         "Graph-pass effect counters (fusion, scan, DCE, quant, TP)"),
    Rule("pipeline_", "gauge", "pipeline",
         "Pipeline-parallel scan/segment counters"),
    Rule("pp_", "gauge", "pipeline",
         "Pipeline-parallel schedule stats (stages, bubble fraction)"),
    Rule("tp_", "gauge", "tensor_parallel",
         "Tensor-parallel constraint/fallback counters"),
    Rule("collective_matmul_", "gauge", "tensor_parallel",
         "Collective-matmul chunking engagement/fallbacks"),
    Rule("ep_", "gauge", "expert_parallel",
         "Expert-parallel ('ep' axis) mesh/plan bookkeeping"),
    Rule("moe_", "gauge", "expert_parallel",
         "Mixture-of-experts routing: expert balance and drop "
         "fractions (ppm), routed-FFN engagement, all-to-all "
         "chunking engagement/fallbacks"),
    Rule("flash_attention_", "gauge", "kernels",
         "Flash-attention kernel engagement"),
    Rule("quant_", "gauge", "quantization",
         "Quantization engagement and quality deltas"),
    # -- phase attribution / profiling (this PR) ------------------------
    Rule("phase_", "gauge", "phases",
         "Step-phase attribution: per-bucket seconds/fractions and "
         "predicted compute/comm split"),
    Rule("comm_", "gauge", "phases",
         "Collective ledger: exposed vs hidden communication time"),
    Rule("prof_", "gauge", "profiler_capture",
         "Anomaly-triggered / continuous profiler capture counters"),
    # -- observability plane --------------------------------------------
    Rule("flight_", "gauge", "flight",
         "Flight-recorder sink bookkeeping (rotations)"),
    Rule("watchdog_", "gauge", "health",
         "Stall-watchdog trips"),
    Rule("postmortem_", "gauge", "health",
         "Postmortem bundles written"),
    Rule("health_", "gauge", "health",
         "Heartbeat delivery failures/blackholes"),
    Rule("cluster_", "gauge", "health",
         "Rank-0 aggregated cluster health (skew, stragglers, HBM)"),
    Rule("hbm_", "gauge", "xla_stats",
         "HBM budget gate and live device memory"),
    Rule("xla_", "gauge", "xla_stats",
         "XLA introspection availability/fallback counters"),
    Rule("slo_", "gauge", "slo",
         "SLO burn rates and remaining error budget per objective"),
    Rule("request_trace", "gauge", "request_trace",
         "Per-request trace store occupancy/retention"),
    # -- training-side subsystems ---------------------------------------
    Rule("ckpt_", "gauge", "checkpoint",
         "Checkpoint save/restore/GC outcomes and bytes"),
    Rule("elastic_", "gauge", "elastic",
         "Elastic restart/reshard lifecycle counters"),
    Rule("chaos_", "gauge", "elastic",
         "Chaos fault injection arming/firing"),
    Rule("emb_", "gauge", "embedding",
         "Sharded-embedding traffic and placement stats"),
    # -- serving ---------------------------------------------------------
    Rule("decode_", "gauge", "serving",
         "Decode-engine lifecycle, paging, speculation, goodput"),
    Rule("serving_", "gauge", "serving",
         "Batching server lifecycle and queue occupancy"),
    Rule("prefill_", "gauge", "serving",
         "Chunked-prefill padding/live token accounting"),
    Rule("spec_", "gauge", "serving",
         "Speculative-decoding acceptance rates"),
    # -- disaggregated serving (serving/disagg.py) ------------------------
    Rule("migrate_", "gauge", "disagg",
         "KV-page migration traffic (pages/bytes, device vs "
         "host-bounce transport)"),
    Rule("disagg_", "gauge", "disagg",
         "Disagg router lifecycle: handoffs, re-dispatches, replica "
         "deaths, role-set sizes"),
    Rule("autoscale_", "gauge", "disagg",
         "SLO-driven re-roling: re-roles, cooldown skips, preflight "
         "failures, observed burn/queue signals"),
)

_UNIT_SUFFIXES = (
    ("_seconds_micro", "microseconds (int)"),
    ("_us_total", "microseconds (int)"),
    ("_seconds", "seconds"),
    ("_bytes", "bytes"),
    ("_ppm", "parts-per-million"),
    ("_micro", "micro-units (int, value x 1e6)"),
    ("_ms", "milliseconds"),
    ("_rps", "requests/second"),
)


def unit_of(name: str) -> str:
    """Unit of a concrete series name by suffix convention."""
    for suffix, unit in _UNIT_SUFFIXES:
        if name.endswith(suffix):
            return unit
    return "count"


def lookup(name: str) -> Optional[Rule]:
    """First rule matching ``name`` (namespace already stripped), or
    None — an undocumented series."""
    for r in RULES:
        if (name == r.prefix) if r.exact else name.startswith(r.prefix):
            return r
    return None


def catalog_markdown() -> str:
    """Deterministic METRICS.md body rendered from ``RULES``."""
    lines = [
        "# Metrics catalog",
        "",
        "Generated by `python -m paddle_tpu.observe.metrics_catalog "
        "--write` — do not edit by hand; tier-1 "
        "(`tests/test_metrics_catalog.py`) fails on drift and on any "
        "`/metrics` series without a row here.",
        "",
        "Series are exported under the `paddle_tpu_` namespace. "
        "`Match` is a name prefix unless marked `(exact)`. Units are "
        "suffix-encoded per name: `_seconds` (float, histograms), "
        "`_seconds_micro`/`_micro` (integer micro-units), `_bytes`, "
        "`_ppm` (parts-per-million), `_rps`; bare names are counts. "
        "Counters export with Prometheus type `gauge` because the "
        "registry is resettable.",
        "",
        "| Match | Type | Subsystem | Description |",
        "|---|---|---|---|",
    ]
    for r in RULES:
        match = f"`{r.prefix}`" + (" (exact)" if r.exact else "*")
        lines.append(
            f"| {match} | {r.type} | {r.subsystem} | {r.description} |")
    return "\n".join(lines) + "\n"


def write_file(path: str) -> str:
    with open(path, "w") as f:
        f.write(catalog_markdown())
    return path


def check_file(path: str) -> bool:
    """True when the checked-in catalog matches the rules."""
    try:
        with open(path) as f:
            return f.read() == catalog_markdown()
    except OSError:
        return False


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import os

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observe.metrics_catalog",
        description="Generate/verify METRICS.md from the catalog rules")
    p.add_argument("--write", action="store_true",
                   help="(re)write METRICS.md")
    p.add_argument("--check", action="store_true",
                   help="exit 1 when METRICS.md drifted from the rules")
    p.add_argument("--path", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "METRICS.md"))
    args = p.parse_args(argv)
    if args.write:
        print(write_file(args.path))
        return 0
    if args.check:
        if check_file(args.path):
            print("METRICS.md: up to date")
            return 0
        print("METRICS.md: DRIFTED — regenerate with --write",
              file=sys.stderr)
        return 1
    print(catalog_markdown(), end="")
    return 0


if __name__ == "__main__":  # pragma: no cover - CLI
    sys.exit(main())
