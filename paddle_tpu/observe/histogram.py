"""Log-bucketed latency histograms + Prometheus text exposition.

Role parity: the reference's ``StatRegistry`` (platform/monitor.h:77)
holds int64 counters only — no notion of a latency *distribution*, which
is the metric that matters for tail-sensitive serving ("p99 under
heavy traffic", ROADMAP north star).  This module adds the missing
half: ``stat_time(name, seconds)`` feeds a process-wide, thread-safe
histogram with power-of-two buckets from 1µs to ~67s, and the whole
registry (counters + histograms) renders as Prometheus text-exposition
format for the fleet KV HTTP server's ``/metrics`` route.

Quantiles are bucket-interpolated (the classic Prometheus
``histogram_quantile`` estimate): exact enough to steer optimization,
cheap enough to leave on in production.  The true maximum is tracked
exactly.
"""
from __future__ import annotations

import math
import re
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["BUCKET_BOUNDS", "Histogram", "HistogramRegistry", "histogram",
           "stat_time", "export_histograms", "histogram_summaries",
           "prometheus_text"]

# power-of-two bounds 1µs .. ~67s (27 finite buckets + the +Inf bucket);
# log-spaced so one grid serves µs-scale collectives and minute-scale
# compiles with constant relative error
BUCKET_BOUNDS: Tuple[float, ...] = tuple(1e-6 * 2 ** i for i in range(27))


class Histogram:
    """Thread-safe log-bucketed histogram of nonnegative seconds."""

    __slots__ = ("name", "_counts", "_count", "_sum", "_max", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._counts = [0] * (len(BUCKET_BOUNDS) + 1)  # +1: the +Inf bucket
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    @staticmethod
    def _bucket_index(value: float) -> int:
        if value <= BUCKET_BOUNDS[0]:
            return 0
        if value > BUCKET_BOUNDS[-1]:
            return len(BUCKET_BOUNDS)
        # buckets are exact powers of two of 1e-6: index via log2
        return int(math.ceil(math.log2(value / 1e-6)))

    def observe(self, value: float) -> None:
        value = float(value)
        if value < 0.0 or value != value:  # negative / NaN: drop, never raise
            return
        i = self._bucket_index(value)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += value
            if value > self._max:
                self._max = value

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(BUCKET_BOUNDS) + 1)
            self._count = 0
            self._sum = 0.0
            self._max = 0.0

    # -- reading ---------------------------------------------------------
    def _snap(self):
        with self._lock:
            return list(self._counts), self._count, self._sum, self._max

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def max(self) -> float:
        with self._lock:
            return self._max

    def percentile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate, clamped to the exact
        max (so p100-ish asks never report a bucket bound above the
        largest value ever seen).  ``q`` in [0, 100]."""
        counts, count, _sum, mx = self._snap()
        if count == 0:
            return 0.0
        rank = q / 100.0 * count
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = BUCKET_BOUNDS[i - 1] if 0 < i <= len(BUCKET_BOUNDS) \
                    else 0.0
                hi = BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS) else mx
                frac = (rank - cum) / c
                return min(lo + (max(hi, lo) - lo) * frac, mx)
            cum += c
        return mx

    def summary(self) -> Dict[str, float]:
        counts, count, total, mx = self._snap()
        out = {"count": count, "sum": round(total, 6)}
        if count:
            out.update(
                mean=round(total / count, 6),
                p50=round(self.percentile(50), 6),
                p95=round(self.percentile(95), 6),
                p99=round(self.percentile(99), 6),
                max=round(mx, 6),
            )
        return out

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """Prometheus-style (le_upper_bound, cumulative_count) rows,
        ending with (+inf, total)."""
        counts, count, _sum, _mx = self._snap()
        rows, cum = [], 0
        for bound, c in zip(BUCKET_BOUNDS, counts):
            cum += c
            rows.append((bound, cum))
        rows.append((math.inf, count))
        return rows


class HistogramRegistry:
    """Process-wide singleton, same shape as monitor.StatRegistry."""

    _instance: "HistogramRegistry" = None  # type: ignore[assignment]
    _instance_lock = threading.Lock()

    def __init__(self):
        self._hists: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "HistogramRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name)
            return h

    def export(self) -> List[Tuple[str, Histogram]]:
        with self._lock:
            return sorted(self._hists.items())

    def reset(self, name: Optional[str] = None) -> None:
        if name is not None:
            self.histogram(name).reset()
            return
        with self._lock:
            hists = list(self._hists.values())
        for h in hists:
            h.reset()


def histogram(name: str) -> Histogram:
    return HistogramRegistry.instance().histogram(name)


def stat_time(name: str, seconds: float) -> None:
    """Record one latency observation (the timing sibling of
    ``monitor.stat_add``).  Name by unit: ``*_seconds``."""
    HistogramRegistry.instance().histogram(name).observe(seconds)


def export_histograms() -> Dict[str, Dict[str, float]]:
    return {n: h.summary()
            for n, h in HistogramRegistry.instance().export()}


def histogram_summaries() -> List[Tuple[str, float]]:
    """Flattened (``<name>_<stat>``, value) rows for
    ``monitor.export_stats()`` — quantiles ride the same snapshot the
    counters do, so ``/stats`` and user dashboards get p50/p95/p99
    without a second API."""
    rows: List[Tuple[str, float]] = []
    for name, h in HistogramRegistry.instance().export():
        for k, v in h.summary().items():
            rows.append((f"{name}_{k}", v))
    return rows


# ---------------------------------------------------------------------------
# Prometheus text exposition (the serving/fleet /metrics route)
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str, namespace: str) -> str:
    n = _NAME_RE.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return f"{namespace}_{n}"


def _fmt(v) -> str:
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def prometheus_text(namespace: str = "paddle_tpu") -> str:
    """Render every StatRegistry counter (as a gauge: our counters can
    be reset) and every histogram (as a real cumulative-bucket
    histogram) in Prometheus/OpenMetrics text-exposition format v0.0.4.

    Served by the fleet KV HTTP server's ``/metrics`` route:
    ``curl :port/metrics | promtool check metrics`` parses clean.
    """
    from ..monitor import StatRegistry

    lines: List[str] = []
    for name, value in StatRegistry.instance().export():
        m = _metric_name(name, namespace)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {_fmt(value)}")
    for name, h in HistogramRegistry.instance().export():
        m = _metric_name(name, namespace)
        lines.append(f"# TYPE {m} histogram")
        for bound, cum in h.cumulative_buckets():
            lines.append(f'{m}_bucket{{le="{_fmt(bound)}"}} {cum}')
        lines.append(f"{m}_sum {_fmt(h.sum)}")
        lines.append(f"{m}_count {h.count}")
    return "\n".join(lines) + "\n"
