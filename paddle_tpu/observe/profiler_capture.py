"""Anomaly-triggered + continuous ``jax.profiler`` capture.

The phase-attribution engine (``observe/phases.py``) says where a step
went on *average*; this module answers "what happened at 03:12 when
p99 doubled" — automatically, with the evidence already on disk when a
human looks.

- **Anomaly trigger** (``FLAGS_prof_trigger_ratio``): every drained
  step's wall time feeds a rolling-median baseline; a step exceeding
  ``ratio x baseline`` — or any ``slo_burn_rate_*_ppm`` gauge past its
  budget (PR 12) — fires ONE bounded capture: a ``jax.profiler`` trace
  window of at most ``FLAGS_prof_capture_s`` seconds plus a phase
  snapshot, dumped as a postmortem bundle (``phases.json`` section,
  rendered by ``python -m tools.postmortem``).  The trigger then
  latches until the step time drops back under the threshold, and a
  ``FLAGS_prof_cooldown_s`` quiet period follows every capture, so one
  episode produces one bundle, not one per step — and the capture's
  own overhead can never re-trigger it.
- **Continuous mode** (``FLAGS_prof_continuous_s``): a daemon thread
  captures one bounded window every N seconds (duty cycle
  ``capture_s / continuous_s``) into a 2-deep rotating directory set —
  the always-on-fleet profiling mode, without bundles.

Capability-guarded like the AOT stages: ``jax_compat.profiler_start``
probes the installed jax, a backend that cannot trace counts
``prof_trace_unavailable`` and the phase snapshot still lands.  Trace
directories are summarized best-effort (file count/bytes + event count
where the chrome-trace JSON is readable) — parsing failures degrade to
the raw listing, never to a lost capture.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional

from ..framework import flags as _flags
from ..monitor import stat_add, stat_set

__all__ = ["CaptureEngine", "capture_engine", "on_step_drained",
           "maybe_start_continuous", "stop_continuous", "parse_trace_dir",
           "reset_capture"]

BASELINE_WINDOW = 64   # rolling step-time samples behind the median
BASELINE_WARMUP = 8    # steps before the trigger may fire


def _burning_slo() -> Optional[str]:
    """Name of the first SLO objective burning past budget
    (``slo_burn_rate_<name>_ppm`` > 1e6), or None."""
    from ..monitor import StatRegistry

    for name, value in StatRegistry.instance().export():
        if name.startswith("slo_burn_rate_") and name.endswith("_ppm") \
                and value > 1_000_000:
            return name
    return None


def parse_trace_dir(directory: str) -> Dict:
    """Best-effort summary of a ``jax.profiler`` trace directory:
    file count + total bytes always; trace-event count where a
    ``*.trace.json(.gz)`` is present and parseable (the CPU backend's
    host-only traces are; some TPU runtimes emit only protobufs —
    those still count as captured files)."""
    out: Dict = {"dir": directory, "files": 0, "bytes": 0}
    try:
        paths: List[str] = []
        for root, _dirs, files in os.walk(directory):
            for f in files:
                paths.append(os.path.join(root, f))
        out["files"] = len(paths)
        out["bytes"] = sum(os.path.getsize(p) for p in paths)
        events = 0
        for p in paths:
            if p.endswith(".trace.json.gz") or p.endswith(".trace.json"):
                try:
                    if p.endswith(".gz"):
                        import gzip

                        with gzip.open(p, "rt") as f:
                            doc = json.load(f)
                    else:
                        with open(p) as f:
                            doc = json.load(f)
                    events += len(doc.get("traceEvents", []))
                except Exception:  # noqa: BLE001 - summary only
                    continue
        if events:
            out["trace_events"] = events
    except OSError:
        pass
    return out


class CaptureEngine:
    """Rolling baseline + latched anomaly capture + continuous mode;
    one instance per process (the executor drain feeds the module
    singleton)."""

    def __init__(self, window: int = BASELINE_WINDOW,
                 warmup: int = BASELINE_WARMUP):
        self._lock = threading.Lock()
        self._samples = collections.deque(maxlen=int(window))
        self.warmup = int(warmup)
        self._latched = False
        self._last_burn_check = 0.0
        self._burning = False
        self._last_capture_t = 0.0
        self._capture_thread: Optional[threading.Thread] = None
        self._continuous_thread: Optional[threading.Thread] = None
        self._continuous_stop = threading.Event()
        self.captures = 0
        self.bundles: List[str] = []

    # -- baseline + trigger (executor drain path) ------------------------
    def _baseline(self) -> float:
        s = sorted(self._samples)
        return s[len(s) // 2] if s else 0.0

    def on_step(self, wall_s: float, compiled: bool = False) -> None:
        """Feed one drained step; fires at most one capture per
        anomaly episode.  First-call (compile) steps never feed the
        baseline — a compile is not a regression."""
        ratio = float(_flags.flag("prof_trigger_ratio") or 0.0)
        if ratio <= 0.0 or compiled:
            return
        wall = max(float(wall_s), 0.0)
        # the SLO-burn probe walks the stat registry: throttle it to
        # ~1/s so the trigger path stays amortized-free per step
        now = time.monotonic()
        burn = None
        if now - self._last_burn_check >= 1.0:
            self._last_burn_check = now
            burn = _burning_slo()
            self._burning = burn is not None
        fire: Optional[str] = None
        cooldown = float(_flags.flag("prof_cooldown_s") or 0.0)
        with self._lock:
            base = self._baseline()
            armed = len(self._samples) >= self.warmup
            spiking = armed and base > 0.0 and wall > ratio * base
            if not spiking:
                # a spiking step never joins the baseline: the anomaly
                # must not drag its own detector upward
                self._samples.append(wall)
            capturing = self._capture_thread is not None \
                and self._capture_thread.is_alive()
            cooling = now - self._last_capture_t < cooldown \
                and self._last_capture_t > 0.0
            if (spiking or burn is not None) and not self._latched \
                    and not capturing and not cooling:
                self._latched = True
                self._last_capture_t = now
                fire = (f"step_time {wall * 1e3:.1f}ms > {ratio:g}x "
                        f"baseline {base * 1e3:.1f}ms") if spiking \
                    else f"slo_burn {burn}"
            elif self._latched and not spiking and not self._burning:
                self._latched = False  # episode over: re-arm
        if fire is not None:
            self._start_capture(fire)

    # -- one bounded capture ---------------------------------------------
    def _start_capture(self, trigger: str) -> None:
        stat_add("prof_captures_triggered")
        t = threading.Thread(target=self._capture, args=(trigger,),
                             name="prof-capture", daemon=True)
        with self._lock:
            self._capture_thread = t
        t.start()

    def _capture(self, trigger: str) -> None:
        from ..framework import jax_compat
        from . import flight as _flight
        from . import health as _health

        capture_s = max(float(_flags.flag("prof_capture_s") or 0.0), 0.0)
        base = _flags.flag("postmortem_dir") or "postmortem"
        trace_dir = os.path.join(
            str(base), f"prof_{time.strftime('%Y%m%d_%H%M%S')}_"
                       f"{os.getpid()}")
        started = False
        try:
            os.makedirs(trace_dir, exist_ok=True)
            started = jax_compat.profiler_start(trace_dir)
        except OSError:
            pass
        if not started:
            stat_add("prof_trace_unavailable")
        _flight.record("prof/capture_start", trigger=trigger,
                       trace=started, capture_s=capture_s)
        if started:
            # the bound: stop no matter what after capture_s
            time.sleep(capture_s)
            jax_compat.profiler_stop()
        profiler = parse_trace_dir(trace_dir) if started else \
            {"unavailable": True}
        try:
            bundle = _health.dump_postmortem(
                "step_time_anomaly",
                extra={"trigger": trigger, "profiler": profiler,
                       "prof_capture_s": capture_s})
        except Exception:  # noqa: BLE001 - capture must not kill callers
            bundle = None
        with self._lock:
            self.captures += 1
            if bundle:
                self.bundles.append(bundle)
        stat_add("prof_captures")
        stat_set("prof_capture_latched", 1)
        _flight.record("prof/capture_done", trigger=trigger,
                       bundle=bundle or "")

    def wait(self, timeout: float = 30.0) -> bool:
        """Join the in-flight capture thread (tests/bench); returns
        whether it finished."""
        with self._lock:
            t = self._capture_thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    # -- continuous low-duty-cycle mode ----------------------------------
    def start_continuous(self) -> bool:
        """Start the continuous-profiling daemon when
        ``FLAGS_prof_continuous_s`` > 0; idempotent."""
        period = float(_flags.flag("prof_continuous_s") or 0.0)
        if period <= 0.0:
            return False
        with self._lock:
            if self._continuous_thread is not None \
                    and self._continuous_thread.is_alive():
                return True
            self._continuous_stop.clear()
            self._continuous_thread = threading.Thread(
                target=self._continuous_loop, args=(period,),
                name="prof-continuous", daemon=True)
            self._continuous_thread.start()
        return True

    def _continuous_loop(self, period: float) -> None:
        from ..framework import jax_compat
        from . import flight as _flight

        base = _flags.flag("postmortem_dir") or "postmortem"
        root = os.path.join(str(base), "prof_continuous")
        n = 0
        while not self._continuous_stop.wait(period):
            capture_s = max(float(_flags.flag("prof_capture_s") or 0.0),
                            0.0)
            # 2-deep rotation: slot index alternates, so disk usage is
            # bounded at two windows no matter how long the fleet runs
            trace_dir = os.path.join(root, f"window_{n % 2}")
            n += 1
            try:
                import shutil

                shutil.rmtree(trace_dir, ignore_errors=True)
                os.makedirs(trace_dir, exist_ok=True)
            except OSError:
                continue
            if not jax_compat.profiler_start(trace_dir):
                stat_add("prof_trace_unavailable")
                continue
            time.sleep(capture_s)
            jax_compat.profiler_stop()
            stat_add("prof_continuous_captures")
            _flight.record("prof/continuous_window",
                           **parse_trace_dir(trace_dir))

    def stop_continuous(self) -> None:
        self._continuous_stop.set()
        with self._lock:
            t, self._continuous_thread = self._continuous_thread, None
        if t is not None:
            t.join(timeout=5)

    def reset(self) -> None:
        self.stop_continuous()
        self.wait(timeout=5)
        with self._lock:
            self._samples.clear()
            self._latched = False
            self._last_burn_check = 0.0
            self._burning = False
            self._last_capture_t = 0.0
            self.captures = 0
            self.bundles = []
        stat_set("prof_capture_latched", 0)


_ENGINE = CaptureEngine()


def capture_engine() -> CaptureEngine:
    return _ENGINE


def on_step_drained(wall_s: float, compiled: bool = False) -> None:
    """Drain-path hook (framework/executor.py): never raises."""
    try:
        _ENGINE.on_step(wall_s, compiled=compiled)
    except Exception:  # noqa: BLE001 - observer only
        stat_add("prof_trigger_errors")


def maybe_start_continuous() -> bool:
    """Auto-start hook (Executor construction): the continuous daemon
    when ``FLAGS_prof_continuous_s`` > 0, else nothing."""
    try:
        return _ENGINE.start_continuous()
    except Exception:  # noqa: BLE001
        return False


def stop_continuous() -> None:
    _ENGINE.stop_continuous()


def reset_capture() -> None:
    _ENGINE.reset()
