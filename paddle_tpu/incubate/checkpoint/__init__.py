from . import auto_checkpoint  # noqa: F401
