"""Auto-checkpoint: periodic save from Executor.run with deterministic
resume.

Role parity: reference fluid/incubate/checkpoint/auto_checkpoint.py:71
(`AutoCheckpointChecker`, `train_epoch_range`, the `_auto_checkpoint`
hook in Executor.run at executor.py:1200).  TPU-native: checkpoints ride
:class:`paddle_tpu.ckpt.CheckpointManager` — the save is asynchronous
(training continues while the writer thread serializes), commits are
atomic with a SHA-256 manifest, old snapshots are retention-GC'd, and
resume restores the FULL scope (parameters, optimizer slots, AMP
loss-scale counters, the RNG key) plus the epoch/step counters, so a
restarted job is bitwise a continuation of the crashed one.  Rank 0
writes on multi-process runs (the fresh-process resume parity test is
the oracle).

Enable via env (reference contract) or explicitly::

    PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT \
    PADDLE_EDL_HDFS_CHECKPOINT_PATH=/ckpt/dir  python train.py

    # or
    auto_checkpoint.configure(dir, save_interval_s=10)
    for epoch in auto_checkpoint.train_epoch_range("job1", 10):
        exe.run(...)   # saves on the configured cadence, resumes on boot
"""
from __future__ import annotations

import os
import time
from typing import Optional

_cfg = None


class _Config:
    def __init__(self, dirname, save_interval_s=10.0, every_n_steps=None,
                 async_save=None, keep_n=None):
        self.dirname = dirname
        self.save_interval_s = save_interval_s
        self.every_n_steps = every_n_steps
        self.async_save = async_save
        self.keep_n = keep_n
        self.last_save = 0.0
        self.step = 0
        self.epoch_state = {}
        self.resume_attempted = False
        self.manager = None


def _env_config() -> Optional[_Config]:
    if os.environ.get("PADDLE_RUNNING_ENV") != "PADDLE_EDL_AUTO_CHECKPOINT":
        return None
    path = os.environ.get("PADDLE_EDL_HDFS_CHECKPOINT_PATH")
    if not path:
        return None
    interval = float(os.environ.get("PADDLE_EDL_SAVE_CHECKPOINT_INTER", "10"))
    return _Config(path, save_interval_s=interval)


def configure(dirname, save_interval_s=10.0, every_n_steps=None,
              async_save=None, keep_n=None):
    """Programmatic enable (tests / single scripts).  ``async_save`` /
    ``keep_n`` default from ``FLAGS_ckpt_async_save`` /
    ``FLAGS_ckpt_keep_n``."""
    global _cfg
    _cfg = _Config(dirname, save_interval_s, every_n_steps,
                   async_save=async_save, keep_n=keep_n)
    return _cfg


def disable():
    # detach FIRST: close() re-raises a failed background save, and a
    # config left active with a closed manager would crash every
    # subsequent Executor.run instead of having auto-checkpoint off
    global _cfg
    cfg, _cfg = _cfg, None
    if cfg is not None and cfg.manager is not None:
        cfg.manager.close()


def _active() -> Optional[_Config]:
    global _cfg
    if _cfg is None:
        _cfg = _env_config()
    return _cfg


def _is_rank0() -> bool:
    # the one rank convention (PADDLE_TRAINER_ID, else jax process
    # index): a pure jax multi-process run never sets the env var, and
    # treating every such process as rank 0 would race all of them on
    # the same step_<N>.tmp directory
    from ...distributed.parallel_env import get_rank

    return get_rank() == 0


def _ckpt_dir(cfg):
    return os.path.join(cfg.dirname, "auto_ckpt")


def _manager(cfg):
    if cfg.manager is None:
        from ...ckpt import CheckpointManager

        # Only rank 0 ever saves (the on_executor_run gate), so the
        # snapshot is rank-0-local: force rank=0/world_size=1 instead of
        # letting the manager infer world_size=jax.process_count().  An
        # inferred world>1 would make the lone writer wait forever on
        # sync_global_devices barriers no other rank calls, and the
        # manifest would require shard_r1..r{k} files nobody writes.
        cfg.manager = CheckpointManager(
            _ckpt_dir(cfg), keep_n=cfg.keep_n, async_save=cfg.async_save,
            rank=0, world_size=1)
    return cfg.manager


def wait(cfg=None):
    """Drain the pending async save (test/shutdown barrier)."""
    cfg = cfg or _active()
    if cfg is not None and cfg.manager is not None:
        cfg.manager.wait()


def save_checkpoint(exe, program, scope, cfg=None):
    """Snapshot the FULL scope + counters through the manager (the
    reference save_checkpoint saved persistables only and lost the RNG
    on anything but rank 0's format)."""
    from ...framework.scope import global_scope

    cfg = cfg or _active()
    scope = scope or global_scope()
    _manager(cfg).save(cfg.step, scope=scope,
                       host_state={"epoch_state": cfg.epoch_state,
                                   "time": time.time()})


def load_checkpoint(exe, program, scope, cfg=None) -> Optional[dict]:
    """Restore the newest intact snapshot; returns a meta dict with
    ``step``/``epoch_state`` or None when nothing was ever committed."""
    from ...framework.scope import global_scope

    cfg = cfg or _active()
    scope = scope or global_scope()
    if not os.path.isdir(_ckpt_dir(cfg)):
        return None
    meta = _manager(cfg).restore(scope=scope)
    if meta is None:
        return None
    host = meta.get("host_state", {}) or {}
    cfg.step = int(meta["step"])
    cfg.epoch_state = dict(host.get("epoch_state", {}))
    return {"step": cfg.step, "epoch_state": cfg.epoch_state,
            "time": host.get("time")}


def on_executor_run(exe, program, scope, fed=True):
    """The Executor.run hook (reference executor.py:1200): counts steps
    and saves on the configured cadence from rank 0.  Only fed runs count
    as steps — startup/init programs carry no feeds."""
    cfg = _active()
    if cfg is None or not _is_rank0() or not fed:
        return
    cfg.step += 1
    due = False
    if cfg.every_n_steps:
        due = cfg.step % cfg.every_n_steps == 0
    else:
        due = (time.time() - cfg.last_save) >= cfg.save_interval_s
    if due:
        save_checkpoint(exe, program, scope, cfg)
        cfg.last_save = time.time()


def maybe_resume(exe, program, scope, fed=True):
    """Pre-run hook: on a restarted job, restore the previous snapshot
    BEFORE the first counted step executes (the env-mode resume contract;
    reference AutoCheckpointChecker restores epoch ranges the same way)."""
    cfg = _active()
    if cfg is None or not fed or cfg.resume_attempted:
        return
    cfg.resume_attempted = True
    load_checkpoint(exe, program, scope, cfg)


class train_epoch_range:
    """Reference `acp.train_epoch_range(name, max_epoch)`: iterate epochs,
    skipping the ones a restored checkpoint already finished."""

    def __init__(self, name, max_epoch_num):
        self.name = name
        self.max = max_epoch_num

    def __iter__(self):
        cfg = _active()
        start = 0
        if cfg is not None:
            start = int(cfg.epoch_state.get(self.name, 0))
        for e in range(start, self.max):
            yield e
            if cfg is not None:
                cfg.epoch_state[self.name] = e + 1
