"""Auto-checkpoint: periodic save from Executor.run with deterministic
resume.

Role parity: reference fluid/incubate/checkpoint/auto_checkpoint.py:71
(`AutoCheckpointChecker`, `train_epoch_range`, the `_auto_checkpoint`
hook in Executor.run at executor.py:1200).  TPU-native simplifications:
checkpoints go through the existing var_io format (the fresh-process
resume parity test is the oracle), the RNG key and an epoch/step counter
are saved alongside the persistables, and the rank-0 process writes on
multi-process runs.

Enable via env (reference contract) or explicitly::

    PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT \
    PADDLE_EDL_HDFS_CHECKPOINT_PATH=/ckpt/dir  python train.py

    # or
    auto_checkpoint.configure(dir, save_interval_s=10)
    for epoch in auto_checkpoint.train_epoch_range("job1", 10):
        exe.run(...)   # saves on the configured cadence, resumes on boot
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

_cfg = None


class _Config:
    def __init__(self, dirname, save_interval_s=10.0, every_n_steps=None):
        self.dirname = dirname
        self.save_interval_s = save_interval_s
        self.every_n_steps = every_n_steps
        self.last_save = 0.0
        self.step = 0
        self.epoch_state = {}
        self.resume_attempted = False


def _env_config() -> Optional[_Config]:
    if os.environ.get("PADDLE_RUNNING_ENV") != "PADDLE_EDL_AUTO_CHECKPOINT":
        return None
    path = os.environ.get("PADDLE_EDL_HDFS_CHECKPOINT_PATH")
    if not path:
        return None
    interval = float(os.environ.get("PADDLE_EDL_SAVE_CHECKPOINT_INTER", "10"))
    return _Config(path, save_interval_s=interval)


def configure(dirname, save_interval_s=10.0, every_n_steps=None):
    """Programmatic enable (tests / single scripts)."""
    global _cfg
    _cfg = _Config(dirname, save_interval_s, every_n_steps)
    return _cfg


def disable():
    global _cfg
    _cfg = None


def _active() -> Optional[_Config]:
    global _cfg
    if _cfg is None:
        _cfg = _env_config()
    return _cfg


def _is_rank0() -> bool:
    return int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0) == 0


def _ckpt_dir(cfg):
    return os.path.join(cfg.dirname, "auto_ckpt")


def save_checkpoint(exe, program, scope, cfg=None):
    """Write persistables + RNG + counters (reference save_checkpoint)."""
    from ...fluid import io as fluid_io
    from ...framework.executor import RNG_VAR
    from ...framework.scope import global_scope

    cfg = cfg or _active()
    scope = scope or global_scope()
    out = _ckpt_dir(cfg)
    os.makedirs(out, exist_ok=True)
    from ...fluid import scope_guard

    with scope_guard(scope):
        fluid_io.save_persistables(exe, out, main_program=program,
                                   filename="persistables")
    meta = {"step": cfg.step, "epoch_state": cfg.epoch_state,
            "time": time.time()}
    rng = scope.get_var(RNG_VAR) if scope.has_var(RNG_VAR) else None
    if rng is not None:
        meta["rng"] = np.asarray(rng).tolist()
    tmp = os.path.join(out, "meta.json.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(out, "meta.json"))  # atomic publish


def load_checkpoint(exe, program, scope, cfg=None) -> Optional[dict]:
    """Restore a previous run's state; returns the meta dict or None."""
    from ...fluid import io as fluid_io
    from ...framework.executor import RNG_VAR
    from ...framework.scope import global_scope

    cfg = cfg or _active()
    out = _ckpt_dir(cfg)
    meta_path = os.path.join(out, "meta.json")
    if not os.path.exists(meta_path):
        return None
    scope = scope or global_scope()
    from ...fluid import scope_guard

    with scope_guard(scope):
        fluid_io.load_persistables(exe, out, main_program=program,
                                   filename="persistables")
    with open(meta_path) as f:
        meta = json.load(f)
    if "rng" in meta:
        import jax.numpy as jnp

        scope.set_var(RNG_VAR, jnp.asarray(np.asarray(meta["rng"],
                                                      np.uint32)))
    cfg.step = int(meta.get("step", 0))
    cfg.epoch_state = dict(meta.get("epoch_state", {}))
    return meta


def on_executor_run(exe, program, scope, fed=True):
    """The Executor.run hook (reference executor.py:1200): counts steps
    and saves on the configured cadence from rank 0.  Only fed runs count
    as steps — startup/init programs carry no feeds."""
    cfg = _active()
    if cfg is None or not _is_rank0() or not fed:
        return
    cfg.step += 1
    due = False
    if cfg.every_n_steps:
        due = cfg.step % cfg.every_n_steps == 0
    else:
        due = (time.time() - cfg.last_save) >= cfg.save_interval_s
    if due:
        save_checkpoint(exe, program, scope, cfg)
        cfg.last_save = time.time()


def maybe_resume(exe, program, scope, fed=True):
    """Pre-run hook: on a restarted job, restore the previous snapshot
    BEFORE the first counted step executes (the env-mode resume contract;
    reference AutoCheckpointChecker restores epoch ranges the same way)."""
    cfg = _active()
    if cfg is None or not fed or cfg.resume_attempted:
        return
    cfg.resume_attempted = True
    load_checkpoint(exe, program, scope, cfg)


class train_epoch_range:
    """Reference `acp.train_epoch_range(name, max_epoch)`: iterate epochs,
    skipping the ones a restored checkpoint already finished."""

    def __init__(self, name, max_epoch_num):
        self.name = name
        self.max = max_epoch_num

    def __iter__(self):
        cfg = _active()
        start = 0
        if cfg is not None:
            start = int(cfg.epoch_state.get(self.name, 0))
        for e in range(start, self.max):
            yield e
            if cfg is not None:
                cfg.epoch_state[self.name] = e + 1
