"""``paddle.incubate``-role namespace (reference fluid/incubate)."""
from . import checkpoint  # noqa: F401
