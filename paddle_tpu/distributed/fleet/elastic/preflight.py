"""Device preflight with a deadline: probe the backend in a subprocess.

BENCH r04/r05 died because the *first in-process* ``jax.devices()``
call wedged ("device init did not complete within 240s") — once a
backend hangs inside your own process there is nothing left to
supervise with.  The probe therefore runs in a CHILD process under
``subprocess`` timeout: a tiny jit dispatch (`import jax` + compile +
execute one add) that exercises init, compile, and dispatch, while the
parent — the supervisor — can never be hung by it.

The verdict is structured, not a string soup:

- ``ok``            probe printed its sentinel; ``platform`` is set.
- ``init_timeout``  the child exceeded ``FLAGS_elastic_preflight_timeout_s``.
- ``compile_error`` the child exited nonzero (or produced no sentinel);
  ``diag`` carries the stderr tail.

Failures retry with exponential backoff (``FLAGS_elastic_backoff_s *
2^k``) up to ``attempts`` — a transiently-held chip (an orphaned worker
still being reaped) recovers without burning the supervisor's restart
budget.  Every attempt lands in the flight recorder
(``elastic/preflight``) and the ``elastic_preflight_*`` metric family.
"""
from __future__ import annotations

import subprocess
import sys
import time
from typing import Callable, Optional

from ....framework import flags as _flags
from . import chaos as _chaos

__all__ = ["PreflightVerdict", "preflight_device", "DEFAULT_PROBE_CODE",
           "PREFLIGHT_OK", "PREFLIGHT_INIT_TIMEOUT",
           "PREFLIGHT_COMPILE_ERROR"]

PREFLIGHT_OK = "ok"
PREFLIGHT_INIT_TIMEOUT = "init_timeout"
PREFLIGHT_COMPILE_ERROR = "compile_error"

# init + compile + dispatch in one child; the sentinel keeps parsing
# robust against libraries that chat on stdout during import
DEFAULT_PROBE_CODE = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "x = jax.jit(lambda v: v + 1)(jnp.zeros((8,), jnp.float32))\n"
    "x.block_until_ready()\n"
    "print('PREFLIGHT_OK', jax.devices()[0].platform)\n"
)


class PreflightVerdict:
    """Structured outcome of :func:`preflight_device`."""

    __slots__ = ("ok", "verdict", "platform", "diag", "attempts",
                 "elapsed_s")

    def __init__(self, verdict: str, platform: Optional[str] = None,
                 diag: str = "", attempts: int = 1,
                 elapsed_s: float = 0.0):
        self.verdict = verdict
        self.ok = verdict == PREFLIGHT_OK
        self.platform = platform
        self.diag = diag
        self.attempts = int(attempts)
        self.elapsed_s = float(elapsed_s)

    def to_dict(self) -> dict:
        return {"verdict": self.verdict, "ok": self.ok,
                "platform": self.platform, "diag": self.diag,
                "attempts": self.attempts,
                "elapsed_s": round(self.elapsed_s, 3)}

    def __repr__(self) -> str:  # readable in failure records
        return (f"PreflightVerdict({self.verdict!r}, "
                f"platform={self.platform!r}, attempts={self.attempts})")


def _one_probe(probe_code: str, timeout_s: float) -> PreflightVerdict:
    f = _chaos.take("preflight_init_timeout")
    if f is not None:
        return PreflightVerdict(
            PREFLIGHT_INIT_TIMEOUT,
            diag=f"chaos: injected preflight init timeout ({timeout_s}s)")
    try:
        r = subprocess.run([sys.executable, "-c", probe_code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return PreflightVerdict(
            PREFLIGHT_INIT_TIMEOUT,
            diag=f"device init did not complete within {timeout_s}s")
    for line in reversed((r.stdout or "").splitlines()):
        if line.startswith("PREFLIGHT_OK"):
            parts = line.split()
            return PreflightVerdict(
                PREFLIGHT_OK,
                platform=parts[1] if len(parts) > 1 else "unknown")
    diag = (r.stderr or r.stdout or "no output").strip()[-2000:]
    return PreflightVerdict(
        PREFLIGHT_COMPILE_ERROR,
        diag=f"probe exited {r.returncode}: {diag}")


def preflight_device(attempts: int = 2,
                     timeout_s: Optional[float] = None,
                     backoff_s: Optional[float] = None,
                     probe_code: Optional[str] = None,
                     sleep_fn: Callable[[float], None] = time.sleep
                     ) -> PreflightVerdict:
    """Probe the device up to ``attempts`` times with exponential
    backoff; returns the first ``ok`` verdict, else the last failure.
    ``timeout_s`` / ``backoff_s`` default from
    ``FLAGS_elastic_preflight_timeout_s`` / ``FLAGS_elastic_backoff_s``.
    Never raises — a preflight that cannot even run is a failed
    verdict, not an exception."""
    from ....monitor import stat_add
    from ....observe import flight as _flight

    timeout_s = float(_flags.flag("elastic_preflight_timeout_s")
                      if timeout_s is None else timeout_s)
    backoff_s = float(_flags.flag("elastic_backoff_s")
                      if backoff_s is None else backoff_s)
    code = probe_code or DEFAULT_PROBE_CODE
    attempts = max(int(attempts), 1)
    t0 = time.perf_counter()
    v = PreflightVerdict(PREFLIGHT_COMPILE_ERROR, diag="no attempts made",
                         attempts=0)
    for i in range(attempts):
        try:
            v = _one_probe(code, timeout_s)
        except Exception as e:  # noqa: BLE001 - subprocess machinery broke
            v = PreflightVerdict(
                PREFLIGHT_COMPILE_ERROR,
                diag=f"probe could not run: {type(e).__name__}: {e}")
        v.attempts = i + 1
        v.elapsed_s = time.perf_counter() - t0
        stat_add("elastic_preflight_attempts")
        stat_add(f"elastic_preflight_{v.verdict}")
        _flight.record("elastic/preflight", attempt=i + 1,
                       verdict=v.verdict, platform=v.platform,
                       diag=(v.diag or "")[:300],
                       elapsed_s=round(v.elapsed_s, 3))
        if v.ok:
            return v
        if i + 1 < attempts:
            stat_add("elastic_preflight_retries")
            sleep_fn(backoff_s * (2 ** i))
    return v
