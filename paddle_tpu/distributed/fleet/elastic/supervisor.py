"""Elastic training supervisor: preemption as a recoverable event.

The reference framework's industrial value was that training *survived
the cluster* — Paddle's fleet stack treated worker loss as routine.
PRs 4/6/7/8 built every hard part of that story here (async atomic
checkpoints + ``ResumableIterator``, stall watchdog + postmortem
bundles, cluster heartbeat/dead-rank plane, cross-degree bitwise
resume); this module is the loop that finally *uses* them:

``ElasticSupervisor.run(train_fn, manager, loader)`` drives a step
loop and, when a device or rank disappears, classifies the failure,
dumps a postmortem bundle, and restarts — rebuilding on the surviving
topology when the world shrank — instead of dying:

1. **Preflight with a deadline** (:mod:`.preflight`): a subprocess-
   isolated probe so a wedged backend can never hang the supervisor.
2. **Supervised step loop**: steps run inside an in-flight window the
   PR 6 :class:`~paddle_tpu.observe.health.StallWatchdog` samples (a
   supervisor-local progress feed — one counter pair + the current
   step's dispatch time); a trip dumps the bundle and restarts the
   attempt.  The loop also polls the PR 6 health plane
   (``/metrics/cluster`` or an injected ``cluster_fn``) for dead
   ranks, and fires :mod:`.chaos` hook points.
3. **Failure classification** — ``transient`` (restart in place),
   ``topology_change`` (drop the dead ranks, re-shard, restore), or
   ``poison_step`` (the same step failed identically twice, or the
   budget gate refused it: replaying cannot help — terminal).
4. **Elastic restore**: every (re)start restores the latest *intact*
   checkpoint through the PR 4 manager (the PR 7 ``LocalShard``
   re-assembly makes the bytes topology-independent), fast-forwards
   the ``ResumableIterator``, and continues — bitwise on the new
   world (pinned by ``tests/test_elastic.py``).
5. **Retry budget**: ``FLAGS_elastic_max_restarts`` attempts with
   ``FLAGS_elastic_backoff_s * 2^k`` backoff, then a loud
   :class:`ElasticTerminated` carrying the whole restart history —
   never a silent hang, never a silent 0.0.

``train_fn(topology)`` builds the model/executor for the given
:class:`Topology` and returns a program object exposing
``step(batch) -> loss`` plus either a ``scope`` (device state the
checkpoint manager snapshots/restores) or ``state()``/``load_state()``
(host-state dict), optionally ``components`` (extra checkpoint
components, e.g. an LR scheduler) and ``close()``.  A bare callable is
wrapped as a stateless step function.

Honest limitation: this is in-process supervision — a host thread
wedged *forever* inside a device call can be diagnosed (watchdog →
bundle) but not preempted from the same process.  That is exactly why
preflight is subprocess-isolated, and why multi-host deployments run
one supervised process per rank (the launcher restarts processes; this
loop restarts *topologies*).
"""
from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, Optional, Sequence

from ....framework import flags as _flags
from ....monitor import stat_add, stat_set
from ....observe import flight as _flight
from . import chaos
from .preflight import PreflightVerdict, preflight_device

__all__ = ["Topology", "ElasticSupervisor", "SupervisorResult",
           "ElasticTerminated", "PreflightError", "StallDetected",
           "DeadRankDetected", "classify_failure", "is_device_failure",
           "dead_ranks_from_cluster", "FAILURE_TRANSIENT",
           "FAILURE_TOPOLOGY", "FAILURE_POISON"]

FAILURE_TRANSIENT = "transient"
FAILURE_TOPOLOGY = "topology_change"
FAILURE_POISON = "poison_step"


class ElasticTerminated(RuntimeError):
    """Loud terminal failure: the retry budget is exhausted or the
    failure is poison.  Carries the restart history so the terminal
    record is a diagnosis, not a shrug."""

    def __init__(self, msg: str, history: Optional[List[dict]] = None):
        super().__init__(msg)
        self.history = list(history or [])


class PreflightError(RuntimeError):
    """A preflight verdict other than ``ok`` (always transient: its
    own bounded retries already ran)."""

    def __init__(self, verdict: PreflightVerdict):
        super().__init__(
            f"device preflight failed: {verdict.verdict} "
            f"after {verdict.attempts} attempt(s): {verdict.diag}")
        self.verdict = verdict


class StallDetected(RuntimeError):
    """The stall watchdog tripped on this attempt's step window."""

    def __init__(self, step: int, bundle: Optional[str] = None):
        super().__init__(
            f"stall watchdog tripped at step {step}"
            + (f" (postmortem: {bundle})" if bundle else ""))
        self.step = int(step)
        self.bundle = bundle


class DeadRankDetected(RuntimeError):
    """The health plane dead-listed rank(s) this topology depends on."""

    def __init__(self, ranks: Sequence[int]):
        self.ranks = sorted(int(r) for r in ranks)
        super().__init__(f"health plane dead-listed rank(s) {self.ranks}")


# message markers that make a generic exception read as the DEVICE
# failing rather than the program (bench uses this to decide a flagship
# is worth retrying)
_DEVICE_MARKERS = ("device", "backend", "tpu", "pjrt", "xla",
                   "resource_exhausted", "deadline_exceeded",
                   "unavailable", "init did not complete", "preflight",
                   "stall watchdog", "heartbeat", "dead-listed")


def is_device_failure(exc: BaseException) -> bool:
    """Does this exception look like the device/cluster failing (worth
    a retry) rather than the program being wrong (not)?"""
    if isinstance(exc, (PreflightError, StallDetected, DeadRankDetected,
                        chaos.RankKilled)):
        return True
    msg = f"{type(exc).__name__}: {exc}".lower()
    return any(m in msg for m in _DEVICE_MARKERS)


def classify_failure(exc: BaseException,
                     dead_ranks: Optional[Sequence[int]] = None,
                     repeat: bool = False) -> str:
    """transient | topology_change | poison_step (module docstring §3).

    ``dead_ranks`` is the health plane's word at failure time;
    ``repeat`` means the SAME step already failed with the SAME
    exception once — replaying is provably useless."""
    if isinstance(exc, (chaos.RankKilled, DeadRankDetected)) or dead_ranks:
        return FAILURE_TOPOLOGY
    if isinstance(exc, PreflightError):
        return FAILURE_TRANSIENT
    try:
        from ....observe.xla_stats import MemoryBudgetError

        if isinstance(exc, MemoryBudgetError):
            # deterministic refusal: the program does not fit — a
            # replay on the same topology refuses identically
            return FAILURE_POISON
    except ImportError:  # pragma: no cover - partial installs
        pass
    if repeat:
        return FAILURE_POISON
    return FAILURE_TRANSIENT


def dead_ranks_from_cluster(url: str, timeout_s: float = 2.0
                            ) -> Callable[[], List[int]]:
    """Build a ``dead_ranks_fn`` (for :class:`ElasticSupervisor` or
    :class:`~paddle_tpu.ckpt.KVBarrier`) polling rank 0's aggregated
    ``GET /metrics/cluster`` route.  Unreachable aggregator = no
    verdict (empty list): liveness decisions need positive evidence."""
    import urllib.request

    base = url.rstrip("/")
    if not base.startswith("http"):
        base = "http://" + base

    def fn() -> List[int]:
        try:
            with urllib.request.urlopen(f"{base}/metrics/cluster",
                                        timeout=timeout_s) as r:
                doc = json.load(r)
            return [int(x) for x in (doc.get("dead_ranks") or [])]
        except Exception:  # noqa: BLE001 - no evidence, no verdict
            return []

    return fn


class Topology:
    """The live world the current attempt runs on: which ranks exist.
    Mesh/axis layout is ``train_fn``'s business (it knows its model);
    the supervisor only tracks membership."""

    def __init__(self, world_size: Optional[int] = None,
                 ranks: Optional[Sequence[int]] = None):
        if ranks is not None:
            self.ranks = sorted(int(r) for r in ranks)
        else:
            self.ranks = list(range(int(world_size or 1)))
        self.world_size = len(self.ranks)

    def without(self, dead: Sequence[int]) -> "Topology":
        gone = {int(r) for r in dead}
        return Topology(ranks=[r for r in self.ranks if r not in gone])

    def __repr__(self) -> str:
        return f"Topology(world_size={self.world_size}, ranks={self.ranks})"


class SupervisorResult:
    """What a survived run looks like: the full loss trajectory
    (replayed steps overwrite their first emission, so it matches an
    uninterrupted run), restart accounting, and the last-built train
    program (``.train`` — read final state from it)."""

    def __init__(self):
        self.losses: List[float] = []
        self.restarts = 0
        self.reshards = 0
        self.preflight_retries = 0
        self.status = "ok"            # "ok" | "recovered"
        self.history: List[dict] = []
        self.final_world_size = 0
        self.final_step = 0
        self.steps_per_sec = 0.0      # of the final (successful) attempt
        self.train = None

    def to_dict(self) -> dict:
        return {"status": self.status, "restarts": self.restarts,
                "reshards": self.reshards,
                "preflight_retries": self.preflight_retries,
                "final_world_size": self.final_world_size,
                "final_step": self.final_step,
                "steps_per_sec": round(self.steps_per_sec, 3),
                "history": self.history}


class _FnProgram:
    """Adapter: a bare ``fn(step_index, batch) -> loss`` as a program
    with no checkpointable state."""

    def __init__(self, fn):
        self._fn = fn
        self._step = 0

    def step(self, batch):
        self._step += 1
        return self._fn(self._step, batch)


class ElasticSupervisor:
    """See module docstring.  ``max_restarts`` / ``backoff_s`` /
    ``preflight_timeout_s`` default from ``FLAGS_elastic_max_restarts``
    / ``FLAGS_elastic_backoff_s`` / ``FLAGS_elastic_preflight_timeout_s``.

    ``manager`` (on :meth:`run`) may be a
    :class:`~paddle_tpu.ckpt.CheckpointManager`, a factory
    ``f(topology) -> CheckpointManager`` (rebuilt per attempt — the
    multi-rank case, where world size is part of the manager), or
    ``None`` (no checkpointing: a failure replays from step 1).
    ``cluster_fn`` (a zero-arg callable returning the
    ``/metrics/cluster`` document) or ``cluster_url`` wires dead-rank
    detection; ``watchdog_timeout_s > 0`` arms the stall watchdog over
    the supervisor's own step window."""

    def __init__(self, total_steps: Optional[int] = None,
                 world_size: int = 1,
                 max_restarts: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 preflight: bool = True,
                 preflight_attempts: int = 2,
                 preflight_timeout_s: Optional[float] = None,
                 preflight_probe_code: Optional[str] = None,
                 watchdog_timeout_s: float = 0.0,
                 cluster_fn: Optional[Callable[[], dict]] = None,
                 cluster_url: Optional[str] = None,
                 cluster_poll_s: float = 1.0,
                 save_every: int = 1,
                 postmortem_dir: Optional[str] = None,
                 sleep_fn: Callable[[float], None] = time.sleep):
        self.total_steps = total_steps
        self.initial_world_size = int(world_size)
        self.max_restarts = int(_flags.flag("elastic_max_restarts")
                                if max_restarts is None else max_restarts)
        self.backoff_s = float(_flags.flag("elastic_backoff_s")
                               if backoff_s is None else backoff_s)
        self.preflight = bool(preflight)
        self.preflight_attempts = int(preflight_attempts)
        self.preflight_timeout_s = preflight_timeout_s
        self.preflight_probe_code = preflight_probe_code
        self.watchdog_timeout_s = float(watchdog_timeout_s)
        if cluster_fn is None and cluster_url:
            url_fn = dead_ranks_from_cluster(cluster_url)
            cluster_fn = lambda: {"dead_ranks": url_fn()}  # noqa: E731
        self.cluster_fn = cluster_fn
        self.cluster_poll_s = float(cluster_poll_s)
        self.save_every = int(save_every)
        self.postmortem_dir = postmortem_dir
        self.sleep_fn = sleep_fn
        # per-attempt step-window progress the watchdog samples
        self._progress = {"dispatched": 0, "drained": 0}
        self._step_t0: Optional[float] = None
        self._current_step = 0
        self._watchdog = None
        self._stall_bundles: List[str] = []
        self._stalled = None

    # -- watchdog over the supervisor's own step window -----------------
    def _progress_fn(self) -> Dict:
        p = dict(self._progress)
        inflight = max(p["dispatched"] - p["drained"], 0)
        out = {"dispatched": p["dispatched"], "drained": p["drained"],
               "inflight": inflight}
        t0 = self._step_t0
        if inflight and t0 is not None:
            out["oldest_inflight_age_s"] = round(
                time.perf_counter() - t0, 3)
        return out

    def _start_watchdog(self):
        if self.watchdog_timeout_s <= 0:
            return
        import threading

        from ....observe.health import StallWatchdog

        self._stalled = threading.Event()

        def on_stall(bundle: str) -> None:
            self._stall_bundles.append(bundle)
            self._stalled.set()

        self._watchdog = StallWatchdog(
            timeout_s=self.watchdog_timeout_s,
            directory=self.postmortem_dir,
            progress_fn=self._progress_fn, on_stall=on_stall)
        self._watchdog.start()

    def _stop_watchdog(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None

    # -- cluster / dead-rank polling ------------------------------------
    def _poll_dead_ranks(self) -> List[int]:
        if self.cluster_fn is None:
            return []
        try:
            doc = self.cluster_fn() or {}
        except Exception:  # noqa: BLE001 - no evidence, no verdict
            return []
        return [int(r) for r in (doc.get("dead_ranks") or [])]

    # -- per-attempt plumbing -------------------------------------------
    @staticmethod
    def _wrap_program(obj):
        if hasattr(obj, "step"):
            return obj
        if callable(obj):
            return _FnProgram(obj)
        raise TypeError(
            f"train_fn must return an object with .step(batch) or a "
            f"callable, got {type(obj).__name__}")

    @staticmethod
    def _fresh_iterator(loader):
        if loader is None:
            return None
        from ....ckpt import ResumableIterator

        it = loader if isinstance(loader, ResumableIterator) \
            else ResumableIterator(loader)
        # reset BEFORE restore: a failed attempt left the iterator
        # mid-epoch, and without a checkpoint to fast-forward from the
        # replay must start at batch 0, not wherever the crash left it
        it.set_state_dict(None)
        return it

    def _manager_for(self, manager, topo):
        if manager is None:
            return None, False
        if callable(manager) and not hasattr(manager, "save"):
            return manager(topo), True
        return manager, False

    @staticmethod
    def _quiesce() -> None:
        """Drain every live executor window and pending async save:
        the next attempt must observe completed steps and committed
        (or cleanly failed) checkpoints only."""
        try:
            from ....framework.executor import quiesce_all

            quiesce_all(raise_errors=False)
        except ImportError:  # pragma: no cover - partial installs
            pass

    def _cleanup_attempt(self, prog, mgr, owns_mgr: bool,
                         reshard: bool) -> None:
        self._stop_watchdog()
        self._quiesce()
        if prog is not None and hasattr(prog, "close"):
            try:
                prog.close()
            except Exception:  # noqa: BLE001
                pass
        if mgr is not None and owns_mgr:
            try:
                mgr.close()
            except Exception:  # noqa: BLE001 - background save error
                pass            # already classified via the attempt
        if reshard:
            # re-init hook: drop every live executor's compiled caches
            # so the rebuild on the NEW topology starts clean
            try:
                from ....framework.executor import close_all

                close_all()
            except ImportError:  # pragma: no cover
                pass

    # -- the loop --------------------------------------------------------
    def run(self, train_fn, manager=None, loader=None,
            total_steps: Optional[int] = None) -> SupervisorResult:
        total = int(self.total_steps if total_steps is None
                    else total_steps)
        if total <= 0:
            raise ValueError("ElasticSupervisor needs total_steps > 0")
        result = SupervisorResult()
        losses: Dict[int, float] = {}
        topo = Topology(self.initial_world_size)
        restarts = 0
        last_sig = None
        history: List[dict] = []
        _flight.record("elastic/start", total_steps=total,
                       world_size=topo.world_size,
                       max_restarts=self.max_restarts)
        while True:
            rec = {"attempt": len(history), "world_size": topo.world_size,
                   "ts": time.time()}
            prog = mgr = it = None
            owns_mgr = False
            prev_fault_hook = None
            hook_installed = False
            self._progress = {"dispatched": 0, "drained": 0}
            self._step_t0 = None
            self._current_step = 0
            self._stall_bundles = []
            steps_done = 0
            t_attempt = time.perf_counter()
            last_cluster_poll = 0.0
            try:
                if self.preflight:
                    v = preflight_device(
                        attempts=self.preflight_attempts,
                        timeout_s=self.preflight_timeout_s,
                        backoff_s=self.backoff_s,
                        probe_code=self.preflight_probe_code,
                        sleep_fn=self.sleep_fn)
                    result.preflight_retries += max(v.attempts - 1, 0)
                    if not v.ok:
                        raise PreflightError(v)
                prog = self._wrap_program(train_fn(topo))
                mgr, owns_mgr = self._manager_for(manager, topo)
                scope = getattr(prog, "scope", None)
                start = 0
                if mgr is not None and scope is None and not (
                        hasattr(prog, "state")
                        and hasattr(prog, "load_state")):
                    # a stateless program (bare callable) has nothing
                    # to checkpoint: run unsupervised-checkpointing
                    # instead of crashing the first save (and then
                    # reading as a poison step)
                    _flight.record("elastic/ckpt_skipped",
                                   reason="program has no scope and no "
                                          "state()/load_state()")
                    if owns_mgr:
                        mgr.close()
                    mgr, owns_mgr = None, False
                if mgr is not None:
                    it = self._fresh_iterator(loader)
                    if it is not None:
                        mgr.register("data", it)
                    for name, comp in (getattr(prog, "components", None)
                                       or {}).items():
                        mgr.register(name, comp)
                    # chain the chaos hook in FRONT of any caller-
                    # installed fault hook, and restore the caller's
                    # when the attempt ends — the supervisor must not
                    # silently eat a reused manager's own hook
                    prev_fault_hook = getattr(mgr, "_fault_hook", None)

                    def _hook(phase, step, _prev=prev_fault_hook):
                        chaos.checkpoint_fault_hook(phase, step)
                        if _prev is not None:
                            _prev(phase, step)

                    mgr.set_fault_hook(_hook)
                    hook_installed = True
                    if scope is not None:
                        meta = mgr.restore(scope=scope)
                    else:
                        meta = mgr.restore()
                        if meta is not None and hasattr(prog, "load_state"):
                            prog.load_state(meta.get("state") or {})
                    if meta is not None:
                        start = int(meta["step"])
                        stat_add("elastic_restores")
                elif loader is not None:
                    it = self._fresh_iterator(loader)
                stat_set("elastic_world_size", topo.world_size)
                self._start_watchdog()
                _flight.record("elastic/attempt", attempt=len(history),
                               start_step=start,
                               world_size=topo.world_size)
                for step in range(start + 1, total + 1):
                    self._current_step = step
                    now = time.monotonic()
                    if self.cluster_fn is not None and \
                            now - last_cluster_poll >= self.cluster_poll_s:
                        last_cluster_poll = now
                        dead = [r for r in self._poll_dead_ranks()
                                if r in topo.ranks]
                        if dead:
                            raise DeadRankDetected(dead)
                    self._progress["dispatched"] += 1
                    self._step_t0 = time.perf_counter()
                    chaos.step_hook(step, topology=topo)
                    batch = next(it) if it is not None else None
                    loss = prog.step(batch)
                    self._progress["drained"] += 1
                    self._step_t0 = None
                    steps_done += 1
                    if loss is not None:
                        losses[step] = float(loss)
                    if self._stalled is not None and self._stalled.is_set():
                        raise StallDetected(
                            step, self._stall_bundles[-1]
                            if self._stall_bundles else None)
                    if mgr is not None and self.save_every > 0 \
                            and step % self.save_every == 0:
                        if scope is not None:
                            mgr.save(step, scope=scope)
                        else:
                            mgr.save(step, state=prog.state())
                if mgr is not None:
                    mgr.wait()
                    if hook_installed:
                        mgr.set_fault_hook(prev_fault_hook)
                self._stop_watchdog()
                dt = time.perf_counter() - t_attempt
                result.steps_per_sec = steps_done / dt if dt > 0 else 0.0
                result.restarts = restarts
                result.reshards = sum(1 for h in history
                                      if h.get("kind") == FAILURE_TOPOLOGY)
                result.status = "recovered" if restarts else "ok"
                result.history = history
                result.final_world_size = topo.world_size
                result.final_step = total
                result.losses = [losses[s] for s in range(1, total + 1)
                                 if s in losses]
                result.train = prog
                if restarts:
                    stat_add("elastic_runs_recovered")
                _flight.record("elastic/done", status=result.status,
                               restarts=restarts,
                               world_size=topo.world_size)
                if mgr is not None and owns_mgr:
                    try:
                        mgr.close()
                    except Exception:  # noqa: BLE001
                        pass
                return result
            except Exception as e:  # noqa: BLE001 - the whole point
                dead = []
                if isinstance(e, chaos.RankKilled):
                    dead = [e.rank]
                elif isinstance(e, DeadRankDetected):
                    dead = list(e.ranks)
                else:
                    dead = [r for r in self._poll_dead_ranks()
                            if r in topo.ranks]
                sig = (self._current_step, type(e).__name__,
                       str(e)[:200])
                repeat = sig == last_sig
                last_sig = sig
                kind = classify_failure(e, dead_ranks=dead, repeat=repeat)
                err = f"{type(e).__name__}: {e}"[:300]
                rec.update(kind=kind, step=self._current_step,
                           error=err, dead_ranks=dead)
                history.append(rec)
                stat_add("elastic_failures")
                _flight.record("elastic/failure", kind=kind,
                               step=self._current_step, error=err,
                               dead_ranks=dead,
                               world_size=topo.world_size)
                try:
                    from ....observe.health import dump_postmortem

                    rec["postmortem"] = dump_postmortem(
                        f"elastic_{kind}", directory=self.postmortem_dir,
                        exc=(type(e), e, e.__traceback__),
                        extra={"restart_history": history,
                               "world_size": topo.world_size})
                except Exception:  # noqa: BLE001 - diagnosis best-effort
                    pass
                if mgr is not None and hook_installed:
                    try:
                        mgr.set_fault_hook(prev_fault_hook)
                    except Exception:  # noqa: BLE001
                        pass
                self._cleanup_attempt(prog, mgr, owns_mgr,
                                      reshard=kind == FAILURE_TOPOLOGY)
                if kind == FAILURE_POISON:
                    stat_add("elastic_terminal_failures")
                    _flight.record("elastic/terminal", reason="poison",
                                   step=self._current_step)
                    raise ElasticTerminated(
                        f"poison step {self._current_step}: replaying "
                        f"cannot help ({err}); restart history: "
                        f"{len(history)} attempt(s)", history) from e
                restarts += 1
                stat_add("elastic_restarts")
                if restarts > self.max_restarts:
                    stat_add("elastic_terminal_failures")
                    _flight.record("elastic/terminal", reason="budget",
                                   restarts=restarts)
                    raise ElasticTerminated(
                        f"restart budget exhausted ({self.max_restarts} "
                        f"restarts; FLAGS_elastic_max_restarts); last "
                        f"failure: {err}; restart history: "
                        f"{len(history)} attempt(s)", history) from e
                if kind == FAILURE_TOPOLOGY:
                    topo = topo.without(dead or [max(topo.ranks)])
                    if topo.world_size <= 0:
                        stat_add("elastic_terminal_failures")
                        raise ElasticTerminated(
                            "no live ranks left to re-shard onto",
                            history) from e
                    stat_add("elastic_reshards")
                    _flight.record("elastic/reshard", dead_ranks=dead,
                                   world_size=topo.world_size)
                backoff = self.backoff_s * (2 ** (restarts - 1))
                _flight.record("elastic/restart", attempt=len(history),
                               backoff_s=backoff,
                               world_size=topo.world_size)
                if backoff > 0:
                    self.sleep_fn(backoff)
