"""``fleet.elastic`` — preemption-proof elastic training.

The supervisor loop over the pieces PRs 4/6/7/8 built: subprocess
device preflight with a deadline (:mod:`.preflight`), a supervised
step loop under the stall watchdog + cluster health plane with
failure classification and elastic restore on the surviving topology
(:mod:`.supervisor`), and injectable faults so every recovery path is
rehearsed continuously (:mod:`.chaos`).  See the README "Elastic
training" section for the lifecycle and the flag reference
(``FLAGS_elastic_max_restarts`` / ``FLAGS_elastic_preflight_timeout_s``
/ ``FLAGS_elastic_backoff_s``).
"""
from __future__ import annotations

from . import chaos
from .chaos import RankKilled, TornCheckpoint
from .preflight import (DEFAULT_PROBE_CODE, PREFLIGHT_COMPILE_ERROR,
                        PREFLIGHT_INIT_TIMEOUT, PREFLIGHT_OK,
                        PreflightVerdict, preflight_device)
from .supervisor import (FAILURE_POISON, FAILURE_TOPOLOGY,
                         FAILURE_TRANSIENT, DeadRankDetected,
                         ElasticSupervisor, ElasticTerminated,
                         PreflightError, StallDetected, SupervisorResult,
                         Topology, classify_failure,
                         dead_ranks_from_cluster, is_device_failure)

__all__ = [
    "ElasticSupervisor", "SupervisorResult", "Topology",
    "ElasticTerminated", "PreflightError", "StallDetected",
    "DeadRankDetected", "RankKilled", "TornCheckpoint",
    "preflight_device", "PreflightVerdict", "DEFAULT_PROBE_CODE",
    "PREFLIGHT_OK", "PREFLIGHT_INIT_TIMEOUT", "PREFLIGHT_COMPILE_ERROR",
    "classify_failure", "is_device_failure", "dead_ranks_from_cluster",
    "FAILURE_TRANSIENT", "FAILURE_TOPOLOGY", "FAILURE_POISON", "chaos",
]
