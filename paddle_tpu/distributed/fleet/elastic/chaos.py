"""Fault injection for the elastic training supervisor.

Recovery paths that are only exercised when real hardware dies are
recovery paths that have silently rotted by the time they matter
(BENCH r04/r05: the first genuine device loss produced 0.0 because
nothing had ever rehearsed it).  This module keeps a small process-wide
armory of *injectable* faults that the supervisor's hook points — and
nothing else — consult, so every classified failure mode is driven
continuously by tests and the ``bench.py`` chaos leg:

- ``kill_rank_mid_step``   (params ``rank``, ``at_step``): raises
  :class:`RankKilled` from the supervisor's step hook — the
  topology-change path (re-shard + elastic restore).
- ``hang_device_call``     (params ``at_step``, ``seconds``): sleeps
  inside the in-flight step window so the stall watchdog trips — the
  transient path (postmortem bundle + restart in place).
- ``torn_checkpoint``      (params ``at_step``): raises from the
  checkpoint writer's ``pre_commit`` fault hook, leaving exactly the
  torn ``.tmp`` a killed process would — restore must fall back.
- ``heartbeat_blackhole``  (params ``rank``): the named rank's
  :class:`~paddle_tpu.observe.health.HealthReporter` drops its beats
  so the health plane dead-lists a live process — the
  dead-rank-detection path.
- ``preflight_init_timeout`` (no params): one preflight probe reports
  ``init_timeout`` without spawning the subprocess — the r04/r05
  "device init did not complete" failure on demand.
- ``kill_prefill_replica`` (params ``replica``): the disaggregated
  serving router (``serving/disagg.py``) hard-stops the named prefill
  replica at its handoff hook — the in-flight prefill dies with
  ``ServerClosedError`` and the router's re-dispatch path must finish
  the request on a survivor with zero drops.

Arming is explicit (:func:`inject`) and consumption is counted: a
fault fires ``count`` times then disarms (``count=-1`` = until
:func:`clear`).  Firing is observable — every arm/fire lands in the
flight recorder and on ``chaos_faults_armed`` / ``chaos_faults_fired``.
The module deliberately imports almost nothing: hook points in
low-level code (heartbeats) check ``sys.modules`` for it, so a process
that never imports chaos pays nothing.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

__all__ = ["FAULTS", "RankKilled", "TornCheckpoint", "inject", "clear",
           "armed", "take", "step_hook", "checkpoint_fault_hook"]

FAULTS = ("kill_rank_mid_step", "hang_device_call", "torn_checkpoint",
          "heartbeat_blackhole", "preflight_init_timeout",
          "kill_prefill_replica")


class RankKilled(RuntimeError):
    """An (injected) rank death: the supervisor classifies this as a
    topology change and re-shards onto the survivors."""

    def __init__(self, rank: int, msg: Optional[str] = None):
        super().__init__(msg or f"rank {rank} killed")
        self.rank = int(rank)


class TornCheckpoint(RuntimeError):
    """Injected writer death mid-commit: leaves the torn ``.tmp`` a
    killed process would; restore must fall back to the previous
    intact step."""


_LOCK = threading.Lock()
_ARMED: List[dict] = []  # {"fault": name, "count": n, **params}


def _flight(event: str, **fields) -> None:
    try:
        from ....observe import flight

        flight.record(event, **fields)
    except Exception:  # noqa: BLE001 - chaos must never add real faults
        pass


def inject(fault: str, count: int = 1, **params) -> None:
    """Arm ``fault`` to fire ``count`` times (``-1`` = until
    :func:`clear`).  ``params`` are matched against the hook point's
    context (e.g. ``at_step=4`` fires only at step 4) — a param the
    hook does not supply is treated as fault payload (``rank=1`` on a
    kill names the victim)."""
    if fault not in FAULTS:
        raise KeyError(f"unknown chaos fault {fault!r} (have {FAULTS})")
    with _LOCK:
        _ARMED.append({"fault": fault, "count": int(count), **params})
    from ....monitor import stat_add

    stat_add("chaos_faults_armed")
    _flight("chaos/inject", fault=fault, count=count, **params)


def clear(fault: Optional[str] = None) -> None:
    """Disarm every armed fault (or only ``fault``)."""
    with _LOCK:
        if fault is None:
            _ARMED.clear()
        else:
            _ARMED[:] = [f for f in _ARMED if f["fault"] != fault]


def armed(fault: Optional[str] = None) -> List[dict]:
    """Snapshot of armed faults (tests/debugging)."""
    with _LOCK:
        return [dict(f) for f in _ARMED
                if fault is None or f["fault"] == fault]


def take(fault: str, **ctx) -> Optional[dict]:
    """Consume one firing of ``fault`` whose params match ``ctx``
    (params present in BOTH must be equal; payload-only params pass
    through).  Returns the fault's param dict or ``None``."""
    with _LOCK:
        for f in _ARMED:
            if f["fault"] != fault:
                continue
            if any(k in ctx and f[k] != ctx[k]
                   for k in f if k not in ("fault", "count")):
                continue
            if f["count"] > 0:
                f["count"] -= 1
                if f["count"] == 0:
                    _ARMED.remove(f)
            fired = {k: v for k, v in f.items() if k != "count"}
            break
        else:
            return None
    from ....monitor import stat_add

    stat_add("chaos_faults_fired")
    _flight("chaos/fire", **fired, **{k: v for k, v in ctx.items()
                                      if k not in fired})
    return fired


def step_hook(step: int, topology=None) -> None:
    """The supervisor's per-step hook point, called inside the
    in-flight window (after dispatch accounting, before the train
    step) so a hang here is indistinguishable from a wedged device
    call to the watchdog."""
    f = take("hang_device_call", at_step=step)
    if f is not None:
        time.sleep(float(f.get("seconds", 1.0)))
    f = take("kill_rank_mid_step", at_step=step)
    if f is not None:
        rank = int(f.get("rank", 1))
        raise RankKilled(rank, f"chaos: rank {rank} killed mid-step "
                               f"{step}")


def checkpoint_fault_hook(phase: str, step: int) -> None:
    """Install on a :class:`~paddle_tpu.ckpt.CheckpointManager` via
    ``set_fault_hook`` (the supervisor does): an armed
    ``torn_checkpoint`` kills the writer at ``pre_commit``, leaving
    the torn ``.tmp`` on disk."""
    if phase != "pre_commit":
        return
    f = take("torn_checkpoint", at_step=step)
    if f is not None:
        raise TornCheckpoint(
            f"chaos: checkpoint writer killed pre-commit at step {step}")
