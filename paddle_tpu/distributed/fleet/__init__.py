"""Fleet: the distributed-training facade.

Role parity: reference python/paddle/distributed/fleet/base/fleet_base.py —
fleet.init:125, worker_num/worker_index, distributed_optimizer:554,
minimize:946 (meta-optimizer selection), barrier_worker.  TPU-native:
init builds the device mesh (parallel_env) instead of NCCL rings; minimize
runs the meta-optimizer chain and the collective transpile; the executor
runs the result SPMD over the mesh.
"""
from __future__ import annotations

from typing import Optional

from ..parallel_env import get_mesh, get_rank, get_world_size, init_parallel_env
from .base.distributed_strategy import DistributedStrategy
from .base.role_maker import PaddleCloudRoleMaker, RoleMakerBase, UserDefinedRoleMaker
from .meta_optimizers import compile_strategy


class Fleet:
    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._user_optimizer = None
        self._is_collective = True
        self._inited = False

    # -- lifecycle --------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        self._is_collective = is_collective
        self._strategy = strategy or DistributedStrategy()
        if is_collective and get_mesh() is None:
            init_parallel_env()
        self._inited = True
        return self

    # -- topology queries -------------------------------------------------
    def is_first_worker(self) -> bool:
        return self.worker_index() == 0

    def worker_index(self) -> int:
        return get_rank()

    def worker_num(self) -> int:
        return max(get_world_size(), 1)

    def is_worker(self) -> bool:
        return self._role_maker is None or self._role_maker._is_worker()

    def worker_endpoints(self, to_string=False):
        eps = (self._role_maker._get_trainer_endpoints()
               if self._role_maker else [])
        return ",".join(eps) if to_string else eps

    def is_server(self) -> bool:
        return bool(self._role_maker and getattr(
            self._role_maker, "_is_server", lambda: False)())

    def barrier_worker(self):
        if self._role_maker:
            self._role_maker._barrier("worker")

    # PS-mode API parity stubs (documented N/A on TPU: SURVEY §2.8 —
    # the north star is collective mode; these keep user scripts importable)
    def init_worker(self):
        pass

    def init_server(self, *args, **kwargs):
        pass

    def run_server(self):
        raise NotImplementedError(
            "parameter-server mode is N/A on the TPU collective runtime "
            "(SURVEY §2.8); use is_collective=True")

    def stop_worker(self):
        pass

    # -- optimizer --------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        self._user_optimizer = optimizer
        return self

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._user_optimizer is None:
            raise RuntimeError("call fleet.distributed_optimizer(opt) first")
        chain = compile_strategy(loss, self._role_maker,
                                 self._user_optimizer, self._strategy)
        return chain.minimize(loss, startup_program, parameter_list, no_grad_set)

    # dygraph path: return the optimizer wrapped for DP (grads psum'd by
    # DataParallel.apply_collective_grads before step)
    @property
    def user_defined_optimizer(self):
        return self._user_optimizer

    @property
    def distributed_strategy(self):
        return self._strategy


def __getattr__(name):
    # fleet.elastic is lazy: the supervisor pulls in ckpt/observe and
    # most fleet users (pure training scripts) never touch it
    if name == "elastic":
        import importlib

        mod = importlib.import_module(".elastic", __name__)
        globals()[name] = mod
        return mod
    if name == "distributed_embedding":
        # the sharded-embedding builder (replaces the reference's
        # parameter-server fleet.distributed_embedding); lazy for the
        # same reason as elastic
        from ..embedding import distributed_embedding as _de

        globals()[name] = _de
        return _de
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


_fleet_singleton = Fleet()

init = _fleet_singleton.init
is_first_worker = _fleet_singleton.is_first_worker
worker_index = _fleet_singleton.worker_index
worker_num = _fleet_singleton.worker_num
is_worker = _fleet_singleton.is_worker
worker_endpoints = _fleet_singleton.worker_endpoints
is_server = _fleet_singleton.is_server
barrier_worker = _fleet_singleton.barrier_worker
init_worker = _fleet_singleton.init_worker
init_server = _fleet_singleton.init_server
run_server = _fleet_singleton.run_server
stop_worker = _fleet_singleton.stop_worker
distributed_optimizer = _fleet_singleton.distributed_optimizer
minimize = _fleet_singleton.minimize

__all__ = [
    "DistributedStrategy", "Fleet", "PaddleCloudRoleMaker",
    "UserDefinedRoleMaker", "distributed_embedding", "elastic", "init",
    "is_first_worker", "worker_index", "worker_num", "is_worker",
    "barrier_worker", "distributed_optimizer", "minimize",
]
