"""Collective program transpile: insert grad-allreduce into a program.

Role parity: reference python/paddle/fluid/transpiler/collective.py —
`GradAllReduce` (:244) scales the loss grad by 1/nranks
(_insert_scale_loss_grad_ops) and inserts `c_allreduce_sum` after each
parameter gradient; `LocalSGD` (:270) periodically averages params.
TPU-native: no comm-init ops are inserted (the mesh already exists);
the c_allreduce_sum ops lower to lax.psum inside the one compiled
train-step program.
"""
from __future__ import annotations

from ...framework.program import GRAD_SUFFIX, Program


def _grad_param_pairs(block, params_grads=None):
    if params_grads:
        return [(p.name if hasattr(p, "name") else p,
                 g.name if hasattr(g, "name") else g) for p, g in params_grads]
    pairs = []
    for var in block.vars.values():
        if getattr(var, "is_parameter", False):
            gname = var.name + GRAD_SUFFIX
            if block._find_var_recursive(gname) is not None:
                pairs.append((var.name, gname))
    return pairs


class GradAllReduce:
    def __init__(self, nranks, ring_id=0, fuse_all_reduce=True, fp16=False):
        self.nranks = nranks
        self.ring_id = ring_id
        # fp16_allreduce strategy: halve allreduce bytes by casting grads
        # to bf16 around the collective (reference
        # fp16_allreduce_optimizer.py; bf16 is the TPU-native low-precision)
        self.fp16 = fp16

    def transpile(self, main_program: Program, params_grads=None,
                  loss_grad_name=None):
        if self.nranks <= 1:
            return main_program
        block = main_program.global_block
        pairs = _grad_param_pairs(block, params_grads)
        grad_names = {g for _, g in pairs}

        new_ops = []
        for op in block.ops:
            new_ops.append(op)
            # scale the loss grad once (reference _insert_scale_loss_grad_ops)
            if loss_grad_name and loss_grad_name in op.output_arg_names() \
                    and op.type == "fill_constant":
                from ...framework.program import Operator

                new_ops.append(Operator(
                    block, "scale", {"X": [loss_grad_name]},
                    {"Out": [loss_grad_name]},
                    {"scale": 1.0 / self.nranks, "bias": 0.0,
                     "bias_after_scale": True}))
            # allreduce each grad right after the op that produces it last
            produced = [g for g in op.output_arg_names() if g in grad_names]
            for g in produced:
                if self._is_last_def(block, op, g):
                    from ...framework import dtypes
                    from ...framework.program import Operator

                    if self.fp16:
                        new_ops.append(Operator(
                            block, "cast", {"X": [g]}, {"Out": [g]},
                            {"out_dtype": dtypes.to_enum("bfloat16")}))
                    new_ops.append(Operator(
                        block, "c_allreduce_sum", {"X": [g]}, {"Out": [g]},
                        {"ring_id": self.ring_id, "use_calc_stream": True}))
                    if self.fp16:
                        new_ops.append(Operator(
                            block, "cast", {"X": [g]}, {"Out": [g]},
                            {"out_dtype": dtypes.to_enum("float32")}))
        block.ops[:] = new_ops
        main_program._bump()  # direct ops[] rewrite: invalidate fingerprint
        return main_program

    @staticmethod
    def _is_last_def(block, op, name):
        seen = False
        for other in block.ops:
            if other is op:
                seen = True
                continue
            if seen and name in other.output_arg_names() \
                    and other.type != "c_allreduce_sum":
                return False
        return True


class LocalSGD:
    """Periodic parameter averaging (reference transpiler/collective.py:270).

    On TPU the step-K averaging is driven host-side: call
    ``average_step(exe, scope)`` once per train step; every k_steps-th
    call runs a tiny compiled program psum-averaging the params.
    """

    def __init__(self, nranks, k_steps=1, ring_id=0):
        self.nranks, self.k_steps, self.ring_id = nranks, k_steps, ring_id
        self._avg_program = None
        self._param_names = []
        self._step = 0

    def build_average_program(self, main_program: Program) -> Program:
        from ...framework.program import Program as P

        avg = P()
        block = avg.global_block
        for var in main_program.global_block.vars.values():
            if getattr(var, "is_parameter", False):
                self._param_names.append(var.name)
                block.create_var(name=var.name, shape=var.shape,
                                 dtype=var.dtype, persistable=True)
                block.append_op("c_allreduce_sum", {"X": var.name},
                                {"Out": var.name}, {"ring_id": self.ring_id})
                block.append_op("scale", {"X": var.name}, {"Out": var.name},
                                {"scale": 1.0 / self.nranks, "bias": 0.0})
        self._avg_program = avg
        return avg

    def average_step(self, exe, scope=None):
        """Call once per train step; averages params every k_steps calls.

        Multi-process deployment (one process per host, private params):
        the average crosses processes via the coordination service.  The
        Executor invokes this automatically after each main-program run.
        """
        self._step += 1
        if self._step % self.k_steps:
            return False
        import jax

        if jax.process_count() > 1:
            import numpy as np

            from ...framework.scope import global_scope
            from jax.experimental import multihost_utils

            scope = scope or global_scope()
            for name in self._param_names:
                v = np.asarray(scope.get_var(name))
                gathered = multihost_utils.process_allgather(v)
                scope.set_var(name, gathered.mean(axis=0).astype(v.dtype))
            return True
        if self._avg_program is not None:
            exe.run(self._avg_program, scope=scope)
        return True
