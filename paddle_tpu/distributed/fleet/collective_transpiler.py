"""Collective program transpile: insert grad-allreduce into a program.

Role parity: reference python/paddle/fluid/transpiler/collective.py —
`GradAllReduce` (:244) scales the loss grad by 1/nranks
(_insert_scale_loss_grad_ops) and inserts `c_allreduce_sum` after each
parameter gradient; `LocalSGD` (:270) periodically averages params.
TPU-native: no comm-init ops are inserted (the mesh already exists);
the c_allreduce_sum ops lower to lax.psum inside the one compiled
train-step program.
"""
from __future__ import annotations

from ...framework.program import GRAD_SUFFIX, Program


def _grad_param_pairs(block, params_grads=None):
    if params_grads:
        return [(p.name if hasattr(p, "name") else p,
                 g.name if hasattr(g, "name") else g) for p, g in params_grads]
    pairs = []
    for var in block.vars.values():
        if getattr(var, "is_parameter", False):
            gname = var.name + GRAD_SUFFIX
            if block._find_var_recursive(gname) is not None:
                pairs.append((var.name, gname))
    return pairs


def _last_writer_map(ops):
    """name -> index of the LAST op writing it (c_allreduce_sum writers
    excluded, matching the old ``_is_last_def`` contract: an in-place
    allreduce is not a new definition).  One pass over the op list —
    replaces the per-grad O(ops) rescan that made transpile O(ops**2)
    on BERT-scale programs."""
    last = {}
    for i, op in enumerate(ops):
        if op.type == "c_allreduce_sum":
            continue
        for n in op.output_arg_names():
            last[n] = i
    return last


class GradAllReduce:
    def __init__(self, nranks, ring_id=0, fuse_all_reduce=True, fp16=False,
                 fuse_grad_size_in_MB=32):
        self.nranks = nranks
        self.ring_id = ring_id
        # fp16_allreduce strategy: halve allreduce bytes by casting grads
        # to bf16 around the collective (reference
        # fp16_allreduce_optimizer.py; bf16 is the TPU-native low-precision)
        self.fp16 = fp16
        # tensor fusion (reference fuse_all_reduce_op_pass): the inserted
        # per-grad collectives are MARKED with op attrs and the
        # framework.passes FuseAllReducePass buckets them at dispatch
        # time — with fuse_all_reduce=False the ops carry no marks and
        # the exact per-grad program compiles
        self.fuse_all_reduce = bool(fuse_all_reduce)
        self.fuse_grad_size_in_MB = float(fuse_grad_size_in_MB or 32)

    def transpile(self, main_program: Program, params_grads=None,
                  loss_grad_name=None):
        if self.nranks <= 1:
            return main_program
        block = main_program.global_block
        pairs = _grad_param_pairs(block, params_grads)
        grad_names = {g for _, g in pairs}
        last_writer = _last_writer_map(block.ops)

        from ...framework.passes import (DP_LOSS_SCALE_ATTR, FUSE_SIZE_ATTR,
                                         FUSED_ALLREDUCE_ATTR)

        mark = {}
        if self.fuse_all_reduce:
            mark = {FUSED_ALLREDUCE_ATTR: True,
                    FUSE_SIZE_ATTR: self.fuse_grad_size_in_MB}

        new_ops = []
        for i, op in enumerate(block.ops):
            new_ops.append(op)
            # scale the loss grad once (reference _insert_scale_loss_grad_ops)
            if loss_grad_name and loss_grad_name in op.output_arg_names() \
                    and op.type == "fill_constant":
                from ...framework.program import Operator

                # DP_LOSS_SCALE_ATTR: the tensor-parallel meta-optimizer
                # removes this op — under GSPMD the loss is the GLOBAL
                # batch mean, so its gradient needs no 1/nranks correction
                new_ops.append(Operator(
                    block, "scale", {"X": [loss_grad_name]},
                    {"Out": [loss_grad_name]},
                    {"scale": 1.0 / self.nranks, "bias": 0.0,
                     "bias_after_scale": True,
                     DP_LOSS_SCALE_ATTR: True}))
            # allreduce each grad right after the op that produces it last
            produced = [g for g in op.output_arg_names() if g in grad_names]
            for g in produced:
                if last_writer.get(g) == i:
                    from ...framework import dtypes
                    from ...framework.program import Operator

                    if self.fp16:
                        new_ops.append(Operator(
                            block, "cast", {"X": [g]}, {"Out": [g]},
                            {"out_dtype": dtypes.to_enum("bfloat16"),
                             **mark}))
                    new_ops.append(Operator(
                        block, "c_allreduce_sum", {"X": [g]}, {"Out": [g]},
                        {"ring_id": self.ring_id, "use_calc_stream": True,
                         **mark}))
                    if self.fp16:
                        new_ops.append(Operator(
                            block, "cast", {"X": [g]}, {"Out": [g]},
                            {"out_dtype": dtypes.to_enum("float32"),
                             **mark}))
        block.ops[:] = new_ops
        main_program._bump()  # direct ops[] rewrite: invalidate fingerprint
        return main_program


class LocalSGD:
    """Periodic parameter averaging (reference transpiler/collective.py:270).

    On TPU the step-K averaging is driven host-side: call
    ``average_step(exe, scope)`` once per train step; every k_steps-th
    call runs a tiny compiled program psum-averaging the params.
    """

    def __init__(self, nranks, k_steps=1, ring_id=0):
        self.nranks, self.k_steps, self.ring_id = nranks, k_steps, ring_id
        self._avg_program = None
        self._param_names = []
        self._step = 0

    def build_average_program(self, main_program: Program) -> Program:
        from ...framework.program import Program as P

        avg = P()
        block = avg.global_block
        for var in main_program.global_block.vars.values():
            if getattr(var, "is_parameter", False):
                self._param_names.append(var.name)
                block.create_var(name=var.name, shape=var.shape,
                                 dtype=var.dtype, persistable=True)
                block.append_op("c_allreduce_sum", {"X": var.name},
                                {"Out": var.name}, {"ring_id": self.ring_id})
                block.append_op("scale", {"X": var.name}, {"Out": var.name},
                                {"scale": 1.0 / self.nranks, "bias": 0.0})
        self._avg_program = avg
        return avg

    def average_step(self, exe, scope=None):
        """Call once per train step; averages params every k_steps calls.

        Multi-process deployment (one process per host, private params):
        the average crosses processes via the coordination service.  The
        Executor invokes this automatically after each main-program run.
        """
        self._step += 1
        if self._step % self.k_steps:
            return False
        import jax

        if jax.process_count() > 1:
            import numpy as np

            from ...framework.scope import global_scope
            from jax.experimental import multihost_utils

            scope = scope or global_scope()
            for name in self._param_names:
                v = np.asarray(scope.get_var(name))
                gathered = multihost_utils.process_allgather(v)
                scope.set_var(name, gathered.mean(axis=0).astype(v.dtype))
            return True
        if self._avg_program is not None:
            exe.run(self._avg_program, scope=scope)
        return True
