"""Meta-optimizers: strategy-driven optimizer/program rewrites.

Role parity: reference fleet/meta_optimizers/ (13 classes) + the
StrategyCompiler chain (fleet/base/strategy_compiler.py:89,112).  Each
meta-optimizer declares _can_apply() against the DistributedStrategy and
wraps minimize; the compiler orders the applicable ones and the last
graph-level one performs the collective transpile.
"""
from __future__ import annotations

from ...framework.program import GRAD_SUFFIX
from .collective_transpiler import GradAllReduce, LocalSGD


class MetaOptimizerBase:
    can_be_last = False

    def __init__(self, inner_opt):
        self.inner_opt = inner_opt
        self.role_maker = None
        self.user_strategy = None

    def _set_basic_info(self, loss, role_maker, user_opt, user_strategy):
        self.loss = loss
        self.role_maker = role_maker
        self.user_opt = user_opt
        self.user_strategy = user_strategy

    def _can_apply(self) -> bool:
        return False

    def _nranks(self):
        from ..parallel_env import get_world_size

        return get_world_size()

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self.inner_opt.minimize(loss, startup_program, parameter_list,
                                       no_grad_set)


class LarsMetaOptimizer(MetaOptimizerBase):
    """Swap Momentum for LARS (reference lars_optimizer.py)."""

    def _can_apply(self):
        from ...optimizer.static_opt import MomentumOptimizer

        return (self.user_strategy.lars
                and isinstance(self.inner_opt, MomentumOptimizer))

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ...optimizer.static_opt import LarsMomentumOptimizer

        cfg = self.user_strategy.lars_configs
        opt = LarsMomentumOptimizer(
            learning_rate=self.inner_opt._learning_rate,
            momentum=getattr(self.inner_opt, "_momentum", 0.9),
            lars_coeff=cfg["lars_coeff"],
            lars_weight_decay=cfg["lars_weight_decay"],
            regularization=self.inner_opt.regularization,
            grad_clip=self.inner_opt._grad_clip)
        return opt.minimize(loss, startup_program, parameter_list, no_grad_set)


class LambMetaOptimizer(MetaOptimizerBase):
    """Swap Adam for LAMB (reference lamb_optimizer.py)."""

    def _can_apply(self):
        from ...optimizer.static_opt import AdamOptimizer

        return (self.user_strategy.lamb
                and isinstance(self.inner_opt, AdamOptimizer))

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ...optimizer.static_opt import LambOptimizer

        cfg = self.user_strategy.lamb_configs
        opt = LambOptimizer(
            learning_rate=self.inner_opt._learning_rate,
            beta1=getattr(self.inner_opt, "_beta1", 0.9),
            beta2=getattr(self.inner_opt, "_beta2", 0.999),
            epsilon=getattr(self.inner_opt, "_epsilon", 1e-6),
            lamb_weight_decay=cfg["lamb_weight_decay"],
            regularization=self.inner_opt.regularization,
            grad_clip=self.inner_opt._grad_clip)
        return opt.minimize(loss, startup_program, parameter_list, no_grad_set)


class RecomputeMetaOptimizer(MetaOptimizerBase):
    """Activation recompute (reference recompute_optimizer.py).

    TPU note: the XLA path's generic grad lowering already re-emits the
    forward under vjp, so memory-for-compute here means marking segments
    for jax.checkpoint; wired through program._recompute_checkpoints and
    honored by the scan-based pipeline executor (milestone: pipeline).
    """

    def _can_apply(self):
        return self.user_strategy.recompute

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        prog = loss.block.program
        prog._recompute_checkpoints = list(
            self.user_strategy.recompute_configs.get("checkpoints", []))
        return self.inner_opt.minimize(loss, startup_program, parameter_list,
                                       no_grad_set)


class FP16AllReduceMetaOptimizer(MetaOptimizerBase):
    """Cast grads to fp16/bf16 around the allreduce
    (reference fp16_allreduce_optimizer.py)."""

    def _can_apply(self):
        return self.user_strategy.fp16_allreduce

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        loss.block.program._fp16_allreduce = True
        return ops, params_grads


class LocalSGDMetaOptimizer(MetaOptimizerBase):
    """Periodic param averaging instead of per-step allreduce
    (reference localsgd_optimizer.py)."""

    can_be_last = True

    def _can_apply(self):
        return self.user_strategy.localsgd

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        import jax

        if jax.process_count() == 1 and self._nranks() > 1:
            # single-process SPMD keeps params replicated across the mesh,
            # so per-replica divergence between averages cannot exist —
            # localsgd would silently train on shard 0's data only.
            raise NotImplementedError(
                "strategy.localsgd needs per-replica parameter state: run "
                "one process per host (paddle_tpu.distributed.launch) so "
                "each process holds its own params, or use "
                "strategy.gradient_merge for step-K synchronization in the "
                "single-process SPMD runtime")
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        cfg = self.user_strategy.localsgd_configs
        prog = loss.block.program
        prog._localsgd = LocalSGD(jax.process_count(), k_steps=cfg["k_steps"])
        prog._localsgd.build_average_program(prog)
        return ops, params_grads


class GraphExecutionMetaOptimizer(MetaOptimizerBase):
    """The default collective DP transpile (reference
    graph_execution_optimizer.py:92 + transpiler/collective.py:244)."""

    can_be_last = True

    def _can_apply(self):
        return self._nranks() > 1

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        prog = loss.block.program
        GradAllReduce(
            self._nranks(),
            fp16=bool(getattr(prog, "_fp16_allreduce", False)),
        ).transpile(prog, params_grads,
                    loss_grad_name=loss.name + GRAD_SUFFIX)
        return ops, params_grads


META_OPTIMIZERS = [
    LarsMetaOptimizer,
    LambMetaOptimizer,
    RecomputeMetaOptimizer,
    FP16AllReduceMetaOptimizer,
    LocalSGDMetaOptimizer,
    GraphExecutionMetaOptimizer,
]


def compile_strategy(loss, role_maker, inner_opt, strategy):
    """Longest-compatible-chain ordering (reference strategy_compiler.py:89):
    each applicable meta-optimizer wraps the previous; graph-level ones
    (can_be_last) are mutually exclusive — the first applicable wins."""
    chain = inner_opt
    last_used = False
    for cls in META_OPTIMIZERS:
        mo = cls(chain)
        mo._set_basic_info(loss, role_maker, inner_opt, strategy)
        if not mo._can_apply():
            continue
        if mo.can_be_last:
            if last_used:
                continue
            last_used = True
        chain = mo
    return chain
