"""Meta-optimizers: strategy-driven optimizer/program rewrites.

Role parity: reference fleet/meta_optimizers/ (13 classes) + the
StrategyCompiler chain (fleet/base/strategy_compiler.py:89,112).  Each
meta-optimizer declares _can_apply() against the DistributedStrategy and
wraps minimize; the compiler orders the applicable ones and the last
graph-level one performs the collective transpile.
"""
from __future__ import annotations

from ...framework.program import GRAD_SUFFIX
from .collective_transpiler import GradAllReduce, LocalSGD, _last_writer_map


class MetaOptimizerBase:
    can_be_last = False

    def __init__(self, inner_opt):
        self.inner_opt = inner_opt
        self.role_maker = None
        self.user_strategy = None

    def _set_basic_info(self, loss, role_maker, user_opt, user_strategy):
        self.loss = loss
        self.role_maker = role_maker
        self.user_opt = user_opt
        self.user_strategy = user_strategy

    def _can_apply(self) -> bool:
        return False

    def _nranks(self):
        from ..parallel_env import get_world_size

        return get_world_size()

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self.inner_opt.minimize(loss, startup_program, parameter_list,
                                       no_grad_set)

    # delegation so meta-optimizers compose (a wrapping meta-opt may call
    # backward/apply_gradients on its inner chain)
    def backward(self, *args, **kwargs):
        return self.inner_opt.backward(*args, **kwargs)

    def apply_gradients(self, params_grads):
        return self.inner_opt.apply_gradients(params_grads)

    def __getattr__(self, name):
        if name == "inner_opt":  # not yet set (unpickling/deepcopy)
            raise AttributeError(name)
        return getattr(self.inner_opt, name)


class LarsMetaOptimizer(MetaOptimizerBase):
    """Swap Momentum for LARS (reference lars_optimizer.py)."""

    def _can_apply(self):
        from ...optimizer.static_opt import MomentumOptimizer

        return (self.user_strategy.lars
                and isinstance(self.inner_opt, MomentumOptimizer))

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ...optimizer.static_opt import LarsMomentumOptimizer

        cfg = self.user_strategy.lars_configs
        opt = LarsMomentumOptimizer(
            learning_rate=self.inner_opt._learning_rate,
            momentum=getattr(self.inner_opt, "_momentum", 0.9),
            lars_coeff=cfg["lars_coeff"],
            lars_weight_decay=cfg["lars_weight_decay"],
            regularization=self.inner_opt.regularization,
            grad_clip=self.inner_opt._grad_clip)
        return opt.minimize(loss, startup_program, parameter_list, no_grad_set)


class LambMetaOptimizer(MetaOptimizerBase):
    """Swap Adam for LAMB (reference lamb_optimizer.py)."""

    def _can_apply(self):
        from ...optimizer.static_opt import AdamOptimizer

        return (self.user_strategy.lamb
                and isinstance(self.inner_opt, AdamOptimizer))

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ...optimizer.static_opt import LambOptimizer

        cfg = self.user_strategy.lamb_configs
        opt = LambOptimizer(
            learning_rate=self.inner_opt._learning_rate,
            beta1=getattr(self.inner_opt, "_beta1", 0.9),
            beta2=getattr(self.inner_opt, "_beta2", 0.999),
            epsilon=getattr(self.inner_opt, "_epsilon", 1e-6),
            lamb_weight_decay=cfg["lamb_weight_decay"],
            regularization=self.inner_opt.regularization,
            grad_clip=self.inner_opt._grad_clip)
        return opt.minimize(loss, startup_program, parameter_list, no_grad_set)


class AMPMetaOptimizer(MetaOptimizerBase):
    """Mixed precision (reference amp_optimizer.py): wrap the inner
    optimizer with the static AMP decorator — program rewrite inserting
    bf16/fp16 casts per white/black lists, plus dynamic loss scaling in
    fp16 mode (amp/static_amp.py)."""

    def _can_apply(self):
        return self.user_strategy.amp

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ...amp.lists import AutoMixedPrecisionLists
        from ...amp.static_amp import decorate

        cfg = self.user_strategy.amp_configs
        lists = AutoMixedPrecisionLists(
            custom_white_list=cfg.get("custom_white_list") or None,
            custom_black_list=cfg.get("custom_black_list") or None,
            custom_black_varnames=cfg.get("custom_black_varnames") or None)
        wrapped = decorate(
            self.inner_opt,
            amp_lists=lists,
            init_loss_scaling=float(cfg.get("init_loss_scaling", 2.0 ** 15)),
            incr_every_n_steps=int(cfg.get("incr_every_n_steps", 1000)),
            decr_every_n_nan_or_inf=int(cfg.get("decr_every_n_nan_or_inf", 2)),
            incr_ratio=float(cfg.get("incr_ratio", 2.0)),
            decr_ratio=float(cfg.get("decr_ratio", 0.5)),
            use_dynamic_loss_scaling=bool(
                cfg.get("use_dynamic_loss_scaling", True)),
            # TPU-native default: bf16, no loss scaling
            use_bf16=bool(cfg.get("use_bf16", True)))
        if not wrapped._use_bf16 and not getattr(
                self.inner_opt, "supports_grad_transform", False):
            # fp16 mode drives backward/apply_gradients directly; a
            # DIRECT gradient-merge inner composes via the grad-transform
            # hook (static_amp routes unscale + scaling-state updates
            # through the merge mask), but a merge buried deeper in the
            # chain would be silently bypassed — refuse that loudly
            o = self.inner_opt
            while isinstance(o, MetaOptimizerBase):
                if isinstance(o, GradientMergeMetaOptimizer):
                    raise NotImplementedError(
                        "amp (fp16 + loss scaling) composes with "
                        "gradient_merge only when gradient_merge is the "
                        "direct inner optimizer; use bf16 amp "
                        "(amp_configs={'use_bf16': True}, the TPU "
                        "default) for this chain")
                o = o.inner_opt
        return wrapped.minimize(loss, startup_program, parameter_list,
                                no_grad_set)


class RecomputeMetaOptimizer(MetaOptimizerBase):
    """Activation recompute (reference recompute_optimizer.py +
    backward.py:689): user-marked checkpoint vars partition the forward;
    append_backward re-emits each segment behind a `recompute_barrier`
    (lax.optimization_barrier CSE fence) so XLA recomputes activations in
    the backward instead of keeping them alive.

    Scan-over-layers extras (recompute_configs ``policy`` /
    ``scan_layers``): stamped AFTER the inner minimize onto the
    program's optimizer ops (``__layer_scan__`` /
    ``__layer_scan_policy__`` — attrs, so the contract survives
    clone/proto round-trips AND re-keys every executor cache via the
    fingerprint).  They turn the executor-side LayerScanPass on for
    this program and pick the ``jax.checkpoint`` remat policy its scan
    bodies are wrapped in — extending the barrier-based recompute
    support to XLA rematerialization choices per repeated block."""

    def _can_apply(self):
        return self.user_strategy.recompute

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ...framework.passes import (LAYER_SCAN_ATTR,
                                         LAYER_SCAN_POLICY_ATTR)
        from ...framework.jax_compat import REMAT_POLICIES

        cfg = self.user_strategy.recompute_configs
        ckpts = list(cfg.get("checkpoints", []))
        policy = str(cfg.get("policy") or "")
        scan_layers = int(cfg.get("scan_layers") or 0)
        if policy and policy not in REMAT_POLICIES:
            raise ValueError(
                f"recompute_configs['policy'] must be one of "
                f"{sorted(REMAT_POLICIES)}, got {policy!r}")
        if not ckpts and not (policy or scan_layers):
            raise ValueError(
                "strategy.recompute=True needs recompute_configs with "
                "'checkpoints': [var_names] (barrier-based recompute), "
                "'scan_layers': N and/or 'policy': <remat policy> "
                "(scan-over-layers), or both")
        prog = loss.block.program
        if ckpts:
            prog._recompute_checkpoints = ckpts
        ret = self.inner_opt.minimize(loss, startup_program, parameter_list,
                                      no_grad_set)
        if policy or scan_layers:
            stamped = False
            for op in prog.global_block.ops:
                if op.type in _OPTIMIZER_OP_TYPES:
                    if scan_layers:
                        op.attrs[LAYER_SCAN_ATTR] = scan_layers
                    if policy:
                        op.attrs[LAYER_SCAN_POLICY_ATTR] = policy
                    stamped = True
            if not stamped:
                raise ValueError(
                    "recompute_configs scan_layers/policy found no "
                    "optimizer ops to stamp; minimize() must build the "
                    "training program first")
            prog._bump()
        return ret


class GradientMergeMetaOptimizer(MetaOptimizerBase):
    """Accumulate grads K steps, apply the update on every K-th step
    (reference GradientMergeOptimizer, fluid/optimizer.py:5025).

    TPU-native: no conditional_block — the update runs every step but is
    masked: merged_grad = acc * mask (mask = 1 on the K-th step, else 0),
    and every state var written by the optimizer ops is snapshot before /
    select-restored after, so momentum/adam state only advances on real
    update steps.  XLA fuses the selects; there is no control-flow
    divergence on device."""

    supports_grad_transform = True  # fp16-AMP composes through the mask

    def _can_apply(self):
        return self.user_strategy.gradient_merge

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_transform=None):
        from ...framework.program import default_startup_program
        from ...initializer import ConstantInitializer
        from ...framework import unique_name

        cfg = self.user_strategy.gradient_merge_configs
        k = int(cfg.get("k_steps", 1))
        avg = bool(cfg.get("avg", True))
        if k <= 1:
            if grad_transform is None:
                return self.inner_opt.minimize(loss, startup_program,
                                               parameter_list, no_grad_set)
            # degenerate merge still owes the caller its transform (fp16
            # AMP's unscale + overflow check ride it — dropping it would
            # apply loss-scaled gradients)
            pgs = self.inner_opt.backward(loss, startup_program,
                                          parameter_list, no_grad_set)
            pgs = grad_transform(pgs)
            return self.inner_opt.apply_gradients(pgs), pgs

        params_grads = self.inner_opt.backward(
            loss, startup_program, parameter_list, no_grad_set)
        block = loss.block.program.global_block
        startup = startup_program or default_startup_program()

        def persistent(name, shape, value):
            v = block.create_var(name=name, shape=list(shape),
                                 dtype="float32", persistable=True,
                                 stop_gradient=True)
            sv = startup.global_block.create_var(
                name=name, shape=list(shape), dtype="float32",
                persistable=True)
            ConstantInitializer(value)(sv, startup.global_block)
            return v

        step = persistent(unique_name.generate("gm_step"), [1], 0.0)
        block.append_op("increment", {"X": [step.name]},
                        {"Out": [step.name]}, {"step": 1.0})
        k_const = block.create_var(name=unique_name.generate("gm_k"),
                                   shape=[1], dtype="float32",
                                   stop_gradient=True)
        block.append_op("fill_constant", {}, {"Out": [k_const.name]},
                        {"shape": [1], "dtype": "float32", "value": float(k)})
        cond = block.create_var(name=unique_name.generate("gm_cond"),
                                shape=[1], dtype="bool", stop_gradient=True)
        block.append_op("equal", {"X": [step.name], "Y": [k_const.name]},
                        {"Out": [cond.name]})
        mask = block.create_var(name=unique_name.generate("gm_mask"),
                                shape=[1], dtype="float32",
                                stop_gradient=True)
        block.append_op("cast", {"X": [cond.name]}, {"Out": [mask.name]},
                        {"out_dtype": "float32"})
        # step wraps back to 0 on update steps: step *= (1 - mask)
        inv = block.create_var(name=unique_name.generate("gm_inv"),
                               shape=[1], dtype="float32",
                               stop_gradient=True)
        block.append_op("scale", {"X": [mask.name]}, {"Out": [inv.name]},
                        {"scale": -1.0, "bias": 1.0, "bias_after_scale": True})
        block.append_op("elementwise_mul",
                        {"X": [step.name], "Y": [inv.name]},
                        {"Out": [step.name]}, {"axis": -1})

        merged = []
        acc_names = []
        for p, g in params_grads:
            acc = persistent(unique_name.generate(p.name + "_gm_acc"),
                             p.shape, 0.0)
            acc_names.append(acc.name)
            # __gm_grad__ marks the accumulate op for the sharding
            # transpiler (an op attr, not a python side channel, so the
            # linkage survives clone/proto round-trips like
            # __sharded_accumulators__ does)
            block.append_op("elementwise_add",
                            {"X": [acc.name], "Y": [g.name]},
                            {"Out": [acc.name]},
                            {"axis": -1, "__gm_grad__": g.name})
            mg = block.create_var(name=unique_name.generate(g.name + ".gm"),
                                  shape=list(p.shape), dtype="float32",
                                  stop_gradient=True)
            block.append_op("elementwise_mul",
                            {"X": [acc.name], "Y": [mask.name]},
                            {"Out": [mg.name]}, {"axis": -1})
            if avg:
                block.append_op("scale", {"X": [mg.name]}, {"Out": [mg.name]},
                                {"scale": 1.0 / k, "bias": 0.0,
                                 "bias_after_scale": True})
            merged.append((p, block.var(mg.name)))

        # optimizer ops run every step on the masked grad; snapshot every
        # state var they overwrite and select-restore on non-update steps.
        # The mark sits BEFORE the grad transform so state the transform
        # writes (e.g. fp16-AMP's loss-scaling counters, which would
        # otherwise advance on masked zero-grads every step) is snapshot
        # and select-restored exactly like optimizer state.
        mark = len(block.ops)
        if grad_transform is not None:
            merged = grad_transform(merged)
        opt_ops = self.inner_opt.apply_gradients(merged)
        appended = block.ops[mark:]
        state_names = []
        seen = set()
        for op in appended:
            for n in op.output_arg_names():
                if n in seen:
                    continue
                var = block._find_var_recursive(n)
                if var is not None and var.persistable:
                    seen.add(n)
                    state_names.append(n)
        backups = {}
        insert_at = mark
        for n in state_names:
            b = n + ".gm_backup"
            var = block._find_var_recursive(n)
            block.create_var(name=b, shape=list(var.shape), dtype=var.dtype,
                             stop_gradient=True)
            from ...framework.program import Operator

            bop = Operator(block, "assign", {"X": [n]}, {"Out": [b]})
            block.ops.insert(insert_at, bop)
            insert_at += 1
            backups[n] = b
        for n, b in backups.items():
            # n = mask*n_updated + (1-mask)*backup
            upd = n + ".gm_upd"
            var = block._find_var_recursive(n)
            block.create_var(name=upd, shape=list(var.shape),
                             dtype=var.dtype, stop_gradient=True)
            block.append_op("elementwise_mul", {"X": [n], "Y": [mask.name]},
                            {"Out": [upd]}, {"axis": -1})
            keep = b + ".keep"
            block.create_var(name=keep, shape=list(var.shape),
                             dtype=var.dtype, stop_gradient=True)
            block.append_op("elementwise_mul", {"X": [b], "Y": [inv.name]},
                            {"Out": [keep]}, {"axis": -1})
            block.append_op("elementwise_add", {"X": [upd], "Y": [keep]},
                            {"Out": [n]}, {"axis": -1})

        # accumulators reset after an applied update: acc *= (1 - mask)
        for acc_name in acc_names:
            block.append_op("elementwise_mul",
                            {"X": [acc_name], "Y": [inv.name]},
                            {"Out": [acc_name]}, {"axis": -1})
        loss.block.program._bump()
        return opt_ops, params_grads


class DGCMetaOptimizer(MetaOptimizerBase):
    """Deep gradient compression (reference
    fleet/meta_optimizers/dgc_optimizer.py + operators/dgc_op.cc):
    per-param momentum/residual accumulators feed a top-k sparsifying
    `dgc` op between backward and the optimizer apply; the sparsified
    grad is what rides the data-parallel allreduce.

    Pair with a plain SGD inner optimizer: the momentum correction
    lives INSIDE the dgc op's U accumulator (the reference's
    DGCMomentumOptimizer collapses both for the same reason — applying
    an outer momentum too would double it).

    Known simplification: the sparsity ratio is CONSTANT — only
    ``dgc_configs["sparsity"][0]`` is honored.  The reference ramps
    sparsity over ``rampup_step`` period steps (dgc_optimizer.py walks
    the sparsity list as warmup progresses); until that period-sparsity
    ramp lands here, pre-rampup steps pass dense grads through
    untouched (see the ``dgc`` lowering's early-return contract) and
    post-rampup steps jump straight to the final ratio."""

    def _can_apply(self):
        return self.user_strategy.dgc

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ...framework import unique_name
        from ...framework.program import default_startup_program
        from ...initializer import ConstantInitializer

        cfg = self.user_strategy.dgc_configs or {}
        ratio = 1.0 - float((cfg.get("sparsity") or [0.999])[0])
        rampup_begin = float(cfg.get("rampup_begin_step", 0))
        m = 0.9  # reference DGCMomentumOptimizer default; DGCConfig
        # carries no momentum field (distributed_strategy.proto)

        params_grads = self.inner_opt.backward(
            loss, startup_program, parameter_list, no_grad_set)
        block = loss.block.program.global_block
        startup = startup_program or default_startup_program()

        def persistent(name, shape, value):
            v = block.create_var(name=name, shape=list(shape),
                                 dtype="float32", persistable=True,
                                 stop_gradient=True)
            sv = startup.global_block.create_var(
                name=name, shape=list(shape), dtype="float32",
                persistable=True)
            ConstantInitializer(value)(sv, startup.global_block)
            return v

        step = persistent(unique_name.generate("dgc_step"), [1], 0.0)
        block.append_op("increment", {"X": [step.name]},
                        {"Out": [step.name]}, {"step": 1.0})

        compressed = []
        for p, g in params_grads:
            u = persistent(unique_name.generate(p.name + "_dgc_u"),
                           p.shape, 0.0)
            v = persistent(unique_name.generate(p.name + "_dgc_v"),
                           p.shape, 0.0)
            enc = block.create_var(
                name=unique_name.generate(g.name + ".dgc"),
                shape=list(p.shape), dtype="float32", stop_gradient=True)
            block.append_op(
                "dgc",
                {"Grad": [g.name], "U": [u.name], "V": [v.name],
                 "CurrentStep": [step.name]},
                {"U_out": [u.name], "V_out": [v.name],
                 "EncodeGrad": [enc.name], "Grad_out": [enc.name]},
                {"m": m, "ratio": ratio,
                 "rampup_begin_step": rampup_begin})
            compressed.append((p, block.var(enc.name)))
        opt_ops = self.inner_opt.apply_gradients(compressed)
        loss.block.program._bump()
        return opt_ops, params_grads


class FP16AllReduceMetaOptimizer(MetaOptimizerBase):
    """Cast grads to fp16/bf16 around the allreduce
    (reference fp16_allreduce_optimizer.py)."""

    def _can_apply(self):
        return self.user_strategy.fp16_allreduce

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        loss.block.program._fp16_allreduce = True
        return ops, params_grads


class LocalSGDMetaOptimizer(MetaOptimizerBase):
    """Periodic param averaging instead of per-step allreduce
    (reference localsgd_optimizer.py)."""

    can_be_last = True

    def _can_apply(self):
        return self.user_strategy.localsgd

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        import jax

        if jax.process_count() == 1 and self._nranks() > 1:
            # single-process SPMD keeps params replicated across the mesh,
            # so per-replica divergence between averages cannot exist —
            # localsgd would silently train on shard 0's data only.
            raise NotImplementedError(
                "strategy.localsgd needs per-replica parameter state: run "
                "one process per host (paddle_tpu.distributed.launch) so "
                "each process holds its own params, or use "
                "strategy.gradient_merge for step-K synchronization in the "
                "single-process SPMD runtime")
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        cfg = self.user_strategy.localsgd_configs
        prog = loss.block.program
        prog._localsgd = LocalSGD(jax.process_count(), k_steps=cfg["k_steps"])
        prog._localsgd.build_average_program(prog)
        return ops, params_grads


_OPTIMIZER_OP_TYPES = {
    "sgd", "momentum", "adam", "adamw", "adamax", "adagrad", "adadelta",
    "rmsprop", "ftrl", "lamb", "lars_momentum", "dgc_momentum", "dpsgd",
}

# param-shaped accumulator input slots per optimizer op (reference
# operators/optimizers/*_op.cc input declarations); Beta*Pow and loss-
# scale scalars are [1]-shaped and deliberately absent
_OPTIMIZER_ACC_SLOTS = {
    "sgd": (),
    "momentum": ("Velocity",),
    "lars_momentum": ("Velocity",),
    "dgc_momentum": ("Velocity",),
    "adam": ("Moment1", "Moment2"),
    "adamw": ("Moment1", "Moment2"),
    "lamb": ("Moment1", "Moment2"),
    "adamax": ("Moment", "InfNorm"),
    "adagrad": ("Moment",),
    "adadelta": ("AvgSquaredGrad", "AvgSquaredUpdate"),
    "rmsprop": ("MeanSquare", "MeanGrad", "Moment"),
    "ftrl": ("SquaredAccumulator", "LinearAccumulator"),
}


class ShardingMetaOptimizer(MetaOptimizerBase):
    """ZeRO-1 optimizer-state sharding (reference
    fleet/meta_optimizers/sharding_optimizer.py:33).

    TPU-native form: instead of assigning whole params to ranks and
    broadcasting (reference _split_program/_add_broadcast_allreduce), every
    param/grad with dim0 divisible by the dp degree is sliced evenly —
    each rank updates its 1/nranks shard with its shard of the (allreduced)
    grad, optimizer accumulators live sharded over the mesh (in/out specs
    P('dp') in the SPMD executor), and `c_allgather` re-assembles the
    updated param for the next forward.  Memory for optimizer state drops
    ~linearly with the dp degree."""

    can_be_last = True  # replaces the plain DP transpile

    def _can_apply(self):
        return self.user_strategy.sharding and self._nranks() > 1

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        prog = loss.block.program
        n = self._nranks()
        sharded_params = self._sharded_param_set(prog, params_grads, n)
        if not sharded_params:
            raise ValueError(
                "strategy.sharding=True but no parameter has dim0 divisible "
                f"by the dp degree {n}; sharding would be a no-op")
        # gradient_merge composition: the merge chain moves into shard
        # space — acc/merged ride the grad SHARD (c_reducescatter output)
        # and join the sharded optimizer state, so merge-accumulator
        # memory also drops by the dp degree
        gm_map = self._collect_gm_map(prog.global_block)
        self._transpile_grads(prog, params_grads, sharded_params,
                              loss.name + GRAD_SUFFIX, gm_map=gm_map)
        self._shard_optimizer_ops(prog, n, sharded_params, gm_map=gm_map)
        return ops, params_grads

    @staticmethod
    def _collect_gm_map(block):
        """Reconstruct {orig grad -> {acc, merged}} from the __gm_grad__
        attrs the merge optimizer stamps on its accumulate ops (attrs,
        not a python side channel, so a clone/proto round-trip between
        the two meta-optimizers cannot lose the linkage)."""
        out = {}
        for i, op in enumerate(block.ops):
            g = op.attr("__gm_grad__", None)
            if not g:
                continue
            acc = op.inputs["X"][0]
            for op2 in block.ops[i + 1:]:
                if op2.type == "elementwise_mul" \
                        and op2.inputs.get("X") == [acc] \
                        and op2.outputs.get("Out") != [acc]:
                    out[g] = {"acc": acc,
                              "merged": op2.outputs["Out"][0]}
                    break
        return out

    def _sharded_param_set(self, prog, params_grads, nranks):
        block = prog.global_block
        out = set()
        for p, _ in params_grads:
            pvar = block._find_var_recursive(
                p.name if hasattr(p, "name") else p)
            if pvar is not None and pvar.shape \
                    and int(pvar.shape[0]) % nranks == 0:
                out.add(pvar.name)
        return out

    def _transpile_grads(self, prog, params_grads, sharded_params,
                         loss_grad_name, gm_map=None):
        """ZeRO-1 grad comm: `c_reducescatter` for sharded params (each
        rank receives only its grad shard — half the volume of
        allreduce+slice), plain `c_allreduce_sum` for params left
        replicated.  Loss-grad 1/nranks scaling as in GradAllReduce."""
        from ...framework import dtypes
        from ...framework.passes import DP_LOSS_SCALE_ATTR
        from ...framework.program import Operator

        n = self._nranks()
        fp16 = bool(getattr(prog, "_fp16_allreduce", False))
        block = prog.global_block
        grad_to_param = {}
        for p, g in params_grads:
            grad_to_param[g.name if hasattr(g, "name") else g] = (
                p.name if hasattr(p, "name") else p)

        last_writer = _last_writer_map(block.ops)
        new_ops = []
        for i, op in enumerate(block.ops):
            new_ops.append(op)
            if loss_grad_name in op.output_arg_names() \
                    and op.type == "fill_constant":
                new_ops.append(Operator(
                    block, "scale", {"X": [loss_grad_name]},
                    {"Out": [loss_grad_name]},
                    {"scale": 1.0 / n, "bias": 0.0,
                     "bias_after_scale": True,
                     DP_LOSS_SCALE_ATTR: True}))
            for g in op.output_arg_names():
                pname = grad_to_param.get(g)
                if pname is None or last_writer.get(g) != i:
                    continue
                comm_in = g
                if fp16:
                    new_ops.append(Operator(
                        block, "cast", {"X": [g]}, {"Out": [g]},
                        {"out_dtype": dtypes.to_enum("bfloat16")}))
                if pname in sharded_params:
                    gvar = block._find_var_recursive(g)
                    g_shard = g + "@SHARD"
                    if not block.has_var(g_shard):
                        shape = list(gvar.shape) if gvar is not None else []
                        if shape:
                            shape[0] = int(shape[0]) // n
                        block.create_var(name=g_shard, shape=shape,
                                         dtype=(gvar.dtype if gvar else
                                                "float32"),
                                         stop_gradient=True)
                    new_ops.append(Operator(
                        block, "c_reducescatter", {"X": [comm_in]},
                        {"Out": [g_shard]}, {"ring_id": 0}))
                    if fp16:
                        new_ops.append(Operator(
                            block, "cast", {"X": [g_shard]},
                            {"Out": [g_shard]},
                            {"out_dtype": dtypes.to_enum("float32")}))
                else:
                    new_ops.append(Operator(
                        block, "c_allreduce_sum", {"X": [comm_in]},
                        {"Out": [g]}, {"ring_id": 0}))
                    if fp16:
                        new_ops.append(Operator(
                            block, "cast", {"X": [g]}, {"Out": [g]},
                            {"out_dtype": dtypes.to_enum("float32")}))
        # gradient_merge composition: the merge accumulation must consume
        # the grad SHARD (its X/Out accumulator joins the sharded state),
        # not the pre-scatter full grad
        if gm_map:
            for op in new_ops:
                if op.type != "elementwise_add":
                    continue
                y = op.inputs.get("Y", [])
                if len(y) == 1 and y[0] in gm_map \
                        and grad_to_param.get(y[0]) in sharded_params \
                        and op.inputs.get("X") == [gm_map[y[0]]["acc"]]:
                    op.inputs["Y"] = [y[0] + "@SHARD"]
        block.ops[:] = new_ops
        prog._bump()

    def _shard_optimizer_ops(self, prog, nranks, sharded_params,
                             gm_map=None):
        from ...framework.program import Operator

        block = prog.global_block
        # merged-grad name -> its accumulator (gradient_merge composition)
        merged_to_acc = {info["merged"]: info["acc"]
                         for info in (gm_map or {}).values()}
        new_ops = []
        for op in block.ops:
            if op.type not in _OPTIMIZER_OP_TYPES:
                new_ops.append(op)
                continue
            pnames = op.inputs.get("Param", [])
            gnames = op.inputs.get("Grad", [])
            if len(pnames) != 1 or len(gnames) != 1 \
                    or pnames[0] not in sharded_params:
                new_ops.append(op)
                continue
            pname, gname = pnames[0], gnames[0]
            pvar = block._find_var_recursive(pname)
            shard_shape = [int(pvar.shape[0]) // nranks] + [
                int(s) for s in pvar.shape[1:]]
            p_shard = pname + "@SHARD"
            # a merged grad already lives in shard space (the merge chain
            # consumed the reducescatter output); plain grads rewire to
            # the @SHARD var the scatter produced
            g_shard = gname if gname in merged_to_acc \
                else gname + "@SHARD"
            if not block.has_var(p_shard):
                block.create_var(name=p_shard, shape=shard_shape,
                                 dtype=pvar.dtype, stop_gradient=True)
            new_ops.append(Operator(block, "c_shard_slice",
                                    {"X": [pname]}, {"Out": [p_shard]}, {}))
            # rewire the update to run on the local shard; accumulators
            # (same shape as the param, read & written) become sharded
            # state, recorded ON the op so the program is self-describing
            # (survives clone/proto round-trips, unlike a python attr)
            outs_set = set(op.output_arg_names())
            sharded_accs = []
            acc_slots = _OPTIMIZER_ACC_SLOTS.get(op.type)
            for slot, names in list(op.inputs.items()):
                if slot == "Param":
                    op.inputs[slot] = [p_shard]
                elif slot == "Grad":
                    op.inputs[slot] = [g_shard]
                elif acc_slots is not None:
                    # exact accumulator identification by slot name —
                    # a same-shaped persistable input in a non-acc slot
                    # (e.g. a MasterParam) must NOT be sharded blindly
                    if slot in acc_slots:
                        sharded_accs.extend(names)
                else:
                    # unknown optimizer type: fall back to the shape
                    # heuristic (persistable, param-shaped, read+written)
                    for nm in names:
                        v = block._find_var_recursive(nm)
                        if (v is not None and v.persistable
                                and tuple(v.shape) == tuple(pvar.shape)
                                and nm in outs_set):
                            sharded_accs.append(nm)
            for slot, names in list(op.outputs.items()):
                op.outputs[slot] = [p_shard if nm == pname else nm
                                    for nm in names]
            if gname in merged_to_acc:
                # the merge accumulator carries shard-space values:
                # record it so the executor gives it a P('dp') spec —
                # merge memory drops by the dp degree like other state
                sharded_accs.append(merged_to_acc[gname])
            op.attrs["__sharded_accumulators__"] = sharded_accs
            new_ops.append(op)
            new_ops.append(Operator(block, "c_allgather",
                                    {"X": [p_shard]}, {"Out": [pname]},
                                    {"ring_id": 0}))
        block.ops[:] = new_ops
        prog._bump()


class PipelineMetaOptimizer(MetaOptimizerBase):
    """GPipe pipeline parallelism (reference
    fleet/meta_optimizers/pipeline_optimizer.py:90 + fluid
    PipelineOptimizer optimizer.py:3695).  Wraps the inner optimizer with
    paddle_tpu.optimizer.PipelineOptimizer; the program must be built with
    device_guard('stage:N') annotations and executed over a mesh with a
    'pp' axis (distributed/pipeline.py)."""

    can_be_last = True  # graph-level: replaces the plain DP transpile

    def _can_apply(self):
        return self.user_strategy.pipeline

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ..parallel_env import get_mesh
        from ...optimizer.pipeline_opt import PipelineOptimizer

        mesh = get_mesh()
        if mesh is not None and "pp" not in mesh.axis_names:
            raise ValueError(
                "strategy.pipeline needs a mesh with a 'pp' axis; build it "
                "with init_parallel_env(axis_names=('pp',)) or "
                "set_mesh(Mesh(devs, ('pp',)))")
        cfg = self.user_strategy.pipeline_configs
        k = int(cfg.get("micro_batch", 1))
        return PipelineOptimizer(self.inner_opt, num_microbatches=k).minimize(
            loss, startup_program, parameter_list, no_grad_set)


class TensorParallelMetaOptimizer(MetaOptimizerBase):
    """Tensor-parallel (Megatron-style intra-layer) sharding over a
    named dp×mp mesh — reference
    fleet/meta_optimizers/tensor_parallel_optimizer.py role, GSPMD-
    native form.

    Outermost wrapper (NOT a can_be_last graph-level optimizer): it
    composes with whichever graph-level chain applied — the plain DP
    transpile, ZeRO-1 sharding, fused allreduce, AMP, recompute — by
    stamping the partition-rule contract onto the program's optimizer
    ops (``TP_RULES_ATTR``/``TP_DEGREE_ATTR``, surviving clone/proto
    round-trips and re-keying every executor cache via the
    fingerprint).  The executor-side ``ShardingPropagationPass`` turns
    the rules into a :class:`~paddle_tpu.framework.passes.TPShardingPlan`
    and the Executor compiles through jit + ``NamedSharding``.

    The one program rewrite done HERE: the dp transpile's 1/nranks
    loss-grad scale op (marked ``DP_LOSS_SCALE_ATTR``) is removed —
    under GSPMD the traced loss is the global-batch mean, so its
    gradient is already exact; keeping the scale would shrink every
    gradient by the dp degree."""

    def _can_apply(self):
        return self.user_strategy.tensor_parallel

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ...framework.passes import (DEFAULT_MEGATRON_RULES,
                                         DP_LOSS_SCALE_ATTR, TP_DEGREE_ATTR,
                                         TP_RULES_ATTR, decode_spec,
                                         encode_spec)
        from ..parallel_env import get_mesh

        strat = self.user_strategy
        if strat.localsgd:
            # pipeline now COMPOSES (the dp×mp×pp mesh: pipeline stages
            # partition the block, tp rules shard within each stage's
            # blocks — distributed/pipeline.py manual Megatron path);
            # localsgd remains genuinely unsupported: its periodic
            # host-side parameter averaging runs between executor calls
            # and has no mp-sharded form here
            raise NotImplementedError(
                "strategy.tensor_parallel does not compose with "
                "strategy.localsgd yet: both re-own program "
                "execution; unset one")
        mesh = get_mesh()
        if mesh is not None and "mp" not in mesh.axis_names:
            raise ValueError(
                "strategy.tensor_parallel needs a mesh with an 'mp' "
                "axis; build it with init_parallel_env(mesh_shape="
                "(dp, mp), axis_names=('dp', 'mp'))")
        if strat.pipeline and mesh is not None \
                and "pp" not in mesh.axis_names:
            raise ValueError(
                "strategy.tensor_parallel + strategy.pipeline needs a "
                "mesh with BOTH 'mp' and 'pp' axes; build it with "
                "init_parallel_env(mesh_shape=(dp, mp, pp), "
                "axis_names=('dp', 'mp', 'pp'))")

        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)

        cfg = strat.tensor_parallel_configs or {}
        # proto default is 1 ("unset"): 0 in the stamp means "use the
        # mesh's mp axis size"; an explicit degree >= 2 is VALIDATED
        # against the mesh at dispatch time
        degree = int(cfg.get("tensor_parallel_degree") or 0)
        if degree <= 1:
            degree = 0
        rules = cfg.get("partition_rules") or DEFAULT_MEGATRON_RULES
        encoded = []
        for pat, spec in rules:
            if not isinstance(spec, str):
                spec = encode_spec(spec)
            decode_spec(spec)  # validate early: bad specs fail HERE
            encoded.append(f"{pat}\t{spec}")

        prog = loss.block.program
        block = prog.global_block
        block.ops[:] = [op for op in block.ops
                        if not op.attr(DP_LOSS_SCALE_ATTR)]
        stamped = False
        for op in block.ops:
            if op.type in _OPTIMIZER_OP_TYPES:
                op.attrs[TP_RULES_ATTR] = list(encoded)
                op.attrs[TP_DEGREE_ATTR] = degree
                stamped = True
        if not stamped:
            raise ValueError(
                "strategy.tensor_parallel found no optimizer ops to "
                "stamp its partition rules on; minimize() must build "
                "the training program first")
        prog._bump()
        return ops, params_grads


class ExpertParallelMetaOptimizer(MetaOptimizerBase):
    """Expert parallelism (mixture-of-experts) over a named mesh with an
    'ep' axis — the reference's incubate MoE distributed layer, GSPMD-
    native form.

    Outermost wrapper like TensorParallelMetaOptimizer: it composes
    with whichever graph-level chain applied by stamping
    ``EP_DEGREE_ATTR`` onto the program's optimizer ops; the executor-
    side ``ShardingPropagationPass`` then seeds ``P('ep', ...)`` on
    every moe_ffn op's stacked expert weights, stamps the all-to-all
    anchors, and refuses ep-sharded consumers outside the routed-FFN
    family.  The dp loss-grad scale op is removed here for the same
    reason as the tp meta-optimizer: under GSPMD the traced loss is the
    global-batch mean already."""

    def _can_apply(self):
        return self.user_strategy.expert_parallel

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ...framework.passes import DP_LOSS_SCALE_ATTR, EP_DEGREE_ATTR
        from ..parallel_env import get_mesh

        strat = self.user_strategy
        if strat.localsgd:
            raise NotImplementedError(
                "strategy.expert_parallel does not compose with "
                "strategy.localsgd yet: localsgd's host-side parameter "
                "averaging has no ep-sharded form here; unset one")
        mesh = get_mesh()
        if mesh is not None and "ep" not in mesh.axis_names:
            raise ValueError(
                "strategy.expert_parallel needs a mesh with an 'ep' "
                "axis; build it with init_parallel_env(mesh_shape="
                "(dp, ep), axis_names=('dp', 'ep')) or FLAGS_ep_degree")
        if strat.pipeline and mesh is not None \
                and "pp" not in mesh.axis_names:
            raise ValueError(
                "strategy.expert_parallel + strategy.pipeline needs a "
                "mesh with BOTH 'ep' and 'pp' axes; build it with "
                "init_parallel_env(mesh_shape=(dp, ep, pp), "
                "axis_names=('dp', 'ep', 'pp'))")

        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)

        cfg = strat.expert_parallel_configs or {}
        # 0 in the stamp means "use the mesh's ep axis size"; an
        # explicit degree >= 2 is VALIDATED against the mesh at
        # dispatch time (ShardingPropagationPass)
        degree = int(cfg.get("expert_parallel_degree") or 0)
        if degree <= 1:
            degree = 0

        prog = loss.block.program
        block = prog.global_block
        if not any(op.type == "moe_ffn" for op in block.ops):
            raise ValueError(
                "strategy.expert_parallel found no moe_ffn ops to "
                "shard; build the model with layers.moe_ffn(...) or "
                "unset the strategy")
        block.ops[:] = [op for op in block.ops
                        if not op.attr(DP_LOSS_SCALE_ATTR)]
        stamped = False
        for op in block.ops:
            if op.type in _OPTIMIZER_OP_TYPES:
                op.attrs[EP_DEGREE_ATTR] = degree
                stamped = True
        if not stamped:
            raise ValueError(
                "strategy.expert_parallel found no optimizer ops to "
                "stamp its degree on; minimize() must build the "
                "training program first")
        prog._bump()
        return ops, params_grads


class GraphExecutionMetaOptimizer(MetaOptimizerBase):
    """The default collective DP transpile (reference
    graph_execution_optimizer.py:92 + transpiler/collective.py:244)."""

    can_be_last = True

    def _can_apply(self):
        return self._nranks() > 1

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        prog = loss.block.program
        strat = self.user_strategy
        GradAllReduce(
            self._nranks(),
            fuse_all_reduce=bool(strat.fuse_all_reduce_ops)
            if strat is not None else True,
            fuse_grad_size_in_MB=(strat.fuse_grad_size_in_MB or 32)
            if strat is not None else 32,
            fp16=bool(getattr(prog, "_fp16_allreduce", False)),
        ).transpile(prog, params_grads,
                    loss_grad_name=loss.name + GRAD_SUFFIX)
        return ops, params_grads


META_OPTIMIZERS = [
    LarsMetaOptimizer,
    LambMetaOptimizer,
    # GradientMerge innermost of the wrappers: it drives backward/apply
    # directly, so program-rewrite metas (AMP) must run outside it
    GradientMergeMetaOptimizer,
    DGCMetaOptimizer,
    AMPMetaOptimizer,
    RecomputeMetaOptimizer,
    FP16AllReduceMetaOptimizer,
    LocalSGDMetaOptimizer,
    PipelineMetaOptimizer,  # graph-level; wins over plain DP when set
    ShardingMetaOptimizer,  # graph-level; wins over plain DP when set
    GraphExecutionMetaOptimizer,
    # OUTERMOST (wraps the graph-level winner): stamps the tensor-
    # parallel rule contract after the dp/ZeRO transpile ran, so it
    # composes with fused-allreduce, AMP, recompute, and ZeRO chains
    TensorParallelMetaOptimizer,
    # expert parallelism rides the same GSPMD substrate and the same
    # outermost position (stamps after every transpile, composes with
    # tp — 'ep' and 'mp' shard disjoint weight families)
    ExpertParallelMetaOptimizer,
]

# strategy flags with no implementation yet: refuse loudly rather than
# silently training without the requested behavior (the reference raises
# when a meta-optimizer is unavailable too)
_UNSUPPORTED_FLAGS = ("a_sync", "elastic", "sequence_parallel")


def compile_strategy(loss, role_maker, inner_opt, strategy):
    """Longest-compatible-chain ordering (reference strategy_compiler.py:89):
    each applicable meta-optimizer wraps the previous; graph-level ones
    (can_be_last) are mutually exclusive — the first applicable wins."""
    for flag in _UNSUPPORTED_FLAGS:
        if getattr(strategy, flag, False):
            raise NotImplementedError(
                f"DistributedStrategy.{flag} is not implemented in the TPU "
                f"runtime; unset it (silently ignoring it would train "
                f"without the requested behavior)")
    chain = inner_opt
    last_used = False
    applied = set()
    for cls in META_OPTIMIZERS:
        mo = cls(chain)
        mo._set_basic_info(loss, role_maker, inner_opt, strategy)
        if not mo._can_apply():
            continue
        if mo.can_be_last:
            if last_used:
                continue
            last_used = True
        applied.add(cls)
        chain = mo
    # graph-level strategies must not be silently dropped when another
    # graph-level meta-optimizer won the can_be_last slot
    graph_level = {"localsgd": LocalSGDMetaOptimizer,
                   "pipeline": PipelineMetaOptimizer,
                   "sharding": ShardingMetaOptimizer}
    winner = next((name for name, cls in graph_level.items()
                   if cls in applied), None)
    for name, cls in graph_level.items():
        if getattr(strategy, name, False) and cls not in applied:
            if winner is not None:
                reason = (f"it conflicts with strategy.{winner} (both are "
                          f"graph-level; only one can transpile the program)")
            else:
                reason = "it needs a data-parallel degree > 1"
            raise ValueError(
                f"strategy.{name}=True could not be applied: {reason}")
    return chain
