"""RoleMaker: cluster topology from env vars.

Role parity: reference fleet/base/role_maker.py:33 (PaddleCloudRoleMaker
env parsing :363) — PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS.  The reference's embedded Gloo rendezvous
(:172) is replaced by jax.distributed's coordination service, which
init_parallel_env stands up; the barrier/all_reduce helpers here are
host-level conveniences over it.
"""
from __future__ import annotations

import os


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def _is_worker(self):
        raise NotImplementedError

    def _worker_num(self):
        raise NotImplementedError

    def _worker_index(self):
        raise NotImplementedError

    def _is_first_worker(self):
        return self._is_worker() and self._worker_index() == 0


class PaddleCloudRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
        self._size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._endpoints = [e for e in eps.split(",") if e]
        self._role = Role.WORKER

    def _is_worker(self):
        return self._role == Role.WORKER

    def _is_server(self):
        return self._role == Role.SERVER

    def _worker_num(self):
        return self._size

    def _worker_index(self):
        return self._rank

    def _get_trainer_endpoints(self):
        return list(self._endpoints)

    def _barrier(self, comm_world="worker"):
        # the coordination service barrier (process level)
        import jax

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("fleet_barrier")

    def _all_gather(self, obj, comm_world="worker"):
        import jax

        if jax.process_count() <= 1:
            return [obj]
        from jax.experimental import multihost_utils

        return list(multihost_utils.broadcast_one_to_all(obj))


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, current_id=0, worker_num=1, role=Role.WORKER, **kwargs):
        super().__init__(is_collective=True)
        self._rank = current_id
        self._size = worker_num
        self._role = role
