"""DistributedStrategy: python façade over the strategy proto.

Role parity: reference
python/paddle/distributed/fleet/base/distributed_strategy.py:101 backed
by framework/distributed_strategy.proto:110 — same property names, same
serializability.
"""
from __future__ import annotations

from ... import distributed_strategy_pb2 as pb


def _is_repeated(field):
    # FieldDescriptor.is_repeated exists from protobuf 5.26 (where
    # .label is deprecated); older protobufs only have .label
    prop = getattr(field, "is_repeated", None)
    return prop if prop is not None else \
        field.label == field.LABEL_REPEATED


def _config_to_dict(msg):
    out = {}
    for field in msg.DESCRIPTOR.fields:
        v = getattr(msg, field.name)
        if _is_repeated(field):
            v = list(v)
        out[field.name] = v
    return out


def _dict_to_config(msg, configs: dict):
    for k, v in (configs or {}).items():
        field = msg.DESCRIPTOR.fields_by_name.get(k)
        if field is None:
            raise ValueError(
                f"unknown config key {k!r} for {msg.DESCRIPTOR.name}; valid: "
                f"{sorted(msg.DESCRIPTOR.fields_by_name)}")
        if _is_repeated(field):
            del getattr(msg, k)[:]
            getattr(msg, k).extend(v)
        else:
            setattr(msg, k, v)


def _bool_prop(name):
    def get(self):
        return getattr(self._proto, name)

    def set(self, v):
        setattr(self._proto, name, bool(v))

    return property(get, set)


def _config_prop(name):
    def get(self):
        return _config_to_dict(getattr(self._proto, name))

    def set(self, configs):
        _dict_to_config(getattr(self._proto, name), configs)

    return property(get, set)


class DistributedStrategy:
    def __init__(self):
        self._proto = pb.DistributedStrategy()

    # serialization parity (reference save_to_prototxt/load_from_prototxt)
    def save_to_prototxt(self, path):
        from google.protobuf import text_format

        with open(path, "w") as f:
            f.write(text_format.MessageToString(self._proto))

    def load_from_prototxt(self, path):
        from google.protobuf import text_format

        with open(path) as f:
            text_format.Parse(f.read(), self._proto)

    def serialize_to_string(self) -> bytes:
        return self._proto.SerializeToString()

    def parse_from_string(self, data: bytes):
        self._proto.ParseFromString(data)

    amp = _bool_prop("amp")
    recompute = _bool_prop("recompute")
    localsgd = _bool_prop("localsgd")
    dgc = _bool_prop("dgc")
    gradient_merge = _bool_prop("gradient_merge")
    lars = _bool_prop("lars")
    lamb = _bool_prop("lamb")
    pipeline = _bool_prop("pipeline")
    elastic = _bool_prop("elastic")
    auto = _bool_prop("auto")
    a_sync = _bool_prop("a_sync")
    sync_batch_norm = _bool_prop("sync_batch_norm")
    fuse_all_reduce_ops = _bool_prop("fuse_all_reduce_ops")
    fp16_allreduce = _bool_prop("fp16_allreduce")
    sharding = _bool_prop("sharding")
    tensor_parallel = _bool_prop("tensor_parallel")
    sequence_parallel = _bool_prop("sequence_parallel")

    amp_configs = _config_prop("amp_configs")
    localsgd_configs = _config_prop("localsgd_configs")
    gradient_merge_configs = _config_prop("gradient_merge_configs")
    dgc_configs = _config_prop("dgc_configs")
    lars_configs = _config_prop("lars_configs")
    lamb_configs = _config_prop("lamb_configs")
    pipeline_configs = _config_prop("pipeline_configs")
    sharding_configs = _config_prop("sharding_configs")
    a_sync_configs = _config_prop("a_sync_configs")

    # extra recompute config keys the proto cannot hold (the
    # RecomputeConfig message carries only the checkpoint list):
    # "policy" — XLA remat policy name wrapped around scanned layer
    # blocks ('nothing_saveable' / 'dots_saveable' / 'save_anything');
    # "scan_layers" — min isomorphic repeat count that turns the
    # LayerScanPass on for this program (0 = follow FLAGS_layer_scan).
    # Python-side only: they do NOT survive serialize_to_string, but DO
    # survive program clone/proto round-trips once the
    # RecomputeMetaOptimizer stamps them onto the optimizer ops.
    _RC_EXTRA_KEYS = ("policy", "scan_layers")

    @property
    def recompute_configs(self):
        out = _config_to_dict(self._proto.recompute_configs)
        out.update(getattr(self, "_rc_extra", {}))
        return out

    @recompute_configs.setter
    def recompute_configs(self, configs):
        extra = {}
        proto_cfg = {}
        for k, v in (configs or {}).items():
            if k in self._RC_EXTRA_KEYS:
                extra[k] = v
            else:
                proto_cfg[k] = v
        _dict_to_config(self._proto.recompute_configs, proto_cfg)
        if not hasattr(self, "_rc_extra"):
            self._rc_extra = {}
        self._rc_extra.update(extra)

    # extra tensor_parallel config keys the proto cannot hold (the
    # TensorParallelConfig message carries only degree + seed):
    # "partition_rules" — ordered (regex, spec) list, spec either a
    # "None,mp" string or a tuple; "mesh_shape" — (dp, mp) used by
    # helpers building the mesh.  Python-side only: they do NOT survive
    # serialize_to_string (the rules DO survive program clone/proto
    # round-trips once minimize stamps them onto the optimizer ops).
    _TP_EXTRA_KEYS = ("partition_rules", "mesh_shape")

    @property
    def tensor_parallel_configs(self):
        out = _config_to_dict(self._proto.tensor_parallel_configs)
        out.update(getattr(self, "_tp_extra", {}))
        return out

    @tensor_parallel_configs.setter
    def tensor_parallel_configs(self, configs):
        extra = {}
        proto_cfg = {}
        for k, v in (configs or {}).items():
            if k in self._TP_EXTRA_KEYS:
                extra[k] = v
            else:
                proto_cfg[k] = v
        _dict_to_config(self._proto.tensor_parallel_configs, proto_cfg)
        if not hasattr(self, "_tp_extra"):
            self._tp_extra = {}
        self._tp_extra.update(extra)

    # expert parallelism (mixture-of-experts): the reference proto
    # predates MoE, so both knobs are pure python-side state — they do
    # NOT survive serialize_to_string (the contract DOES survive program
    # clone/proto round-trips once ExpertParallelMetaOptimizer stamps
    # EP_DEGREE_ATTR onto the optimizer ops).  Config keys:
    # "expert_parallel_degree" — required 'ep' axis size (0/absent =
    # whatever the mesh has).
    @property
    def expert_parallel(self):
        return bool(getattr(self, "_ep_enabled", False))

    @expert_parallel.setter
    def expert_parallel(self, v):
        self._ep_enabled = bool(v)

    @property
    def expert_parallel_configs(self):
        return dict(getattr(self, "_ep_configs", {}))

    @expert_parallel_configs.setter
    def expert_parallel_configs(self, configs):
        if not hasattr(self, "_ep_configs"):
            self._ep_configs = {}
        self._ep_configs.update(configs or {})

    @property
    def nccl_comm_num(self):
        return self._proto.nccl_comm_num

    @nccl_comm_num.setter
    def nccl_comm_num(self, v):
        self._proto.nccl_comm_num = int(v)

    @property
    def fuse_grad_size_in_MB(self):
        """Bucket cap for fused gradient allreduce (default 32 MB);
        consumed by framework/passes.py FuseAllReducePass via the
        collective transpiler's op markers."""
        return self._proto.fuse_grad_size_in_MB

    @fuse_grad_size_in_MB.setter
    def fuse_grad_size_in_MB(self, v):
        iv = int(v)
        if iv != v or iv <= 0:
            # the proto field is int32 MB: silently truncating 0.5 -> 0
            # (-> the 32MB default) would ignore the user's cap; sub-MB
            # caps go through GradAllReduce(fuse_grad_size_in_MB=...)
            raise ValueError(
                f"fuse_grad_size_in_MB must be a positive whole number of "
                f"MB, got {v!r}; for sub-MB bucket caps construct "
                f"GradAllReduce(fuse_grad_size_in_MB=...) directly")
        self._proto.fuse_grad_size_in_MB = iv

    def __repr__(self):
        on = [f.name for f in self._proto.DESCRIPTOR.fields
              if f.type == f.TYPE_BOOL and getattr(self._proto, f.name)]
        return f"DistributedStrategy(enabled={on})"
