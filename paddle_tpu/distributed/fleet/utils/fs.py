"""Filesystem clients (reference fleet/utils/fs.py).

LocalFS is complete; HDFSClient shells out to the `hadoop` binary exactly
like the reference — on hosts without a hadoop install every call raises
with a clear message (checkpoint paths on TPU pods are typically GCS/NFS
mounted locally, which LocalFS covers).
"""
from __future__ import annotations

import os
import shutil
import subprocess


class ExecuteError(Exception):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def touch(self, fs_path):
        raise NotImplementedError


class LocalFS(FS):
    """Reference fleet/utils/fs.py LocalFS."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for e in os.listdir(fs_path):
            (dirs if os.path.isdir(os.path.join(fs_path, e)) else files
             ).append(e)
        return dirs, files

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def mv(self, src, dst, overwrite=False):
        if not overwrite and os.path.exists(dst):
            raise ExecuteError(f"{dst} already exists")
        os.replace(src, dst)

    def touch(self, fs_path, exist_ok=True):
        if os.path.exists(fs_path):
            if not exist_ok:
                raise ExecuteError(f"{fs_path} already exists")
            return
        open(fs_path, "a").close()

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)


class HDFSClient(FS):
    """Reference HDFSClient: drives `hadoop fs` subcommands."""

    def __init__(self, hadoop_home, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._base = [os.path.join(hadoop_home, "bin", "hadoop"), "fs"]
        self._configs = []
        for k, v in (configs or {}).items():
            self._configs += ["-D", f"{k}={v}"]

    def _run(self, *args):
        cmd = self._base + self._configs + list(args)
        if not os.path.exists(self._base[0]):
            raise ExecuteError(
                f"hadoop binary not found at {self._base[0]}; HDFSClient "
                f"needs a hadoop install (use LocalFS for mounted paths)")
        p = subprocess.run(cmd, capture_output=True, text=True)
        if p.returncode != 0:
            raise ExecuteError(f"{' '.join(cmd)} failed: {p.stderr}")
        return p.stdout

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", fs_path)

    def mv(self, src, dst, overwrite=False):
        if self.is_exist(dst):
            if not overwrite:
                raise ExecuteError(
                    f"hdfs mv: destination {dst!r} exists and "
                    f"overwrite=False")
            # hadoop fs -mv refuses to clobber; reference HDFSClient
            # deletes dst first when overwrite=True
            self.delete(dst)
        self._run("-mv", src, dst)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        self._run("-touchz", fs_path)
