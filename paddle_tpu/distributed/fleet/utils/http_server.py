"""HTTP KV server for rendezvous (reference fleet/utils/http_server.py).

RoleMaker's gloo bootstrap in the reference exchanges endpoints through
this KV; here jax.distributed's coordination service is the primary
rendezvous, but the KV server survives as transport for custom cluster
glue (and is exercised by the test suite over real localhost HTTP).

It also doubles as the serving layer's observability port: ``routes``
maps a path (e.g. ``/stats``, ``/health``) to a zero-arg callable whose
return value is served as JSON — GETs on a registered route never touch
the KV store.  A route key ending in ``/`` is a PREFIX route: it
matches any longer path under it and its callable receives the path
remainder as one argument (``/debug/request/<trace id>``).  A route may instead return ``(bytes, content_type)`` for
non-JSON payloads; every server registers a default ``/metrics`` route
serving the whole counter+histogram registry in Prometheus
text-exposition format (``paddle_tpu.observe``), so any fleet/serving
process is scrape-able out of the box.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit


class KVHandler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet
        pass

    def _key(self):
        return self.path.lstrip("/")

    def do_GET(self):
        # route match ignores the query string (scrapers send
        # /stats?format=... and cache-busting /health?ts=...)
        path = urlsplit(self.path).path
        route = self.server.routes.get(path)
        route_arg = None
        if route is not None and path.endswith("/"):
            # an exact GET of a prefix-route key is the empty-remainder
            # case — the handler expects its one argument
            route_arg = ""
        if route is None:
            # prefix routes: a key ending in "/" matches any longer
            # path under it and the handler receives the remainder
            # (e.g. "/debug/request/" -> route("<trace id>"));
            # longest prefix wins
            for rp in sorted(self.server.routes, key=len, reverse=True):
                if rp.endswith("/") and path.startswith(rp) \
                        and len(path) > len(rp):
                    route, route_arg = self.server.routes[rp], \
                        path[len(rp):]
                    break
        if route is not None:
            ctype = "application/json"
            try:
                payload = route(route_arg) if route_arg is not None \
                    else route()
                if isinstance(payload, tuple):  # (body, content_type)
                    payload, ctype = payload
                body = payload if isinstance(payload, bytes) \
                    else json.dumps(payload).encode()
                code = 200
            except Exception as e:  # surface handler bugs as 500s
                body = json.dumps({"error": f"{type(e).__name__}: {e}"}
                                  ).encode()
                ctype = "application/json"
                code = 500
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        with self.server.kv_lock:
            val = self.server.kv.get(self._key())
        if val is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length)
        with self.server.kv_lock:
            self.server.kv[self._key()] = data
        self.send_response(200)
        self.end_headers()

    do_POST = do_PUT

    def do_DELETE(self):
        with self.server.kv_lock:
            self.server.kv.pop(self._key(), None)
        self.send_response(200)
        self.end_headers()


def _metrics_route():
    """Default GET /metrics handler: Prometheus text exposition of the
    whole StatRegistry + histogram registry (observe/histogram.py).
    SLO burn/goodput gauges are re-evaluated per scrape — they
    otherwise refresh only on terminal requests, and a gauge frozen at
    its last-burst peak would never resolve an alert."""
    from ....observe import slo as _slo
    from ....observe.histogram import prometheus_text

    try:
        _slo.refresh_gauges()
    except Exception:  # noqa: BLE001 — the exposition must still serve
        pass
    return (prometheus_text().encode(),
            "text/plain; version=0.0.4; charset=utf-8")


class KVHTTPServer(ThreadingHTTPServer):
    def __init__(self, port, handler=KVHandler, routes=None):
        super().__init__(("", port), handler)
        self.kv = {}
        self.kv_lock = threading.Lock()
        self.routes = dict(routes or {})
        # every fleet/serving HTTP port is scrape-able; pass an explicit
        # "/metrics" route to override (or map it to None to disable —
        # a None route falls through to the KV store)
        self.routes.setdefault("/metrics", _metrics_route)
        if self.routes.get("/metrics") is None:
            del self.routes["/metrics"]

    def kv_snapshot(self, prefix: str = "") -> dict:
        """Consistent copy of the KV store (optionally filtered by key
        prefix) — the read path for aggregating routes like the
        cluster-health ``/metrics/cluster`` (observe/health.py), which
        must not hold the KV lock while rendering."""
        with self.kv_lock:
            if not prefix:
                return dict(self.kv)
            return {k: v for k, v in self.kv.items()
                    if k.startswith(prefix)}


class KVServer:
    """Reference KVServer: start/stop a background KV HTTP server."""

    def __init__(self, port, size=None, routes=None):
        self.http_server = KVHTTPServer(port, KVHandler, routes=routes)
        self.listen_thread = None

    def add_route(self, path: str, fn) -> None:
        """Register ``path`` to serve ``fn()`` as JSON on GET."""
        self.http_server.routes[path] = fn

    def kv_snapshot(self, prefix: str = "") -> dict:
        """Copy of the KV store, optionally filtered by key prefix."""
        return self.http_server.kv_snapshot(prefix)

    @property
    def port(self):
        return self.http_server.server_address[1]

    def start(self):
        self.listen_thread = threading.Thread(
            target=self.http_server.serve_forever, daemon=True)
        self.listen_thread.start()

    def stop(self):
        self.http_server.shutdown()
        if self.listen_thread is not None:
            self.listen_thread.join()
        self.http_server.server_close()
