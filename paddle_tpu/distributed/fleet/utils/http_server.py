"""HTTP KV server for rendezvous (reference fleet/utils/http_server.py).

RoleMaker's gloo bootstrap in the reference exchanges endpoints through
this KV; here jax.distributed's coordination service is the primary
rendezvous, but the KV server survives as transport for custom cluster
glue (and is exercised by the test suite over real localhost HTTP).
"""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class KVHandler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet
        pass

    def _key(self):
        return self.path.lstrip("/")

    def do_GET(self):
        with self.server.kv_lock:
            val = self.server.kv.get(self._key())
        if val is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length)
        with self.server.kv_lock:
            self.server.kv[self._key()] = data
        self.send_response(200)
        self.end_headers()

    do_POST = do_PUT

    def do_DELETE(self):
        with self.server.kv_lock:
            self.server.kv.pop(self._key(), None)
        self.send_response(200)
        self.end_headers()


class KVHTTPServer(ThreadingHTTPServer):
    def __init__(self, port, handler=KVHandler):
        super().__init__(("", port), handler)
        self.kv = {}
        self.kv_lock = threading.Lock()


class KVServer:
    """Reference KVServer: start/stop a background KV HTTP server."""

    def __init__(self, port, size=None):
        self.http_server = KVHTTPServer(port, KVHandler)
        self.listen_thread = None

    @property
    def port(self):
        return self.http_server.server_address[1]

    def start(self):
        self.listen_thread = threading.Thread(
            target=self.http_server.serve_forever, daemon=True)
        self.listen_thread.start()

    def stop(self):
        self.http_server.shutdown()
        if self.listen_thread is not None:
            self.listen_thread.join()
        self.http_server.server_close()
