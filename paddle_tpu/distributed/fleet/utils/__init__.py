"""fleet.utils: filesystem clients + the HTTP KV rendezvous server.

Reference parity: python/paddle/distributed/fleet/utils/fs.py (LocalFS,
HDFSClient) and the http_server KV used by RoleMaker's gloo rendezvous
(role_maker.py:172).
"""
from .fs import HDFSClient, LocalFS  # noqa: F401
from .http_server import KVHandler, KVHTTPServer, KVServer  # noqa: F401
