"""Process launcher: `python -m paddle_tpu.distributed.launch train.py`.

Role parity: reference python/paddle/distributed/fleet/launch.py:304 +
distributed/utils.py:357 (start_local_trainers) / :417
(watch_local_trainers).  TPU-native difference: the reference spawns one
process per GPU; on TPU one process drives all local chips, so the
launcher spawns ONE trainer per host entry in --ips (loopback testing
spawns N local processes with a shared coordinator for the
jax.distributed rendezvous).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma list of host ips (one trainer process per host)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="trainer processes on THIS node (loopback testing)")
    p.add_argument("--coordinator_port", type=int, default=37777)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def start_local_trainers(nproc, coordinator, script, script_args, log_dir=None,
                         base_rank=0, total=None):
    """Spawn trainer subprocesses with the fleet env contract set
    (reference utils.py:357)."""
    procs = []
    total = total if total is not None else nproc
    for i in range(nproc):
        rank = base_rank + i
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(total),
            "PADDLE_COORDINATOR": coordinator,
            "PADDLE_TRAINER_ENDPOINTS": coordinator,
            "FLAGS_selected_tpus": "all",
        })
        out = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            out = open(os.path.join(log_dir, f"workerlog.{rank}"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, script] + list(script_args),
            env=env, stdout=out, stderr=subprocess.STDOUT if out else None))
    return procs


def watch_local_trainers(procs):
    """Poll children; tear the job down if any dies
    (reference utils.py:417 watch + :257 terminate)."""
    try:
        while True:
            alive = False
            for p in procs:
                ret = p.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    terminate_local_procs(procs)
                    return ret
            if not alive:
                return 0
            time.sleep(0.5)
    except KeyboardInterrupt:
        terminate_local_procs(procs)
        return 1


def terminate_local_procs(procs):
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + 5
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            p.kill()


def launch(argv=None):
    args = _parse_args(argv)
    ips = [h for h in args.ips.split(",") if h]
    me = os.environ.get("POD_IP")
    if len(ips) > 1:
        if me is None or me not in ips:
            raise SystemExit(
                "multi-host launch needs POD_IP set to this host's entry in "
                f"--ips (got POD_IP={me!r}, ips={ips}); otherwise every host "
                "would claim node rank 0 and the rendezvous fails")
    else:
        me = ips[0]
    node_rank = ips.index(me)
    coordinator = f"{ips[0]}:{args.coordinator_port}"
    total = len(ips) * args.nproc_per_node
    procs = start_local_trainers(
        args.nproc_per_node, coordinator, args.training_script,
        args.training_script_args, log_dir=args.log_dir,
        base_rank=node_rank * args.nproc_per_node, total=total)
    sys.exit(watch_local_trainers(procs))


if __name__ == "__main__":
    launch()
