"""Pipeline parallelism: GPipe schedule over a 'pp' mesh axis.

Role parity: reference fluid.optimizer.PipelineOptimizer
(optimizer.py:3695) + PipelineTrainer/SectionWorker
(framework/pipeline_trainer.cc:24, section_worker.cc:82): the program is
split into per-device sections by `device_guard("stage:N")` annotations;
micro-batches flow stage to stage.

TPU-native redesign (SURVEY.md §2.8): no section threads or blocking
queues — the whole schedule compiles into ONE XLA program executed SPMD
over the 'pp' mesh axis.  Every rank runs the same code; `lax.switch` on
`axis_index('pp')` selects the local stage, `lax.ppermute` moves boundary
activations (forward) and their cotangents (backward) between neighbor
ranks, and each stage's backward is `jax.vjp` of its traced forward.
GPipe flush schedule: K micro-batch forwards fill the pipe, then K
backwards drain it; per-stage gradients are psum'd over the axis and feed
the program's own optimizer ops, so parameters stay replicated and every
rank applies the identical update.

v2 capabilities (v1's restrictions lifted):
- dropout/RNG inside stages: the key is fold_in(program_key, stage,
  microbatch), so the backward vjp replay regenerates identical masks;
- state written in staged forwards (batch_norm running stats) is carried
  tick-to-tick on the owning rank and published from it at the end;
- boundaries may pass MULTIPLE float tensors with non-uniform shapes:
  each boundary packs into one flat carrier buffer padded to the widest
  boundary (rank-uniform, ppermute-able), unpacked by the next stage;
- dp x pp meshes: feeds shard over 'dp', the schedule runs per dp
  shard, grads psum over both axes.

Remaining restrictions (loud errors): loss-only fetches; boundary
tensors must be floating point.
"""
from __future__ import annotations

from typing import Dict, List


def analyze_stages(program, n_stages: int):
    """Partition forward ops into stages via op_device annotations.

    Untagged ops inherit the previous op's stage (build order), starting
    at stage 0.  Returns (stage_ops, boundary_vars): boundary_vars[s] is
    the LIST of activations stage s hands to later stages.
    """
    meta = getattr(program, "_pipeline", None)
    fwd_end = meta["fwd_end"] if meta else len(program.global_block.ops)
    ops = [op for op in program.global_block.ops[:fwd_end]
           if op.type not in ("feed", "fetch")]
    stage_ops: List[list] = [[] for _ in range(n_stages)]
    cur = 0
    for op in ops:
        dev = op.attr("op_device", None)
        if dev:
            if not str(dev).startswith("stage:"):
                raise ValueError(
                    f"op_device {dev!r} is not a pipeline annotation; use "
                    f"device_guard('stage:N')")
            s = int(str(dev).split(":", 1)[1])
            if s < cur:
                raise ValueError(
                    f"op {op.type!r} tagged stage {s} appears after stage "
                    f"{cur} ops; stages must be contiguous in build order")
            if s >= n_stages:
                raise ValueError(
                    f"op {op.type!r} tagged stage {s} but the mesh has only "
                    f"{n_stages} pipeline stages")
            cur = s
        stage_ops[cur].append(op)

    boundaries = []
    produced_upto = set()
    for s in range(n_stages - 1):
        produced_upto |= {n for op in stage_ops[s]
                          for n in op.output_arg_names()}
        consumed = set()
        for later in range(s + 1, n_stages):
            for op in stage_ops[later]:
                for n in op.input_arg_names():
                    if n in produced_upto:
                        consumed.add(n)
        # cumulative: vars produced at ANY stage <= s and consumed later
        # ride every intervening boundary (skip connections pass through)
        act = sorted(consumed)
        if not act:
            raise ValueError(
                f"pipeline stage boundary {s}->{s + 1} passes no tensors; "
                f"every stage must feed the next")
        boundaries.append(act)
    return stage_ops, boundaries


def build_pipeline_fn(program, mesh, feed_names, state_mut, state_const,
                      state_out, fetch_names, loss_name, params_grads,
                      n_microbatches, bwd_end):
    """The compiled GPipe train step (plugs into Executor._compile).

    Signature matches the standard sharded path:
    (feed_vals, mut_vals, const_vals, rng) -> (fetches, new_state, rng).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from ..framework.lowering import (PSEUDO_OPS, LoweringContext,
                                      get_lowering)

    pp_axis = "pp"
    if pp_axis not in mesh.axis_names:
        raise ValueError(
            f"pipeline execution needs a 'pp' mesh axis; got "
            f"{mesh.axis_names}")
    dp_axis = "dp" if "dp" in mesh.axis_names else None
    dp_size = int(mesh.shape[dp_axis]) if dp_axis else 1
    S = int(mesh.shape[pp_axis])
    K = int(n_microbatches)
    stage_ops, boundaries = analyze_stages(program, S)
    block = program.global_block
    if set(fetch_names) - {loss_name}:
        raise NotImplementedError(
            f"pipeline executor fetches the loss only; got {fetch_names}")

    grad_of = {(p if isinstance(p, str) else p.name):
               (g if isinstance(g, str) else g.name)
               for p, g in params_grads}
    opt_ops = [op for op in block.ops[bwd_end:]
               if op.type not in PSEUDO_OPS]

    # state written inside staged forwards (batch_norm running stats):
    # carried tick-to-tick on the owning stage's rank, published at the end
    state_out_set = set(state_out)
    param_names = set(grad_of)
    opt_writes = {n for op in opt_ops for n in op.output_arg_names()}
    carried_owner: Dict[str, int] = {}
    for s, ops in enumerate(stage_ops):
        for op in ops:
            for n in op.output_arg_names():
                if n in state_out_set and n not in param_names \
                        and n not in opt_writes:
                    carried_owner[n] = s
    carried_names = sorted(carried_owner)

    def trace_ops(ops, env, rng_key=None):
        axes = (pp_axis,) + ((dp_axis,) if dp_axis else ())
        ctx = LoweringContext(block, env, rng_key=rng_key, mesh=mesh,
                              axis_env=axes,
                              fold_axes=(dp_axis,) if dp_axis else ())
        for op in ops:
            try:
                get_lowering(op.type)(ctx, op)
            except Exception as e:
                site = op.callstack[-1] if op.callstack else "<unknown>"
                raise type(e)(
                    f"while lowering pipeline op {op.type!r} (built at "
                    f"{site}): {e}") from e
        return env

    def traced(feed_vals, mut_vals, const_vals, rng):
        base_env = {}
        base_env.update(zip(state_mut, mut_vals))
        base_env.update(zip(state_const, const_vals))
        full_feeds = dict(zip(feed_names, feed_vals))
        r = lax.axis_index(pp_axis)

        params = {pname: base_env[pname] for pname in grad_of}

        # micro-batch every feed: (B, ...) -> (K, B//K, ...)
        mb_feeds = {}
        for n, v in full_feeds.items():
            b = v.shape[0]
            if b % K:
                raise ValueError(
                    f"feed {n!r} batch {b} not divisible by micro_batch "
                    f"count {K}")
            mb_feeds[n] = v.reshape((K, b // K) + v.shape[1:])

        # ---- probe boundary structures stage by stage -------------------
        mb_structs = {n: jax.ShapeDtypeStruct((v.shape[1],) + v.shape[2:],
                                              v.dtype)
                      for n, v in mb_feeds.items()}

        def probe_stage(s, in_structs):
            def f(acts_in):
                env = dict(base_env)
                env.update(params)
                for n, sd in mb_structs.items():
                    env[n] = jnp.zeros(sd.shape, sd.dtype)
                if s > 0:
                    env.update(dict(zip(boundaries[s - 1], acts_in)))
                trace_ops(stage_ops[s], env,
                          rng_key=jax.random.PRNGKey(0))
                return tuple(jnp.asarray(env[n]) for n in boundaries[s])

            dummy = tuple(jnp.zeros(sd.shape, sd.dtype)
                          for sd in (in_structs or ()))
            return jax.eval_shape(f, dummy)

        bnd_structs = []  # per boundary: tuple of ShapeDtypeStructs
        prev = None
        for s in range(S - 1):
            prev = probe_stage(s, prev)
            bnd_structs.append(prev)
        for structs, names in zip(bnd_structs, boundaries):
            for sd, n in zip(structs, names):
                if not jnp.issubdtype(sd.dtype, jnp.floating):
                    raise NotImplementedError(
                        f"pipeline boundary tensor {n!r} has non-float "
                        f"dtype {sd.dtype}; route integer data to every "
                        f"stage via feeds instead")

        # ---- flat f32 carrier buffer, padded to the widest boundary -----
        def _size(sd):
            n = 1
            for d in sd.shape:
                n *= int(d)
            return n

        widths = [sum(_size(sd) for sd in structs)
                  for structs in bnd_structs]
        width = max(widths) if widths else 1
        zero_act = jnp.zeros((width,), jnp.float32)

        def pack(s, vals):
            flat = [jnp.ravel(v).astype(jnp.float32) for v in vals]
            buf = jnp.concatenate(flat) if flat else zero_act
            return jnp.pad(buf, (0, width - buf.shape[0]))

        def unpack(s, buf):
            vals = []
            off = 0
            for sd in bnd_structs[s]:
                n = _size(sd)
                vals.append(buf[off:off + n].reshape(sd.shape)
                            .astype(sd.dtype))
                off += n
            return vals

        def stage_key(rng_key, s, mb_idx):
            # deterministic per (stage, microbatch): the backward vjp
            # replays the forward with the same key -> identical dropout
            # masks (the correctness crux of RNG under GPipe)
            return jax.random.fold_in(jax.random.fold_in(rng_key, mb_idx), s)

        def stage_fwd(s, prm, carried, act_buf, mb_idx, rng_key):
            """Uniform output across branches:
            (out_buf, loss, new_carried)."""
            env = dict(base_env)
            env.update(carried)
            env.update(prm)
            for n, v in mb_feeds.items():
                env[n] = lax.dynamic_index_in_dim(v, mb_idx, 0,
                                                  keepdims=False)
            if s > 0:
                env.update(dict(zip(boundaries[s - 1], unpack(s - 1, act_buf))))
            trace_ops(stage_ops[s], env, rng_key=stage_key(rng_key, s, mb_idx))
            new_carried = {
                n: (env[n] if carried_owner[n] == s else carried[n])
                for n in carried_names
            }
            if s < S - 1:
                out_buf = pack(s, [env[n] for n in boundaries[s]])
                return out_buf, jnp.zeros((), jnp.float32), new_carried
            loss = jnp.asarray(env[loss_name], jnp.float32).reshape(())
            return zero_act, loss, new_carried

        branches = [
            (lambda prm, c, a, i, k, s=s: stage_fwd(s, prm, c, a, i, k))
            for s in range(S)
        ]

        def switch_fwd(prm, carried, act_buf, mb_idx, rng_key):
            return lax.switch(r, branches, prm, carried, act_buf, mb_idx,
                              rng_key)

        fwd_perm = [(i, i + 1) for i in range(S - 1)]
        bwd_perm = [(i + 1, i) for i in range(S - 1)]

        # ---- forward fill (K + S - 1 ticks) -----------------------------
        T = K + S - 1
        saved_in = jnp.zeros((K, width), jnp.float32)
        losses = jnp.zeros((K,), jnp.float32)
        carried = {n: base_env[n] for n in carried_names}
        recv = zero_act
        for t in range(T):
            mb = jnp.clip(t - r, 0, K - 1)
            active = jnp.logical_and(t - r >= 0, t - r < K)
            act_out, loss_mb, new_carried = switch_fwd(
                params, carried, recv, mb, rng)
            carried = {
                n: jnp.where(active, new_carried[n], carried[n])
                for n in carried_names
            }
            # remember this tick's stage INPUT for the backward vjp
            prev = lax.dynamic_index_in_dim(saved_in, mb, 0, keepdims=False)
            upd = jnp.where(active, recv, prev)
            saved_in = lax.dynamic_update_index_in_dim(saved_in, upd, mb, 0)
            losses = losses.at[mb].set(
                jnp.where(active, loss_mb, losses[mb]))
            send = jnp.where(active, act_out, zero_act)
            recv = lax.ppermute(send, pp_axis, fwd_perm)

        # ---- backward drain (K + S - 1 ticks) ---------------------------
        # backward replays the forward with the SAME carried snapshot the
        # vjp does not need exact per-tick stats (grads of running-stat
        # updates are zero: they are stop-gradient outputs)
        def stage_bwd(prm, act_in, mb_idx, g_act, g_loss):
            def f(prm_, act_in_):
                out_buf, loss, _ = switch_fwd(prm_, carried, act_in_,
                                              mb_idx, rng)
                return out_buf, loss

            _, vjp = jax.vjp(f, prm, act_in)
            gp, gact = vjp((g_act, g_loss))
            return gp, gact

        grad_acc = jax.tree.map(jnp.zeros_like, params)
        g_recv = zero_act
        for u in range(T):
            m = jnp.clip(u - (S - 1 - r), 0, K - 1)
            active = jnp.logical_and(u - (S - 1 - r) >= 0,
                                     u - (S - 1 - r) < K)
            is_last = r == S - 1
            g_loss = jnp.where(jnp.logical_and(active, is_last),
                               jnp.float32(1.0 / K), 0.0)
            g_act = jnp.where(is_last, zero_act, g_recv)
            act_in = lax.dynamic_index_in_dim(saved_in, m, 0,
                                              keepdims=False)
            gp, gact = stage_bwd(params, act_in, m, g_act, g_loss)
            # where-select, not multiply: an inf/NaN jacobian at a
            # zero-filled inactive tick must not poison the accumulator
            grad_acc = jax.tree.map(
                lambda a, g: a + jnp.where(active, g, jnp.zeros_like(g)),
                grad_acc, gp)
            g_send = jnp.where(active, gact, zero_act)
            g_recv = lax.ppermute(g_send, pp_axis, bwd_perm)

        # grads live on the owning stage's rank; psum over pp replicates
        # them, psum over dp completes data parallelism
        grad_axes = (pp_axis,) + ((dp_axis,) if dp_axis else ())
        grad_acc = jax.tree.map(
            lambda g: lax.psum(g, grad_axes)
            / (dp_size if dp_axis else 1), grad_acc)

        # publish carried state from its owning rank (other ranks still
        # hold the initial value); under dp the shards saw different data
        # so running stats are pmean'd — same approximation sync-free BN
        # makes in the reference's multi-device path
        final_carried = {}
        for n in carried_names:
            owner = carried_owner[n]
            v = carried[n]
            picked = jnp.where(r == owner, v, jnp.zeros_like(v))
            out = lax.psum(picked, pp_axis)
            if dp_axis:
                out = lax.pmean(out, dp_axis)
            final_carried[n] = out

        env = dict(base_env)
        env.update(final_carried)
        for pname, gname in grad_of.items():
            env[gname] = grad_acc[pname]
        trace_ops(opt_ops, env)

        # full-batch mean loss, present on the last rank; psum-broadcast
        loss_sum = jnp.where(r == S - 1, losses.sum(), 0.0)
        mean_loss = lax.psum(loss_sum, pp_axis) / K
        if dp_axis:
            mean_loss = lax.pmean(mean_loss, dp_axis)
        fetches = tuple(mean_loss for _ in fetch_names)
        new_state = tuple(env[n] for n in state_out)
        new_rng = jax.random.split(rng, 2)[0]
        return fetches, new_state, new_rng

    in_feed_specs = tuple(
        (P(dp_axis) if dp_axis else P()) for _ in feed_names)
    return shard_map(
        traced,
        mesh=mesh,
        in_specs=(in_feed_specs,
                  tuple(P() for _ in state_mut),
                  tuple(P() for _ in state_const),
                  P()),
        out_specs=(tuple(P() for _ in fetch_names),
                   tuple(P() for _ in state_out),
                   P()),
        check_vma=False,
    )
