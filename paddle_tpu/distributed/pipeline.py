"""Pipeline parallelism: GPipe schedule over a 'pp' mesh axis.

Role parity: reference fluid.optimizer.PipelineOptimizer
(optimizer.py:3695) + PipelineTrainer/SectionWorker
(framework/pipeline_trainer.cc:24, section_worker.cc:82): the program is
split into per-device sections by `device_guard("stage:N")` annotations;
micro-batches flow stage to stage.

TPU-native redesign (SURVEY.md §2.8): no section threads or blocking
queues — the whole schedule compiles into ONE XLA program executed SPMD
over the 'pp' mesh axis.  Every rank runs the same code; `lax.switch` on
`axis_index('pp')` selects the local stage, `lax.ppermute` moves boundary
activations (forward) and their cotangents (backward) between neighbor
ranks, and each stage's backward is `jax.vjp` of its traced forward.
GPipe flush schedule: K micro-batch forwards fill the pipe, then K
backwards drain it; per-stage gradients are psum'd over the axis and feed
the program's own optimizer ops, so parameters stay replicated and every
rank applies the identical update (memory-sharded stage params are a
later milestone; correctness parity with the non-pipelined program is
the v1 contract).

v1 restrictions (loud errors, not silent wrongness):
- every stage boundary passes exactly ONE activation tensor and all
  boundaries share one shape/dtype (equal-width trunks — true for
  transformer stacks; ppermute is SPMD and needs rank-uniform buffers);
- no RNG ops (dropout) inside staged forwards;
- the 'pp' axis carries only pipeline parallelism (dp x pp composition
  is a later milestone).
"""
from __future__ import annotations

from typing import Dict, List


def analyze_stages(program, n_stages: int):
    """Partition forward ops into stages via op_device annotations.

    Untagged ops inherit the previous op's stage (build order), starting
    at stage 0.  Returns (stage_ops, boundary_vars): boundary_vars[s] is
    the single activation passed from stage s to s+1.
    """
    meta = getattr(program, "_pipeline", None)
    fwd_end = meta["fwd_end"] if meta else len(program.global_block.ops)
    ops = [op for op in program.global_block.ops[:fwd_end]
           if op.type not in ("feed", "fetch")]
    stage_ops: List[list] = [[] for _ in range(n_stages)]
    cur = 0
    for op in ops:
        dev = op.attr("op_device", None)
        if dev:
            if not str(dev).startswith("stage:"):
                raise ValueError(
                    f"op_device {dev!r} is not a pipeline annotation; use "
                    f"device_guard('stage:N')")
            s = int(str(dev).split(":", 1)[1])
            if s < cur:
                raise ValueError(
                    f"op {op.type!r} tagged stage {s} appears after stage "
                    f"{cur} ops; stages must be contiguous in build order")
            if s >= n_stages:
                raise ValueError(
                    f"op {op.type!r} tagged stage {s} but the mesh has only "
                    f"{n_stages} pipeline stages")
            cur = s
        stage_ops[cur].append(op)

    boundaries = []
    for s in range(n_stages - 1):
        produced_here = {n for op in stage_ops[s]
                         for n in op.output_arg_names()}
        consumed = set()
        for later in range(s + 1, n_stages):
            for op in stage_ops[later]:
                for n in op.input_arg_names():
                    if n in produced_here:
                        consumed.add(n)
        act = sorted(consumed)
        if len(act) != 1:
            raise ValueError(
                f"pipeline stage boundary {s}->{s + 1} must pass exactly "
                f"one activation tensor, found {act or 'none'}; restructure "
                f"the model so each stage hands one tensor to the next")
        boundaries.append(act[0])
    return stage_ops, boundaries


def build_pipeline_fn(program, mesh, feed_names, state_mut, state_const,
                      state_out, fetch_names, loss_name, params_grads,
                      n_microbatches, bwd_end):
    """The compiled GPipe train step (plugs into Executor._compile).

    Signature matches the standard sharded path:
    (feed_vals, mut_vals, const_vals, rng) -> (fetches, new_state, rng).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from ..framework.lowering import (PSEUDO_OPS, LoweringContext,
                                      get_lowering)

    pp_axis = "pp"
    if pp_axis not in mesh.axis_names:
        raise ValueError(
            f"pipeline execution needs a 'pp' mesh axis; got "
            f"{mesh.axis_names}")
    S = int(mesh.shape[pp_axis])
    K = int(n_microbatches)
    stage_ops, boundaries = analyze_stages(program, S)
    block = program.global_block
    if set(fetch_names) - {loss_name}:
        raise NotImplementedError(
            f"pipeline executor fetches the loss only; got {fetch_names}")

    grad_of = {(p if isinstance(p, str) else p.name):
               (g if isinstance(g, str) else g.name)
               for p, g in params_grads}
    opt_ops = [op for op in block.ops[bwd_end:]
               if op.type not in PSEUDO_OPS]

    # v1: stage forwards run in throwaway per-microbatch envs, so state
    # they write (batch_norm running stats) would be silently dropped —
    # reject such programs loudly
    state_out_set = set(state_out)
    param_names = set(grad_of)
    fwd_state_writes = sorted({
        n for ops in stage_ops for op in ops
        for n in op.output_arg_names()
        if n in state_out_set and n not in param_names
    } - {n for op in opt_ops for n in op.output_arg_names()})
    if fwd_state_writes:
        raise NotImplementedError(
            f"pipeline v1 cannot persist state written inside staged "
            f"forwards (e.g. batch_norm running stats): {fwd_state_writes}; "
            f"use use_global_stats/layer_norm, or train non-pipelined")

    def trace_ops(ops, env):
        ctx = LoweringContext(block, env, rng_key=None, mesh=mesh,
                              axis_env=(pp_axis,))
        for op in ops:
            try:
                get_lowering(op.type)(ctx, op)
            except Exception as e:
                site = op.callstack[-1] if op.callstack else "<unknown>"
                raise type(e)(
                    f"while lowering pipeline op {op.type!r} (built at "
                    f"{site}): {e}") from e
        return env

    def traced(feed_vals, mut_vals, const_vals, rng):
        base_env = {}
        base_env.update(zip(state_mut, mut_vals))
        base_env.update(zip(state_const, const_vals))
        full_feeds = dict(zip(feed_names, feed_vals))
        r = lax.axis_index(pp_axis)

        params = {pname: base_env[pname] for pname in grad_of}

        # micro-batch every feed: (B, ...) -> (K, B//K, ...)
        mb_feeds = {}
        for n, v in full_feeds.items():
            b = v.shape[0]
            if b % K:
                raise ValueError(
                    f"feed {n!r} batch {b} not divisible by micro_batch "
                    f"count {K}")
            mb_feeds[n] = v.reshape((K, b // K) + v.shape[1:])

        def stage_fwd(s, prm, act_in, mb_idx):
            """Uniform output: (boundary_act_or_zeros, loss_or_zero)."""
            env = dict(base_env)
            env.update(prm)
            for n, v in mb_feeds.items():
                env[n] = lax.dynamic_index_in_dim(v, mb_idx, 0,
                                                  keepdims=False)
            if s > 0:
                env[boundaries[s - 1]] = act_in
            trace_ops(stage_ops[s], env)
            if s < S - 1:
                return (jnp.asarray(env[boundaries[s]]),
                        jnp.zeros((), jnp.float32))
            loss = jnp.asarray(env[loss_name], jnp.float32).reshape(())
            return (jnp.zeros(act_shape, act_dtype), loss)

        # boundary shape (uniformity enforced): probe stage chain
        mb_structs = {n: jax.ShapeDtypeStruct((v.shape[1],) + v.shape[2:],
                                              v.dtype)
                      for n, v in mb_feeds.items()}

        def probe_stage(s, act_sd):
            def f(act_in):
                env = {n: jnp.zeros(sd.shape, sd.dtype)
                       for n, sd in mb_structs.items()}
                env.update(base_env)
                env.update(params)
                # feeds win over state on name clash
                for n, sd in mb_structs.items():
                    env[n] = jnp.zeros(sd.shape, sd.dtype)
                if s > 0:
                    env[boundaries[s - 1]] = act_in
                trace_ops(stage_ops[s], env)
                return jnp.asarray(env[boundaries[s]])

            return jax.eval_shape(
                f, act_sd if act_sd is not None
                else jax.ShapeDtypeStruct((), jnp.float32))

        act_sd = None
        for s in range(S - 1):
            sd = probe_stage(s, act_sd)
            if act_sd is not None and (sd.shape, sd.dtype) != \
                    (act_sd.shape, act_sd.dtype):
                raise ValueError(
                    f"pipeline boundary {s} activation "
                    f"{sd.dtype}{sd.shape} differs from earlier boundary "
                    f"{act_sd.dtype}{act_sd.shape}; v1 needs uniform "
                    f"boundary shapes")
            act_sd = sd
        act_shape, act_dtype = act_sd.shape, act_sd.dtype
        zero_act = jnp.zeros(act_shape, act_dtype)

        branches = [
            (lambda prm, a, i, s=s: stage_fwd(s, prm, a, i))
            for s in range(S)
        ]

        def switch_fwd(prm, act_in, mb_idx):
            return lax.switch(r, branches, prm, act_in, mb_idx)

        fwd_perm = [(i, i + 1) for i in range(S - 1)]
        bwd_perm = [(i + 1, i) for i in range(S - 1)]

        # ---- forward fill (K + S - 1 ticks) -----------------------------
        T = K + S - 1
        saved_in = jnp.zeros((K,) + act_shape, act_dtype)
        losses = jnp.zeros((K,), jnp.float32)
        recv = zero_act
        for t in range(T):
            mb = jnp.clip(t - r, 0, K - 1)
            active = jnp.logical_and(t - r >= 0, t - r < K)
            act_out, loss_mb = switch_fwd(params, recv, mb)
            # remember this tick's stage INPUT for the backward vjp
            prev = lax.dynamic_index_in_dim(saved_in, mb, 0, keepdims=False)
            upd = jnp.where(active, recv, prev)
            saved_in = lax.dynamic_update_index_in_dim(saved_in, upd, mb, 0)
            losses = losses.at[mb].set(
                jnp.where(active, loss_mb, losses[mb]))
            send = jnp.where(active, act_out, zero_act)
            recv = lax.ppermute(send, pp_axis, fwd_perm)

        # ---- backward drain (K + S - 1 ticks) ---------------------------
        def stage_bwd(prm, act_in, mb_idx, g_act, g_loss):
            def f(prm_, act_in_):
                return switch_fwd(prm_, act_in_, mb_idx)

            _, vjp = jax.vjp(f, prm, act_in)
            gp, gact = vjp((g_act, g_loss))
            return gp, gact

        grad_acc = jax.tree.map(jnp.zeros_like, params)
        g_recv = zero_act
        for u in range(T):
            m = jnp.clip(u - (S - 1 - r), 0, K - 1)
            active = jnp.logical_and(u - (S - 1 - r) >= 0,
                                     u - (S - 1 - r) < K)
            is_last = r == S - 1
            g_loss = jnp.where(jnp.logical_and(active, is_last),
                               jnp.float32(1.0 / K), 0.0)
            g_act = jnp.where(is_last, zero_act, g_recv)
            act_in = lax.dynamic_index_in_dim(saved_in, m, 0,
                                              keepdims=False)
            gp, gact = stage_bwd(params, act_in, m, g_act, g_loss)
            # where-select, not multiply: an inf/NaN jacobian at a
            # zero-filled inactive tick must not poison the accumulator
            grad_acc = jax.tree.map(
                lambda a, g: a + jnp.where(active, g, jnp.zeros_like(g)),
                grad_acc, gp)
            g_send = jnp.where(active, gact, zero_act)
            g_recv = lax.ppermute(g_send, pp_axis, bwd_perm)

        # grads live on the owning stage's rank; psum replicates them so
        # every rank applies the identical optimizer update
        grad_acc = jax.tree.map(lambda g: lax.psum(g, pp_axis), grad_acc)

        env = dict(base_env)
        for pname, gname in grad_of.items():
            env[gname] = grad_acc[pname]
        trace_ops(opt_ops, env)

        # full-batch mean loss, present on the last rank; psum-broadcast
        loss_sum = jnp.where(r == S - 1, losses.sum(), 0.0)
        mean_loss = lax.psum(loss_sum, pp_axis) / K
        fetches = tuple(mean_loss for _ in fetch_names)
        new_state = tuple(env[n] for n in state_out)
        return fetches, new_state, rng

    return shard_map(
        traced,
        mesh=mesh,
        in_specs=(tuple(P() for _ in feed_names),
                  tuple(P() for _ in state_mut),
                  tuple(P() for _ in state_const),
                  P()),
        out_specs=(tuple(P() for _ in fetch_names),
                   tuple(P() for _ in state_out),
                   P()),
        check_vma=False,
    )
