"""Pipeline parallelism: GPipe schedule over a 'pp' mesh axis.

Role parity: reference fluid.optimizer.PipelineOptimizer
(optimizer.py:3695) + PipelineTrainer/SectionWorker
(framework/pipeline_trainer.cc:24, section_worker.cc:82): the program is
split into per-device sections by `device_guard("stage:N")` annotations;
micro-batches flow stage to stage.

TPU-native redesign (SURVEY.md §2.8): no section threads or blocking
queues — the whole schedule compiles into ONE XLA program executed SPMD
over the 'pp' mesh axis.  Every rank runs the same code; `lax.switch` on
`axis_index('pp')` selects the local stage, `lax.ppermute` moves boundary
activations (forward) and their cotangents (backward) between neighbor
ranks, and each stage's backward is `jax.vjp` of its traced forward.
GPipe flush schedule: K micro-batch forwards fill the pipe, then K
backwards drain it.

v3 — per-stage state sharding (the point of PP — memory):
- parameters AND optimizer slots are packed, stage by stage, into ONE
  (n_stages, width) float32 buffer physically sharded over 'pp'
  (`PartitionSpec('pp')` on dim 0), so each rank holds only its own
  stage's ~1/S of the training state.  Inside the shard_map every rank
  sees its LOCAL (width,) row; the `lax.switch` branch for stage s
  reinterprets that row with stage s's layout — on rank r branch r is
  the one selected, so the bytes always match the layout.
- the backward takes `jax.vjp` directly w.r.t. the packed row, so
  per-stage parameter gradients come back packed in the same layout and
  never leave the owning rank (no pp psum for param grads; dp still
  psums).
- optimizer ops are partitioned per stage and run inside a second
  `lax.switch`; each rank updates only its own stage's slice in place.
  Shared optimizer ops (lr schedules, counters) run replicated.
- the scope keeps lightweight `PackedParamRef` views of every owned var
  (framework/scope.py) so save/checkpoint/inspection still read true
  values and `paddle.load` writes trigger a re-pack.
- fetches are no longer loss-only: any forward activation can be
  fetched (per-microbatch values are collected on the owning stage's
  rank, psum-broadcast, and re-assembled over micro-batches and dp).

v2 capabilities retained: dropout-safe per-(stage, microbatch) RNG,
carried batch-norm stats, multi-tensor/ragged/skip boundaries via the
packed activation carrier, dp x pp meshes.

v4 — dp×mp×pp composition + collective–compute overlap:
- tensor parallelism INSIDE each stage (Megatron-style, manual): when
  the program carries a ShardingPropagationPass plan (the
  TensorParallelMetaOptimizer now composes with pipeline), rule-matched
  params and their optimizer slots are packed as per-mp-rank SHARDS —
  the packed buffer grows an mp dimension, (n_stages, mp, width)
  sharded ``P('pp','mp')`` — and the stage trace applies the Megatron
  f/g operators at the pass's constraint anchors: a column-parallel
  matmul's input rides ``f`` (identity fwd / mp-psum bwd), a
  row-parallel (contracted, "\\tP"-flagged) matmul's partial output
  rides ``g`` (mp-psum fwd / identity bwd).  Both are explicit
  ``custom_vjp``s, so ``jax.vjp`` of the staged forward produces exact
  shard gradients with no dependence on psum-transpose conventions.
- scan-over-layers INSIDE each stage: isomorphic per-layer op runs
  within one stage's forward (and its optimizer partition) are traced
  as ONE ``lax.scan`` over stacked per-layer weights (same detection
  machinery as framework/passes.py LayerScanPass, same RNG-threading
  contract, bitwise vs the unrolled trace) — trace/compile cost per
  stage becomes ~constant in stage depth.
- latency-hiding collective matmul: with
  ``FLAGS_collective_matmul_chunks`` > 1, each row-parallel
  matmul+psum decomposes into k output-row chunks whose per-chunk mp
  reduces overlap the remaining chunk matmuls
  (ops/collective_matmul.py).

Remaining restrictions (loud errors): float32 training state; boundary
tensors must be floating point; no cross-stage optimizer reductions
(global grad clip); shared (multi-stage) parameters; mp-sharded
activations may only flow through the matmul/elementwise/activation
family (softmax/dropout/layer_norm and friends need replicated inputs
— the Megatron block shape, where the row-parallel reduce precedes
them, satisfies this by construction).
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

PACKED_STATE_VAR = "@PP_PACKED_STATE@"

_TP_MATMUL_TYPES = ("mul", "matmul", "matmul_v2")

# ops that provably keep a value's mp layout (elementwise over the
# local shard); everything NOT here and not handled structurally must
# see replicated inputs under pipeline×mp — validated at plan time
_MP_PRESERVING = {"relu", "gelu", "tanh", "sigmoid", "cast", "scale",
                  "assign", "c_identity", "recompute_barrier"}


def _mp_only(spec):
    return tuple("mp" if s == "mp" else None for s in (spec or ()))


def _validate_mp_flow(block, stage_ops, tp_plan):
    """Strict mp-layout walk over the staged FORWARD ops (compile
    time).  The manual pipeline×mp trace runs each op on LOCAL shard
    values, so any op outside the understood family that consumes an
    mp-sharded value would compute a silently-wrong local result —
    refuse loudly instead.  Returns the final name -> mp-spec map (the
    fetch/boundary checks read it)."""
    from ..framework.passes import (EMB_SHARD_ATTR, TP_CONSTRAINT_ATTR,
                                    decode_anchor)

    known: Dict[str, tuple] = {
        n: _mp_only(s) for n, s in tp_plan.specs.items()
        if any(x == "mp" for x in s)}

    def has_mp(n):
        return any(x == "mp" for x in known.get(n, ()))

    for si, ops in enumerate(stage_ops):
        for op in ops:
            anchors = [decode_anchor(e)
                       for e in (op.attr(TP_CONSTRAINT_ATTR, []) or [])]
            if op.type in _TP_MATMUL_TYPES:
                outs = op.output_arg_names()
                if anchors:
                    for n, spec, partial in anchors:
                        sp = _mp_only(spec)
                        if partial or not any(x == "mp" for x in sp):
                            known.pop(n, None)  # g-psum'd -> replicated
                        else:
                            known[n] = sp
                elif any(has_mp(n) for n in op.input_arg_names()):
                    raise NotImplementedError(
                        f"pipeline×mp: un-anchored {op.type!r} in stage "
                        f"{si} reads an mp-sharded value; the sharding "
                        f"pass could not classify it — adjust the "
                        f"partition rules")
                else:
                    for n in outs:
                        known.pop(n, None)
                continue
            if op.type in ("transpose", "transpose2"):
                xs = op.inputs.get("X", [])
                outs = op.output_arg_names()
                spec = known.get(xs[0]) if len(xs) == 1 else None
                axes = [int(a) for a in (op.attr("axis", []) or [])]
                if spec is not None and len(axes) == len(spec) and outs:
                    known[outs[0]] = tuple(spec[a] for a in axes)
                    continue
                if any(has_mp(n) for n in op.input_arg_names()):
                    raise NotImplementedError(
                        f"pipeline×mp: transpose of an mp-sharded value "
                        f"with unknown axes in stage {si}")
                for n in outs:
                    known.pop(n, None)
                continue
            if op.type.startswith("elementwise_") \
                    and not op.type.endswith("_grad"):
                xs = op.inputs.get("X", [])
                ys = op.inputs.get("Y", [])
                xsp = known.get(xs[0]) if xs else None
                ysp = known.get(ys[0]) if ys else None
                if ysp is not None and any(x == "mp" for x in ysp):
                    # broadcast operand sharded (a column-parallel
                    # bias): valid only when X is sharded the same way
                    # on its trailing dim
                    if xsp is None or xsp[-1] != ysp[-1]:
                        raise NotImplementedError(
                            f"pipeline×mp: {op.type!r} in stage {si} "
                            f"broadcasts mp-sharded {ys[0]!r} into a "
                            f"differently-laid-out operand")
                for n in op.output_arg_names():
                    if xsp is not None and any(x == "mp" for x in xsp):
                        known[n] = xsp
                    else:
                        known.pop(n, None)
                continue
            if op.type in ("lookup_table", "lookup_table_v2"):
                # row-sharded table: the all-to-all engine
                # (ops/embedding_ops.py) returns a value replicated on
                # mp — but ONLY when the sharding pass stamped the op;
                # an mp-sharded table reaching an unstamped lookup
                # would gather from a local shard as if it were global
                wname = op.inputs.get("W", [None])[0]
                if wname and has_mp(wname):
                    wspec = known.get(wname, ())
                    if not int(op.attr(EMB_SHARD_ATTR, 0) or 0):
                        raise NotImplementedError(
                            f"pipeline×mp: {op.type!r} in stage {si} "
                            f"reads mp-sharded table {wname!r} but the "
                            f"sharding pass did not classify it for "
                            f"the embedding engine (row-shard it: "
                            f"P('mp', None), or drop its rule)")
                    if wspec and (wspec[0] != "mp"
                                  or any(x == "mp" for x in wspec[1:])):
                        raise NotImplementedError(
                            f"pipeline×mp: embedding table {wname!r} "
                            f"in stage {si} must be ROW-sharded "
                            f"(P('mp', None)); got {wspec}")
                bad_ids = sorted(n for n in op.inputs.get("Ids", [])
                                 if has_mp(n))
                if bad_ids:
                    raise NotImplementedError(
                        f"pipeline×mp: embedding ids {bad_ids} in "
                        f"stage {si} are mp-sharded; the engine needs "
                        f"replicated ids")
                for n in op.output_arg_names():
                    known.pop(n, None)  # engine output: replicated
                continue
            if op.type in _MP_PRESERVING:
                xs = op.inputs.get("X", [])
                spec = known.get(xs[0]) if len(xs) == 1 else None
                for n in op.output_arg_names():
                    if spec is not None:
                        known[n] = spec
                    else:
                        known.pop(n, None)
                continue
            if op.type == "flash_attention":
                # the fused op keeps the Megatron shape INTERNALLY: its
                # softmax is per-head, so heads-dim (dim 1) sharded
                # q/k/v is the one layout that flows through locally —
                # no replication needed, unlike the unfused softmax op
                qn = op.inputs.get("Q", [None])[0]
                spec = known.get(qn) if qn else None
                for other in (op.inputs.get("K", [None])[0],
                              op.inputs.get("V", [None])[0]):
                    if (known.get(other) if other else None) != spec:
                        raise NotImplementedError(
                            f"pipeline×mp: flash_attention in stage "
                            f"{si} has q/k/v with mismatched mp "
                            f"layouts; shard all three on the heads "
                            f"dim or none")
                mn = op.inputs.get("Mask", [None])[0]
                if mn and has_mp(mn):
                    raise NotImplementedError(
                        f"pipeline×mp: flash_attention mask {mn!r} in "
                        f"stage {si} is mp-sharded; the additive mask "
                        f"must be replicated")
                if spec is not None and not (
                        len(spec) == 4 and spec[1] == "mp"
                        and all(s != "mp" for j, s in enumerate(spec)
                                if j != 1)):
                    raise NotImplementedError(
                        f"pipeline×mp: flash_attention in stage {si} "
                        f"reads q/k/v sharded on a non-heads dim "
                        f"({spec}); only heads-dim (Megatron) sharding "
                        f"rides through the fused kernel")
                for n in op.output_arg_names():
                    if spec is not None:
                        known[n] = spec
                    else:
                        known.pop(n, None)
                continue
            bad = sorted(n for n in op.input_arg_names() if has_mp(n))
            if bad:
                raise NotImplementedError(
                    f"pipeline×mp: op {op.type!r} in stage {si} reads "
                    f"mp-sharded value(s) {bad}; only the matmul/"
                    f"elementwise/activation family may touch sharded "
                    f"activations — end the sharded region with a "
                    f"row-parallel matmul (the Megatron pattern puts "
                    f"softmax/dropout/layer_norm after the mp reduce) "
                    f"or drop the partition rule for these weights")
            for n in op.output_arg_names():
                known.pop(n, None)
    return known


def analyze_stages(program, n_stages: int):
    """Partition forward ops into stages via op_device annotations.

    Untagged ops inherit the previous op's stage (build order), starting
    at stage 0.  Returns (stage_ops, boundary_vars): boundary_vars[s] is
    the LIST of activations stage s hands to later stages.
    """
    meta = getattr(program, "_pipeline", None)
    fwd_end = meta["fwd_end"] if meta else len(program.global_block.ops)
    ops = [op for op in program.global_block.ops[:fwd_end]
           if op.type not in ("feed", "fetch")]
    stage_ops: List[list] = [[] for _ in range(n_stages)]
    cur = 0
    for op in ops:
        dev = op.attr("op_device", None)
        if dev:
            if not str(dev).startswith("stage:"):
                raise ValueError(
                    f"op_device {dev!r} is not a pipeline annotation; use "
                    f"device_guard('stage:N')")
            s = int(str(dev).split(":", 1)[1])
            if s < cur:
                raise ValueError(
                    f"op {op.type!r} tagged stage {s} appears after stage "
                    f"{cur} ops; stages must be contiguous in build order")
            if s >= n_stages:
                raise ValueError(
                    f"op {op.type!r} tagged stage {s} but the mesh has only "
                    f"{n_stages} pipeline stages")
            cur = s
        stage_ops[cur].append(op)

    boundaries = []
    produced_upto = set()
    for s in range(n_stages - 1):
        produced_upto |= {n for op in stage_ops[s]
                          for n in op.output_arg_names()}
        consumed = set()
        for later in range(s + 1, n_stages):
            for op in stage_ops[later]:
                for n in op.input_arg_names():
                    if n in produced_upto:
                        consumed.add(n)
        # cumulative: vars produced at ANY stage <= s and consumed later
        # ride every intervening boundary (skip connections pass through)
        act = sorted(consumed)
        if not act:
            raise ValueError(
                f"pipeline stage boundary {s}->{s + 1} passes no tensors; "
                f"every stage must feed the next")
        boundaries.append(act)
    return stage_ops, boundaries


class PackPlan:
    """Stage-ownership of training state + its packed layout.

    Ownership (which var lives on which stage, how optimizer ops
    partition) is computed at compile time from the program alone;
    the byte layout (offsets/width) is filled in lazily on the first
    `ensure_packed` call, when the scope has concrete shapes.
    """

    def __init__(self, n_stages, owned_stage, params_by_stage,
                 stage_opt_ops, shared_opt_ops, stage_ops, boundaries,
                 mp_degree=1, tp_dims=None, mp_specs=None):
        self.n_stages = n_stages
        self.owned_stage: Dict[str, int] = owned_stage
        self.owned_names = frozenset(owned_stage)
        self.params_by_stage = params_by_stage
        self.stage_opt_ops = stage_opt_ops
        self.shared_opt_ops = shared_opt_ops
        # the forward stage partition the plan was derived from, so the
        # compiled fn uses the identical view instead of re-deriving one
        self.stage_ops = stage_ops
        self.boundaries = boundaries
        # dp×mp×pp composition: tensor-parallel degree, per-var sharded
        # dim of the owned state (params + inheriting slots), and the
        # strict mp-layout walk's final spec map (fetch validation)
        self.mp_degree = int(mp_degree)
        self.tp_dims: Dict[str, int] = dict(tp_dims or {})
        self.mp_specs: Dict[str, tuple] = dict(mp_specs or {})
        # filled by _build_layout on first ensure_packed; entry shapes
        # are LOCAL (per-mp-rank shard) shapes, gshapes the global ones
        self.entries = None  # per stage: [(name, off, size, lshape), ...]
        self.layout = None   # name -> (stage, off, size, lshape)
        self.gshapes: Dict[str, tuple] = {}
        self.width = None

    # -- layout --------------------------------------------------------
    def _local_shape(self, name, gshape):
        d = self.tp_dims.get(name)
        if d is None or self.mp_degree <= 1:
            return tuple(gshape)
        ls = list(gshape)
        ls[d] = int(ls[d]) // self.mp_degree
        return tuple(ls)

    def _build_layout(self, shapes: Dict[str, tuple]):
        entries = [[] for _ in range(self.n_stages)]
        layout = {}
        cursor = [0] * self.n_stages
        for n in sorted(self.owned_stage):
            s = self.owned_stage[n]
            gshape = tuple(shapes[n])
            shape = self._local_shape(n, gshape)
            size = 1
            for d in shape:
                size *= int(d)
            off = cursor[s]
            cursor[s] += size
            entries[s].append((n, off, size, shape))
            layout[n] = (s, off, size, shape)
            self.gshapes[n] = gshape
        self.entries = entries
        self.layout = layout
        self.width = max(cursor) if max(cursor) > 0 else 1

    # -- host-side pack ------------------------------------------------
    def ensure_packed(self, scope, mesh):
        """Pack owned scope vars into the sharded (S, W) buffer.

        No-op when the scope already holds the packed buffer and every
        owned var is a PackedParamRef view.  A concrete array over an
        owned name (fresh startup run, paddle.load restore) triggers a
        re-pack of those entries.
        """
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ..framework.scope import PackedParamRef

        concrete = {}
        for n in self.owned_stage:
            if not scope.has_var(n):
                raise RuntimeError(
                    f"pipeline state var {n!r} is not in the scope; run "
                    f"the startup program first")
            v = scope.get_var(n)
            if not isinstance(v, PackedParamRef):
                concrete[n] = np.asarray(v)
        has_buf = scope.has_var(PACKED_STATE_VAR)

        if self.layout is None:
            # shapes come from concrete arrays or from the ref views a
            # sibling plan (different fetch list, same program) installed
            shapes = {}
            for n in self.owned_stage:
                v = scope.get_var(n)
                dt = np.dtype(v.dtype)
                if dt != np.float32:
                    raise NotImplementedError(
                        f"pipeline per-stage state sharding requires "
                        f"float32 training state; {n!r} is {dt}")
                shapes[n] = tuple(int(d) for d in v.shape)
            self._build_layout(shapes)
        S, W, MP = self.n_stages, self.width, self.mp_degree
        buf_shape = (S, W) if MP <= 1 else (S, MP, W)
        if has_buf:
            have = tuple(scope.get_var(PACKED_STATE_VAR).shape)
            if have != buf_shape:
                raise RuntimeError(
                    f"existing packed pipeline buffer has shape "
                    f"{have}, expected {buf_shape}; the program's "
                    f"stage-owned state changed — rebuild the scope")
        if has_buf and not concrete:
            return

        buf = np.zeros(buf_shape, np.float32)
        if has_buf:
            buf[:] = np.asarray(scope.get_var(PACKED_STATE_VAR))
        elif len(concrete) != len(self.owned_stage):
            missing = sorted(self.owned_names - set(concrete))
            raise RuntimeError(
                f"pipeline state vars {missing} are packed views but no "
                f"packed buffer exists in this scope")
        for n, v in concrete.items():
            s, off, size, shape = self.layout[n]
            gshape = self.gshapes[n]
            if tuple(v.shape) != tuple(gshape):
                raise ValueError(
                    f"pipeline state var {n!r} has shape {v.shape}, "
                    f"expected {gshape}")
            v = v.astype(np.float32)
            if MP <= 1:
                buf[s, off:off + size] = v.ravel()
                continue
            d = self.tp_dims.get(n)
            for r in range(MP):
                if d is None:
                    shard = v  # replicated: same bytes on every mp rank
                else:
                    k = int(gshape[d]) // MP
                    sl = [slice(None)] * len(gshape)
                    sl[d] = slice(r * k, (r + 1) * k)
                    shard = v[tuple(sl)]
                buf[s, r, off:off + size] = shard.ravel()
        sharding = NamedSharding(mesh, P("pp") if MP <= 1
                                 else P("pp", "mp"))
        arr = jax.make_array_from_callback(
            buf_shape, sharding, lambda idx: buf[idx])
        scope.set_var(PACKED_STATE_VAR, arr)
        for n, (s, off, size, shape) in self.layout.items():
            scope.set_var(n, PackedParamRef(
                scope, PACKED_STATE_VAR, s, off, self.gshapes[n],
                np.float32, mp_degree=MP,
                mp_dim=self.tp_dims.get(n)))


def plan_packing(program, n_stages, state_in, state_out, pipe,
                 tp_plan=None):
    """Compute stage ownership of params + optimizer slots and partition
    the optimizer ops per stage (compile-time; shapes come later).

    ``tp_plan`` (the ShardingPropagationPass output on the post-pass
    program) turns on the dp×mp×pp composition: rule-matched owned vars
    are packed as per-mp-rank shards and the strict mp-flow walk
    validates that sharded activations only meet understood ops."""
    from ..framework.lowering import PSEUDO_OPS

    stage_ops, boundaries = analyze_stages(program, n_stages)
    block = program.global_block
    grad_of = {(p if isinstance(p, str) else p.name):
               (g if isinstance(g, str) else g.name)
               for p, g in pipe["params_grads"]}
    grad_names = set(grad_of.values())
    opt_ops = [op for op in block.ops[pipe["bwd_end"]:]
               if op.type not in PSEUDO_OPS]
    state_vars = set(state_in) | set(state_out)

    # each parameter is owned by the single stage whose forward reads it
    param_stage: Dict[str, int] = {}
    for s, ops in enumerate(stage_ops):
        reads = {n for op in ops for n in op.input_arg_names()}
        for p in grad_of:
            if p in reads:
                if p in param_stage and param_stage[p] != s:
                    raise NotImplementedError(
                        f"parameter {p!r} is read by pipeline stages "
                        f"{param_stage[p]} and {s}; shared (tied) "
                        f"parameters are not supported by the pipeline "
                        f"executor")
                param_stage.setdefault(p, s)
    unread = sorted(set(grad_of) - set(param_stage))
    if unread:
        raise ValueError(
            f"parameters {unread} are not read by any pipeline stage")

    # optimizer slots inherit the stage of the param their op updates;
    # fixpoint so slot-only ops (chained accumulators) resolve too
    owned_stage: Dict[str, int] = dict(param_stage)
    op_stage: Dict[int, int] = {}  # opt-op index -> stage
    pending = list(enumerate(opt_ops))
    while True:
        progressed = False
        still = []
        for idx, op in pending:
            names = set(op.input_arg_names()) | set(op.output_arg_names())
            stages = {owned_stage[n] for n in names if n in owned_stage}
            if len(stages) > 1:
                raise NotImplementedError(
                    f"optimizer op {op.type!r} touches state owned by "
                    f"stages {sorted(stages)}; cross-stage optimizer ops "
                    f"(e.g. global grad clipping) are not supported under "
                    f"pipeline state sharding")
            if stages:
                s = stages.pop()
                op_stage[idx] = s
                for n in op.output_arg_names():
                    if n in state_vars and n not in grad_names:
                        owned_stage[n] = s
                progressed = True
            else:
                still.append((idx, op))
        pending = still
        if not progressed or not pending:
            break
    # preserve PROGRAM ORDER inside each stage: ops resolved in a later
    # fixpoint round must not execute after ops they precede
    stage_opt_ops: List[list] = [
        [opt_ops[i] for i in sorted(op_stage) if op_stage[i] == s]
        for s in range(n_stages)]
    shared_opt_ops = [op for _, op in pending]

    # shared ops must be computable replicated: no stage-owned state, no
    # per-stage gradients, no temporaries produced by per-stage opt ops
    stage_temps = {n for ops in stage_opt_ops for op in ops
                   for n in op.output_arg_names()}
    for op in shared_opt_ops:
        ins = set(op.input_arg_names())
        bad = sorted(ins & (set(owned_stage) | grad_names | stage_temps))
        if bad:
            raise NotImplementedError(
                f"optimizer op {op.type!r} reads {bad} which live on "
                f"individual pipeline stages; global reductions over "
                f"stage-sharded state/gradients are not supported")

    # forward may read owned NON-param state only via the carried-state
    # path, never from the packed buffer
    fwd_reads = {n for ops in stage_ops for op in ops
                 for n in op.input_arg_names()}
    bad = sorted(fwd_reads & (set(owned_stage) - set(param_stage)))
    if bad:
        raise NotImplementedError(
            f"forward ops read optimizer-slot state {bad} which is "
            f"sharded per stage")

    params_by_stage = [[p for p in sorted(grad_of) if param_stage[p] == s]
                       for s in range(n_stages)]

    # dp×mp×pp: per-owned-var sharded dim from the tp plan + the strict
    # mp-flow validation of the staged forward
    mp_degree = 1
    tp_dims: Dict[str, int] = {}
    mp_specs: Dict[str, tuple] = {}
    if tp_plan is not None and tp_plan.mp_degree > 1:
        mp_degree = tp_plan.mp_degree
        for n in owned_stage:
            spec = tuple(tp_plan.specs.get(n, ()))
            dims = [i for i, x in enumerate(spec) if x == "mp"]
            if len(dims) > 1:
                raise NotImplementedError(
                    f"pipeline×mp: {n!r} is mp-sharded on several dims "
                    f"({spec}); one 'mp' dim per var is supported")
            if dims:
                tp_dims[n] = dims[0]
        mp_specs = _validate_mp_flow(block, stage_ops, tp_plan)

    return PackPlan(n_stages, owned_stage, params_by_stage, stage_opt_ops,
                    shared_opt_ops, stage_ops, boundaries,
                    mp_degree=mp_degree, tp_dims=tp_dims,
                    mp_specs=mp_specs)


def _plan_stage_scans(program, plan, extra_needed):
    """Scan-over-layers INSIDE each pipeline stage: detect isomorphic
    per-layer op runs in every stage's forward partition (and its
    optimizer partition) with the LayerScanPass machinery, and plan
    them for a trace-level ``lax.scan`` — the stage body is traced once
    per run instead of once per layer, so trace+compile cost per stage
    stays ~constant in stage depth while numerics are bitwise (same
    ops, same order, same RNG-split chain threaded through the carry).

    Returns ``(fwd_runs, opt_runs, policy)``; ``None`` lists when the
    scan gate (FLAGS_layer_scan / recompute_configs stamps) is off.
    Rejected runs fall back to the unrolled trace, counted
    ``pipeline_scan_skipped_<reason>``."""
    from ..framework.passes import LayerScanPass
    from ..monitor import stat_add, stat_set

    enabled, min_layers, policy = LayerScanPass._config(program)
    if not enabled:
        return None, None, ""
    lsp = LayerScanPass()
    block = program.global_block

    def plan_list(ops_seq, base_need):
        ops_list = list(ops_seq)
        runs = []
        for (start, L, M) in lsp._find_runs(block, ops_list, min_layers):
            cplan, reason = lsp._classify(ops_list, start, L, M)
            if cplan is None:
                stat_add("pipeline_scan_skipped")
                stat_add(f"pipeline_scan_skipped_{reason}")
                continue
            need = set(base_need)
            for i, op in enumerate(ops_list):
                if not (cplan.start <= i < cplan.end):
                    need.update(op.input_arg_names())
            # carry INTERMEDIATES never materialize per layer: a mid-
            # chain value consumed outside the run keeps it unrolled
            bad = False
            for (t, w) in cplan.carries:
                mem_in = [sg[t] for sg in cplan.sigmas]
                mem_out = [sg[w] for sg in cplan.sigmas]
                if (set(mem_in[1:]) | set(mem_out[:-1])) & need:
                    bad = True
                    break
            if bad:
                stat_add("pipeline_scan_skipped")
                stat_add("pipeline_scan_skipped_carry_read")
                continue
            ys_emit = []
            for fam in cplan.ys:
                idxs = [i for i, m in enumerate(fam["members"])
                        if m in need]
                if idxs:
                    ys_emit.append((fam, idxs))
            runs.append({"start": cplan.start, "end": cplan.end,
                         "plan": cplan, "ys_emit": ys_emit})
        return runs

    fwd_runs = [plan_list(plan.stage_ops[s], extra_needed)
                for s in range(plan.n_stages)]
    # optimizer partitions: every owned per-layer state member is read
    # back by the packed-row update, so all ys materialize
    opt_need = set(plan.owned_names) | set(extra_needed)
    opt_runs = [plan_list(plan.stage_opt_ops[s], opt_need)
                for s in range(plan.n_stages)]
    n_runs = sum(len(r) for r in fwd_runs) + sum(len(r) for r in opt_runs)
    stat_set("pipeline_scan_segments", n_runs)
    return fwd_runs, opt_runs, policy


def _emit_stage_scan(ctx, run, lower_one, policy):
    """Trace one planned isomorphic run as a single ``lax.scan`` over
    stacked per-layer values (stacking env entries at trace time keeps
    the op semantics byte-for-byte: each iteration lowers exactly the
    template ops the unrolled trace would, with the same key chain)."""
    import jax.numpy as jnp

    from ..framework import jax_compat as _jc
    from ..framework.lowering import LoweringContext

    plan = run["plan"]
    env = ctx.env
    carry_t = [t for t, _ in plan.carries]
    carry_w = [w for _, w in plan.carries]
    xs_tpls = [f["tpl"] for f in plan.xs]
    xs_stacks = tuple(
        jnp.stack([jnp.asarray(env[m]) for m in f["members"]])
        for f in plan.xs)
    shared_vals = {n: env[n] for n in plan.shared}
    init = tuple(jnp.asarray(env[t]) for t in carry_t)
    ys_emit = run["ys_emit"]
    ys_tpls = [f["tpl"] for f, _ in ys_emit]
    has_key = ctx.rng_key is not None
    consumed = [False]

    def body(carry, x):
        key, cvals = (carry[0], carry[1:]) if has_key else (None, carry)
        benv = dict(shared_vals)
        benv.update(zip(carry_t, cvals))
        if xs_tpls:
            benv.update(zip(xs_tpls, x))
        bctx = LoweringContext(ctx.block, benv, rng_key=key,
                               mesh=ctx.mesh, axis_env=ctx.axis_env,
                               ring_axes=ctx.ring_axes,
                               fold_axes=ctx.fold_axes)
        for top in plan.tpl:
            lower_one(bctx, top)
        consumed[0] = consumed[0] or bctx.rng_consumed
        ys = tuple(jnp.asarray(benv[t]) for t in ys_tpls)
        nc = tuple(benv[w] for w in carry_w)
        if has_key:
            new_key = bctx.rng_key if bctx.rng_consumed else key
            return (new_key,) + nc, ys
        return nc, ys

    body = _jc.wrap_checkpoint(body, policy or "")
    init_carry = ((ctx.rng_key,) + init) if has_key else init
    final, ys_stacks = _jc.scan(body, init_carry,
                                xs_stacks if xs_stacks else None,
                                length=plan.M)
    if has_key:
        new_key, fvals = final[0], final[1:]
        if consumed[0]:
            ctx._rng = new_key
            ctx.rng_consumed = True
    else:
        fvals = final
    sigN = plan.sigmas[-1]
    for w, v in zip(carry_w, fvals):
        env[sigN[w]] = v
    for (fam, idxs), stack in zip(ys_emit, ys_stacks):
        for i in idxs:
            env[fam["members"][i]] = stack[i]


def build_pipeline_fn(program, mesh, feed_names, state_mut, state_const,
                      state_out, fetch_names, loss_name, params_grads,
                      n_microbatches, bwd_end, plan):
    """The compiled GPipe train step (plugs into Executor._compile).

    `state_mut` / `state_out` arrive WITH `PACKED_STATE_VAR` as their
    first entry and the stage-owned names already removed (the executor
    rewrites them via the PackPlan).  Signature matches the standard
    sharded path: (feed_vals, mut_vals, const_vals, rng) ->
    (fetches, new_state, rng).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..framework.jax_compat import shard_map

    from ..framework.lowering import (PSEUDO_OPS, LoweringContext,
                                      get_lowering)

    pp_axis = "pp"
    if pp_axis not in mesh.axis_names:
        raise ValueError(
            f"pipeline execution needs a 'pp' mesh axis; got "
            f"{mesh.axis_names}")
    dp_axis = "dp" if "dp" in mesh.axis_names else None
    dp_size = int(mesh.shape[dp_axis]) if dp_axis else 1
    # dp×mp×pp composition: the mp axis is live when the sharding pass
    # planned per-mp-rank shards (plan.mp_degree > 1); a mesh with an
    # 'mp' axis but no tp plan just replicates over it
    mp_axis = "mp" if (plan.mp_degree > 1
                       and "mp" in mesh.axis_names) else None
    if plan.mp_degree > 1 and mp_axis is None:
        raise ValueError(
            f"pipeline×mp: the sharding plan wants mp="
            f"{plan.mp_degree} but the mesh has no 'mp' axis "
            f"({mesh.axis_names})")
    if mp_axis and int(mesh.shape[mp_axis]) != plan.mp_degree:
        raise ValueError(
            f"pipeline×mp: mesh 'mp' axis has "
            f"{int(mesh.shape[mp_axis])} devices but the sharding plan "
            f"packed {plan.mp_degree}-way shards")
    S = int(mesh.shape[pp_axis])
    K = int(n_microbatches)
    stage_ops, boundaries = plan.stage_ops, plan.boundaries
    block = program.global_block
    assert state_mut and state_mut[0] == PACKED_STATE_VAR
    assert state_out and state_out[0] == PACKED_STATE_VAR
    rest_mut = state_mut[1:]
    rest_out = state_out[1:]

    from ..framework import flags as _flags
    from ..framework.passes import TP_CONSTRAINT_ATTR, decode_anchor
    from ..monitor import stat_set as _stat_set
    from ..observe import tracer as otrace
    from ..ops.collective_matmul import chunked_lower, f_identity, g_psum

    # GPipe's schedule cost, published for the overlap/telemetry plane:
    # of the K + S - 1 forward (and backward) ticks, S - 1 are fill/
    # drain bubbles on any given rank
    _stat_set("pp_stages", S)
    _stat_set("pp_bubble_fraction_ppm",
              int(round((S - 1) / float(K + S - 1) * 1e6)))

    grad_of = {(p if isinstance(p, str) else p.name):
               (g if isinstance(g, str) else g.name)
               for p, g in params_grads}

    # fetches: the loss plus any forward-produced activation
    producer_stage: Dict[str, int] = {}
    for s, ops in enumerate(stage_ops):
        for op in ops:
            for n in op.output_arg_names():
                producer_stage[n] = s  # last producer wins
    extra_fetches = [f for f in fetch_names if f != loss_name]
    for f in extra_fetches:
        if f not in producer_stage:
            raise NotImplementedError(
                f"pipeline fetch {f!r} is not produced by any forward "
                f"stage op; fetchable values are forward activations and "
                f"the loss")
    if plan.mp_specs:
        bad = [f for f in extra_fetches
               if any(x == "mp" for x in plan.mp_specs.get(f, ()))]
        if bad:
            raise NotImplementedError(
                f"pipeline×mp: fetches {bad} are mp-sharded "
                f"activations; fetch a value downstream of the "
                f"row-parallel reduce instead")

    # state written inside staged forwards (batch_norm running stats):
    # carried tick-to-tick on the owning stage's rank, published at the end
    state_out_set = set(rest_out)
    opt_writes = {n for ops in (plan.shared_opt_ops, *plan.stage_opt_ops)
                  for op in ops for n in op.output_arg_names()}
    carried_owner: Dict[str, int] = {}
    for s, ops in enumerate(stage_ops):
        for op in ops:
            for n in op.output_arg_names():
                if n in state_out_set and n not in plan.owned_names \
                        and n not in opt_writes:
                    carried_owner[n] = s
    carried_names = sorted(carried_owner)

    # scan-over-layers inside each stage (trace-level): names any run's
    # stacked outputs must still materialize into the env for
    scan_needed = set(carried_names) | set(fetch_names) | {loss_name}
    for b in boundaries:
        scan_needed.update(b)
    for ops_l in ([plan.shared_opt_ops] + list(plan.stage_opt_ops)):
        for op_ in ops_l:
            scan_needed.update(op_.input_arg_names())
    fwd_runs, opt_runs, scan_policy = _plan_stage_scans(
        program, plan, scan_needed)

    anchored = plan.mp_degree > 1 and mp_axis is not None
    cm_chunks = int(_flags.flag("collective_matmul_chunks") or 0)

    def _lower_one(ctx, op):
        """One op through its registered lowering, with the manual
        Megatron f/g handling at the sharding pass's anchors: a
        column-parallel matmul's row operand rides f (bwd mp-psum of
        dx), a contracted (partial) anchor's output rides g (fwd
        mp-psum) — optionally decomposed into latency-hiding
        collective-matmul chunks."""
        env2 = ctx.env
        try:
            ents = (op.attr(TP_CONSTRAINT_ATTR, []) or []) \
                if anchored else []
            if not ents:
                get_lowering(op.type)(ctx, op)
                return
            anchors = [decode_anchor(e) for e in ents]
            partials = {n for n, sp, p in anchors if p}
            cols = [n for n, sp, p in anchors
                    if not p and any(x == "mp" for x in sp)]
            wrapped = None
            if cols and op.type in _TP_MATMUL_TYPES:
                xn = op.inputs.get("X", [None])[0]
                if xn is not None and xn in env2 \
                        and xn not in op.output_arg_names() \
                        and not any(x == "mp" for x in
                                    plan.mp_specs.get(xn, ())):
                    # f is scoped to THIS op: each consumer of a
                    # replicated activation psums its own cotangent
                    # branch (psum(a)+psum(b) == psum(a+b))
                    wrapped = (xn, env2[xn])
                    env2[xn] = f_identity(env2[xn], mp_axis)
            try:
                done = False
                outs = op.output_arg_names()
                if partials and cm_chunks > 1 \
                        and op.type in _TP_MATMUL_TYPES \
                        and len(outs) == 1 and outs[0] in partials:
                    done = chunked_lower(
                        ctx, op, cm_chunks,
                        lambda v, _i: g_psum(v, mp_axis))
                if not done:
                    get_lowering(op.type)(ctx, op)
                    for n in partials:
                        if n in env2:
                            env2[n] = g_psum(env2[n], mp_axis)
            finally:
                if wrapped is not None:
                    env2[wrapped[0]] = wrapped[1]
        except Exception as e:
            site = op.callstack[-1] if op.callstack else "<unknown>"
            raise type(e)(
                f"while lowering pipeline op {op.type!r} (built at "
                f"{site}): {e}") from e

    def trace_ops(ops, env, rng_key=None, runs=None, stage=None):
        axes = (pp_axis,) \
            + ((mp_axis,) if mp_axis else ()) \
            + ((dp_axis,) if dp_axis else ())
        ctx = LoweringContext(block, env, rng_key=rng_key, mesh=mesh,
                              axis_env=axes,
                              fold_axes=(dp_axis,) if dp_axis else ())
        span = otrace.span("pipeline/stage", stage=stage,
                           ops=len(ops)) \
            if stage is not None else otrace.NULL_SPAN
        with span:
            if not runs:
                for op in ops:
                    _lower_one(ctx, op)
                return env
            ops_l = list(ops)
            run_at = {r["start"]: r for r in runs}
            i = 0
            while i < len(ops_l):
                r = run_at.get(i)
                if r is not None and all(
                        m in env for f_ in r["plan"].xs
                        for m in f_["members"]) \
                        and all(n in env for n in r["plan"].shared) \
                        and all(t in env for t, _ in r["plan"].carries):
                    _emit_stage_scan(ctx, r, _lower_one, scan_policy)
                    i = r["end"]
                else:
                    # an input the plan expected is absent from THIS
                    # env (e.g. a probe with a reduced view): the run
                    # traces unrolled — numerics identical either way
                    _lower_one(ctx, ops_l[i])
                    i += 1
        return env

    def unpack_stage(s, buf):
        """Reinterpret the local packed row with stage s's layout."""
        return {n: buf[off:off + size].reshape(shape)
                for (n, off, size, shape) in plan.entries[s]}

    def traced(feed_vals, mut_vals, const_vals, rng):
        # local packed-state shard -> (W,): (1, W) over P('pp'), or
        # (1, 1, W) over P('pp', 'mp') in the dp×mp×pp composition
        lbuf = mut_vals[0][0]
        if mp_axis:
            lbuf = lbuf[0]
        base_env = {}
        base_env.update(zip(rest_mut, mut_vals[1:]))
        base_env.update(zip(state_const, const_vals))
        full_feeds = dict(zip(feed_names, feed_vals))
        r = lax.axis_index(pp_axis)

        # micro-batch every feed: (B, ...) -> (K, B//K, ...)
        mb_feeds = {}
        for n, v in full_feeds.items():
            b = v.shape[0]
            if b % K:
                raise ValueError(
                    f"feed {n!r} batch {b} not divisible by micro_batch "
                    f"count {K}")
            mb_feeds[n] = v.reshape((K, b // K) + v.shape[1:])

        # ---- probe boundary + fetch structures stage by stage -----------
        mb_structs = {n: jax.ShapeDtypeStruct((v.shape[1],) + v.shape[2:],
                                              v.dtype)
                      for n, v in mb_feeds.items()}
        fetch_by_stage = [[f for f in extra_fetches
                           if producer_stage[f] == s] for s in range(S)]

        def probe_stage(s, in_structs):
            def f(acts_in):
                env = dict(base_env)
                for (n, off, size, shape) in plan.entries[s]:
                    env[n] = jnp.zeros(shape, jnp.float32)
                for n, sd in mb_structs.items():
                    env[n] = jnp.zeros(sd.shape, sd.dtype)
                if s > 0:
                    env.update(dict(zip(boundaries[s - 1], acts_in)))
                trace_ops(stage_ops[s], env,
                          rng_key=jax.random.PRNGKey(0),
                          runs=fwd_runs[s] if fwd_runs else None)
                bnd = tuple(jnp.asarray(env[n]) for n in boundaries[s]) \
                    if s < S - 1 else ()
                fts = tuple(jnp.asarray(env[f]) for f in fetch_by_stage[s])
                return bnd, fts

            dummy = tuple(jnp.zeros(sd.shape, sd.dtype)
                          for sd in (in_structs or ()))
            return jax.eval_shape(f, dummy)

        bnd_structs = []  # per boundary: tuple of ShapeDtypeStructs
        fetch_structs: Dict[str, object] = {}
        prev = None
        for s in range(S):
            prev, fstructs = probe_stage(s, prev)
            if s < S - 1:
                bnd_structs.append(prev)
            for f, sd in zip(fetch_by_stage[s], fstructs):
                fetch_structs[f] = sd
        for structs, names in zip(bnd_structs, boundaries):
            for sd, n in zip(structs, names):
                if not jnp.issubdtype(sd.dtype, jnp.floating):
                    raise NotImplementedError(
                        f"pipeline boundary tensor {n!r} has non-float "
                        f"dtype {sd.dtype}; route integer data to every "
                        f"stage via feeds instead")

        # classify fetches: scalar -> mean over microbatches (loss-like);
        # per-microbatch batched -> concatenated over microbatches
        mb_b = next(iter(mb_structs.values())).shape[0] if mb_structs else 0
        scalar_fetches, batched_fetches = [], []
        for f in extra_fetches:
            sd = fetch_structs[f]
            if sd.shape == ():
                if not jnp.issubdtype(sd.dtype, jnp.floating):
                    raise NotImplementedError(
                        f"pipeline scalar fetch {f!r} must be floating "
                        f"point, got {sd.dtype}")
                scalar_fetches.append(f)
            elif sd.shape and sd.shape[0] == mb_b:
                batched_fetches.append(f)
            else:
                raise NotImplementedError(
                    f"pipeline fetch {f!r} has per-microbatch shape "
                    f"{sd.shape}, which is neither a scalar nor batched "
                    f"over the micro-batch dim ({mb_b})")

        # ---- flat f32 carrier buffer, padded to the widest boundary -----
        def _size(sd):
            n = 1
            for d in sd.shape:
                n *= int(d)
            return n

        widths = [sum(_size(sd) for sd in structs)
                  for structs in bnd_structs]
        width = max(widths) if widths else 1
        zero_act = jnp.zeros((width,), jnp.float32)

        def pack(s, vals):
            flat = [jnp.ravel(v).astype(jnp.float32) for v in vals]
            buf = jnp.concatenate(flat) if flat else zero_act
            return jnp.pad(buf, (0, width - buf.shape[0]))

        def unpack(s, buf):
            vals = []
            off = 0
            for sd in bnd_structs[s]:
                n = _size(sd)
                vals.append(buf[off:off + n].reshape(sd.shape)
                            .astype(sd.dtype))
                off += n
            return vals

        def stage_key(rng_key, s, mb_idx):
            # deterministic per (stage, microbatch): the backward vjp
            # replays the forward with the same key -> identical dropout
            # masks (the correctness crux of RNG under GPipe)
            return jax.random.fold_in(jax.random.fold_in(rng_key, mb_idx), s)

        zero_fetches = tuple(jnp.zeros(fetch_structs[f].shape,
                                       fetch_structs[f].dtype)
                             for f in extra_fetches)

        def stage_fwd(s, buf, carried, act_buf, mb_idx, rng_key):
            """Uniform output across branches:
            (out_buf, loss, fetches, new_carried)."""
            env = dict(base_env)
            env.update(carried)
            env.update({p: v for p, v in unpack_stage(s, buf).items()
                        if p in grad_of})
            for n, v in mb_feeds.items():
                env[n] = lax.dynamic_index_in_dim(v, mb_idx, 0,
                                                  keepdims=False)
            if s > 0:
                env.update(dict(zip(boundaries[s - 1], unpack(s - 1, act_buf))))
            trace_ops(stage_ops[s], env, rng_key=stage_key(rng_key, s, mb_idx),
                      runs=fwd_runs[s] if fwd_runs else None, stage=s)
            new_carried = {
                n: (env[n] if carried_owner[n] == s else carried[n])
                for n in carried_names
            }
            fts = tuple(
                (jnp.asarray(env[f]).astype(fetch_structs[f].dtype)
                 if producer_stage[f] == s else z)
                for f, z in zip(extra_fetches, zero_fetches))
            if s < S - 1:
                out_buf = pack(s, [env[n] for n in boundaries[s]])
                return out_buf, jnp.zeros((), jnp.float32), fts, new_carried
            loss = jnp.asarray(env[loss_name], jnp.float32).reshape(())
            return zero_act, loss, fts, new_carried

        branches = [
            (lambda buf, c, a, i, k, s=s: stage_fwd(s, buf, c, a, i, k))
            for s in range(S)
        ]

        def switch_fwd(buf, carried, act_buf, mb_idx, rng_key):
            return lax.switch(r, branches, buf, carried, act_buf, mb_idx,
                              rng_key)

        fwd_perm = [(i, i + 1) for i in range(S - 1)]
        bwd_perm = [(i + 1, i) for i in range(S - 1)]

        # ---- forward fill (K + S - 1 ticks) -----------------------------
        T = K + S - 1
        saved_in = jnp.zeros((K, width), jnp.float32)
        losses = jnp.zeros((K,), jnp.float32)
        carried = {n: base_env[n] for n in carried_names}
        fetch_bufs = {f: jnp.zeros((K,) + tuple(fetch_structs[f].shape),
                                   fetch_structs[f].dtype)
                      for f in batched_fetches}
        scalar_acc = {f: jnp.zeros((), fetch_structs[f].dtype)
                      for f in scalar_fetches}
        recv = zero_act
        for t in range(T):
            mb = jnp.clip(t - r, 0, K - 1)
            active = jnp.logical_and(t - r >= 0, t - r < K)
            act_out, loss_mb, fts, new_carried = switch_fwd(
                lbuf, carried, recv, mb, rng)
            carried = {
                n: jnp.where(active, new_carried[n], carried[n])
                for n in carried_names
            }
            # remember this tick's stage INPUT for the backward vjp
            prev = lax.dynamic_index_in_dim(saved_in, mb, 0, keepdims=False)
            upd = jnp.where(active, recv, prev)
            saved_in = lax.dynamic_update_index_in_dim(saved_in, upd, mb, 0)
            losses = losses.at[mb].set(
                jnp.where(active, loss_mb, losses[mb]))
            for f, v in zip(extra_fetches, fts):
                if f in fetch_bufs:
                    prevf = lax.dynamic_index_in_dim(fetch_bufs[f], mb, 0,
                                                     keepdims=False)
                    fetch_bufs[f] = lax.dynamic_update_index_in_dim(
                        fetch_bufs[f], jnp.where(active, v, prevf), mb, 0)
                else:
                    scalar_acc[f] = scalar_acc[f] + jnp.where(
                        active, v, jnp.zeros_like(v))
            send = jnp.where(active, act_out, zero_act)
            recv = lax.ppermute(send, pp_axis, fwd_perm)

        # ---- backward drain (K + S - 1 ticks) ---------------------------
        # backward replays the forward with the SAME carried snapshot; the
        # vjp does not need exact per-tick stats (grads of running-stat
        # updates are zero: they are stop-gradient outputs)
        def stage_bwd(buf, act_in, mb_idx, g_act, g_loss):
            def f(buf_, act_in_):
                out_buf, loss, _, _ = switch_fwd(buf_, carried, act_in_,
                                                 mb_idx, rng)
                return out_buf, loss

            _, vjp = jax.vjp(f, buf, act_in)
            gb, gact = vjp((g_act, g_loss))
            return gb, gact

        grad_acc = jnp.zeros_like(lbuf)
        g_recv = zero_act
        for u in range(T):
            m = jnp.clip(u - (S - 1 - r), 0, K - 1)
            active = jnp.logical_and(u - (S - 1 - r) >= 0,
                                     u - (S - 1 - r) < K)
            is_last = r == S - 1
            g_loss = jnp.where(jnp.logical_and(active, is_last),
                               jnp.float32(1.0 / K), 0.0)
            g_act = jnp.where(is_last, zero_act, g_recv)
            act_in = lax.dynamic_index_in_dim(saved_in, m, 0,
                                              keepdims=False)
            gb, gact = stage_bwd(lbuf, act_in, m, g_act, g_loss)
            # where-select, not multiply: an inf/NaN jacobian at a
            # zero-filled inactive tick must not poison the accumulator
            grad_acc = grad_acc + jnp.where(active, gb,
                                            jnp.zeros_like(gb))
            g_send = jnp.where(active, gact, zero_act)
            g_recv = lax.ppermute(g_send, pp_axis, bwd_perm)

        # packed per-stage grads stay on their owning rank (that is the
        # memory point of PP); only dp replicas reduce
        if dp_axis:
            grad_acc = lax.psum(grad_acc, dp_axis) / dp_size

        # publish carried state from its owning rank (other ranks still
        # hold the initial value); under dp the shards saw different data
        # so running stats are pmean'd — same approximation sync-free BN
        # makes in the reference's multi-device path
        final_carried = {}
        for n in carried_names:
            owner = carried_owner[n]
            v = carried[n]
            picked = jnp.where(r == owner, v, jnp.zeros_like(v))
            out = lax.psum(picked, pp_axis)
            if dp_axis:
                out = lax.pmean(out, dp_axis)
            final_carried[n] = out

        # ---- optimizer: shared ops replicated, stage ops switched -------
        env_shared = dict(base_env)
        env_shared.update(final_carried)
        trace_ops(plan.shared_opt_ops, env_shared)

        def opt_branch(s):
            def f(buf, gbuf):
                env = dict(env_shared)
                env.update(unpack_stage(s, buf))
                for p in plan.params_by_stage[s]:
                    _, off, size, shape = plan.layout[p]
                    env[grad_of[p]] = gbuf[off:off + size].reshape(shape)
                trace_ops(plan.stage_opt_ops[s], env,
                          runs=opt_runs[s] if opt_runs else None, stage=s)
                newb = buf
                for (n, off, size, shape) in plan.entries[s]:
                    newb = newb.at[off:off + size].set(
                        jnp.ravel(env[n]).astype(jnp.float32))
                return newb
            return f

        new_buf = lax.switch(r, [opt_branch(s) for s in range(S)],
                             lbuf, grad_acc)

        # full-batch mean loss, present on the last rank; psum-broadcast
        loss_sum = jnp.where(r == S - 1, losses.sum(), 0.0)
        mean_loss = lax.psum(loss_sum, pp_axis) / K
        if dp_axis:
            mean_loss = lax.pmean(mean_loss, dp_axis)

        # assemble fetches in fetch_names order
        computed = {}
        for f in scalar_fetches:
            v = lax.psum(scalar_acc[f], pp_axis) / K
            if dp_axis:
                v = lax.pmean(v, dp_axis)
            computed[f] = v
        for f in batched_fetches:
            full = lax.psum(fetch_bufs[f], pp_axis)
            full = full.reshape((-1,) + tuple(fetch_structs[f].shape[1:]))
            if dp_axis:
                full = lax.all_gather(full, dp_axis, axis=0, tiled=True)
            computed[f] = full
        fetches = tuple(mean_loss if f == loss_name else computed[f]
                        for f in fetch_names)

        out_buf = new_buf[None, None, :] if mp_axis else new_buf[None, :]
        new_state = (out_buf,) \
            + tuple(env_shared[n] for n in rest_out)
        new_rng = jax.random.split(rng, 2)[0]
        return fetches, new_state, new_rng

    in_feed_specs = tuple(
        (P(dp_axis) if dp_axis else P()) for _ in feed_names)
    buf_spec = P(pp_axis, mp_axis) if mp_axis else P(pp_axis)
    return shard_map(
        traced,
        mesh=mesh,
        in_specs=(in_feed_specs,
                  (buf_spec,) + tuple(P() for _ in rest_mut),
                  tuple(P() for _ in state_const),
                  P()),
        out_specs=(tuple(P() for _ in fetch_names),
                   (buf_spec,) + tuple(P() for _ in rest_out),
                   P()),
        check_vma=False,
    )
