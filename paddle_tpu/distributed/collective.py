"""paddle.distributed collective functions.

Role parity: reference python/paddle/distributed/collective.py:89-444 —
broadcast/all_reduce/reduce/all_gather/scatter/barrier emitting c_* ops.
Dual-mode like the rest of the 2.0 API: on graph Variables they append
the c_* op (lowered to XLA collectives under the mesh); on eager Tensors
with a single process they are the world-size-1 identity semantics.
"""
from __future__ import annotations

from ..dispatch import op_call


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3


_RED_SUFFIX = {ReduceOp.SUM: "sum", ReduceOp.MAX: "max",
               ReduceOp.MIN: "min", ReduceOp.PROD: "prod"}


def all_reduce(tensor, op=ReduceOp.SUM, group=0, use_calc_stream=True):
    out = op_call(f"c_allreduce_{_RED_SUFFIX[op]}", {"X": tensor},
                  {"ring_id": int(group), "use_calc_stream": use_calc_stream})
    _write_back(tensor, out)
    return out


def reduce(tensor, dst, op=ReduceOp.SUM, group=0, use_calc_stream=True):
    out = op_call(f"c_reduce_{_RED_SUFFIX[op]}", {"X": tensor},
                  {"ring_id": int(group), "root_id": int(dst),
                   "use_calc_stream": use_calc_stream})
    _write_back(tensor, out)
    return out


def broadcast(tensor, src, group=0, use_calc_stream=True):
    out = op_call("c_broadcast", {"X": tensor},
                  {"ring_id": int(group), "root": int(src),
                   "use_calc_stream": use_calc_stream})
    _write_back(tensor, out)
    return out


def all_gather(tensor_list, tensor, group=0, use_calc_stream=True):
    out = op_call("c_allgather", {"X": tensor},
                  {"ring_id": int(group), "use_calc_stream": use_calc_stream})
    if isinstance(tensor_list, list):
        from ..tensor.manipulation import split

        from .parallel_env import get_world_size

        n = max(get_world_size(), 1)
        tensor_list.extend(split(out, n, axis=0) if n > 1 else [out])
    return out


def scatter(tensor, tensor_list=None, src=0, group=0, use_calc_stream=True):
    src_val = tensor
    if tensor_list:
        from ..tensor.manipulation import concat

        src_val = concat(list(tensor_list), axis=0)
    out = op_call("c_scatter", {"X": src_val},
                  {"ring_id": int(group), "root": int(src),
                   "use_calc_stream": use_calc_stream})
    _write_back(tensor, out)
    return out


def barrier(group=0):
    # process-level rendezvous outside compiled programs
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"pd_barrier_{group}")


def get_rank():
    from .parallel_env import get_rank as _r

    return _r()


def get_world_size():
    from .parallel_env import get_world_size as _w

    return max(_w(), 1)


def _write_back(tensor, out):
    """Reference collective funcs mutate their input tensor in place."""
    if hasattr(tensor, "_set_raw") and hasattr(out, "_value"):
        tensor._set_raw(out._value)
