"""Ring attention: sequence-parallel attention over an 'sp' mesh axis.

The reference has NO long-context story (SURVEY §5: no ring attention,
no sequence parallel — LoD + single-device fused attention only), so
this is a beyond-parity, TPU-first component: Q/K/V are sharded on the
sequence dim over the 'sp' axis; K/V blocks rotate around the ring via
`lax.ppermute` while each rank folds every block into its local queries
with the online-softmax (running max / running sum) rescaling — the
same math as flash attention, distributed.  Peak memory per chip is
O(S_local^2 -> S_local * D) instead of O(S^2), so sequence length
scales linearly with the ring size; the ppermute rides ICI.

Differentiable by construction: ppermute has a transpose rule, so
jax.vjp of this function IS ring attention backward (a reverse ring).
"""
from __future__ import annotations

import math


def ring_attention(q, k, v, axis_name="sp", sm_scale=None, causal=False,
                   bias=None):
    """Per-shard attention inside shard_map.

    Args:
      q, k, v: [B, H, S_local, D] — the local sequence shard.
      axis_name: mesh axis carrying the sequence ring.
      sm_scale: score scale; defaults to 1/sqrt(D).
      causal: causal masking with GLOBAL sequence positions (shard i
        holds positions [i*S_local, (i+1)*S_local)).
      bias: optional additive KEY mask [B, 1, 1, S_local] — each rank
        holds the mask shard for ITS keys; the shard rotates around the
        ring with its k/v block, so a padding mask costs one extra
        O(B*S_local) ppermute per step.  (A full [B,H,Sq,Sk] bias has
        no shardable rotation form and is rejected upstream.)

    Returns [B, H, S_local, D] in q.dtype.  Differentiable by
    construction — ppermute's transpose rule makes jax.vjp of this the
    reverse ring, including the bias cotangent.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    b, h, s_local, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    from ..framework.jax_compat import axis_size
    p = axis_size(axis_name)
    r = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % p) for i in range(p)]

    qf = q.astype(jnp.float32) * sm_scale
    neg = jnp.float32(-1e30)

    def block(qf, kj, vj, bj, j_rank):
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kj.astype(jnp.float32))
        if bj is not None:
            s = s + bj.astype(jnp.float32)  # [B,1,1,Sk] broadcasts
        if causal:
            q_pos = r * s_local + jnp.arange(s_local)
            k_pos = j_rank * s_local + jnp.arange(s_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, neg)
        m = jnp.max(s, axis=-1)  # [B, H, Sq]
        e = jnp.exp(s - m[..., None])
        l = jnp.sum(e, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", e, vj.astype(jnp.float32))
        return m, l, o

    # carry: (k_block, v_block, bias_block, owner_rank, m/l/acc)
    m_run = jnp.full((b, h, s_local), neg)
    l_run = jnp.zeros((b, h, s_local), jnp.float32)
    acc = jnp.zeros((b, h, s_local, d), jnp.float32)
    kj, vj, bj, owner = k, v, bias, r
    for _step in range(p):
        m_j, l_j, o_j = block(qf, kj, vj, bj, owner)
        m_new = jnp.maximum(m_run, m_j)
        alpha = jnp.exp(m_run - m_new)  # rescale old accumulator
        beta = jnp.exp(m_j - m_new)  # rescale this block
        l_run = l_run * alpha + l_j * beta
        acc = acc * alpha[..., None] + o_j * beta[..., None]
        m_run = m_new
        if _step < p - 1:
            kj = lax.ppermute(kj, axis_name, perm)
            vj = lax.ppermute(vj, axis_name, perm)
            if bj is not None:
                bj = lax.ppermute(bj, axis_name, perm)
            owner = (owner - 1) % p
    out = acc / jnp.maximum(l_run[..., None], 1e-30)
    return out.astype(q.dtype)


_SHARDED_CACHE = {}


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", sm_scale=None,
                           causal=False, bias=None):
    """Convenience wrapper: global [B, H, S, D] arrays in, shard_map over
    the sequence dim, global array out (for tests / eager use).  A key
    mask ``bias`` [B, 1, 1, S] shards on its key dim.  The jitted
    callable is cached per (mesh, axis, scale, causal, has-bias) so
    repeated calls hit the compile cache instead of retracing."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..framework.jax_compat import shard_map

    key = (id(mesh), axis_name, sm_scale, causal, bias is not None)
    fn = _SHARDED_CACHE.get(key)
    if fn is None:
        spec = P(None, None, axis_name, None)
        in_specs = (spec, spec, spec) + (
            (P(None, None, None, axis_name),) if bias is not None else ())

        def f(q, k, v, bias=None):
            return ring_attention(q, k, v, axis_name=axis_name,
                                  sm_scale=sm_scale, causal=causal,
                                  bias=bias)

        fn = jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                               out_specs=spec, check_vma=False))
        _SHARDED_CACHE[key] = fn
    args = (q, k, v) if bias is None else (q, k, v, bias)
    return fn(*args)
