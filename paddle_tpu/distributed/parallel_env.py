"""Parallel environment: mesh construction + process bootstrap.

Role parity: reference comm bootstrap — c_gen_nccl_id's TCP id-exchange +
c_comm_init's ring setup (operators/collective/) and the Gloo rendezvous
in fleet RoleMaker (role_maker.py:172).  TPU-native: one process per
HOST drives all its local chips; `jax.distributed.initialize` is the
rendezvous (coordinator address from the launcher's env), and a
`jax.sharding.Mesh` over all devices replaces every ring.  Collectives
ride ICI within a slice and DCN across hosts, scheduled by XLA.

Env contract (same names the reference launcher exports, SURVEY §2.9):
  PADDLE_TRAINER_ID        process (host) index
  PADDLE_TRAINERS_NUM      number of processes
  PADDLE_COORDINATOR       coordinator ip:port (ours; reference derives it
                           from PADDLE_TRAINER_ENDPOINTS[0])
  PADDLE_TRAINER_ENDPOINTS comma list, used as coordinator fallback
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

_mesh = None
_ring_axes: Dict[int, object] = {}


def init_parallel_env(mesh_shape: Optional[Sequence[int]] = None,
                      axis_names: Optional[Sequence[str]] = None):
    """Bootstrap multi-process (if env says so) and build the global mesh.

    Single process: mesh over all visible devices.  Multi process: after
    jax.distributed.initialize, jax.devices() spans all hosts.
    """
    import jax

    global _mesh
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)
    if nproc > 1 and not _distributed_initialized():
        coord = os.environ.get("PADDLE_COORDINATOR")
        if not coord:
            eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
            coord = eps.split(",")[0] if eps else None
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
        from ..framework.jax_compat import config_value, update_config

        if config_value("jax_cpu_collectives_implementation", "") is None:
            # XLA CPU needs an explicit cross-process collectives impl;
            # without it multi-process psum SILENTLY stays process-local
            # (each rank reduces only its own devices).  Setting it here
            # is safe for TPU backends (only consulted when the CPU
            # client is created) but must happen BEFORE any backend
            # exists, hence before jax.distributed.initialize.  Guarded
            # accessor: jax versions WITHOUT the config entry pick gloo
            # by default (or read the env var), so absence is a no-op,
            # not an AttributeError.
            update_config("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=rank)

    devices = jax.devices()
    if mesh_shape is None:
        from ..framework import flags as _flags

        pp = int(_flags.flag("pp_degree") or 0)
        ep = int(_flags.flag("ep_degree") or 0)
        if (pp > 1 or ep > 1) and axis_names is None:
            # FLAGS_pp_degree / FLAGS_ep_degree: carve a (dp, pp),
            # (dp, ep), or (dp, ep, pp) mesh out of the visible devices
            # so stage/expert-annotated programs run without an
            # explicit mesh_shape.  The degree a program runs with is
            # ALWAYS the mesh axis size; these defaults only shape
            # meshes built fully shapeless — an EXPLICIT axis_names
            # argument wins over the flags (the caller named its axes
            # for a reason).  Bad factorizations are rejected HERE,
            # with the axis named, instead of deep in GSPMD with an
            # opaque sharding error.
            carve = 1
            for name, deg in (("ep", ep), ("pp", pp)):
                if deg <= 1:
                    continue
                if len(devices) % deg != 0:
                    raise ValueError(
                        f"FLAGS_{name}_degree={deg} does not divide "
                        f"the {len(devices)} visible devices; pass an "
                        f"explicit mesh_shape or fix the flag")
                carve *= deg
            if carve > len(devices):
                raise ValueError(
                    f"FLAGS_ep_degree={ep} x FLAGS_pp_degree={pp} = "
                    f"{carve} exceeds the {len(devices)} visible "
                    f"devices ('ep' x 'pp' must fit the mesh); pass "
                    f"an explicit mesh_shape or fix the flags")
            if len(devices) % carve != 0:
                raise ValueError(
                    f"FLAGS_ep_degree={ep} x FLAGS_pp_degree={pp} = "
                    f"{carve} does not divide the {len(devices)} "
                    f"visible devices; pass an explicit mesh_shape or "
                    f"fix the flags")
            mesh_shape = [len(devices) // carve]
            axis_names = ["dp"]
            if ep > 1:
                mesh_shape.append(ep)
                axis_names.append("ep")
            if pp > 1:
                mesh_shape.append(pp)
                axis_names.append("pp")
            axis_names = tuple(axis_names)
        else:
            mesh_shape = [len(devices)]
            axis_names = tuple(axis_names or ("dp",))[:1] or ("dp",)
    elif axis_names is None:
        axis_names = ("dp",)
    import numpy as np

    n = int(np.prod(mesh_shape))
    if n != len(devices):
        raise ValueError(
            f"mesh shape {tuple(mesh_shape)} needs {n} devices, "
            f"have {len(devices)}")
    dev_array = np.asarray(devices).reshape(mesh_shape)
    _mesh = jax.sharding.Mesh(dev_array, tuple(axis_names))
    return _mesh


def _distributed_initialized() -> bool:
    # must NOT call jax.process_count(): that instantiates the XLA
    # backend, after which jax.distributed.initialize refuses to run
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:
        return False


def get_mesh():
    return _mesh


def set_mesh(mesh, ring_axes: Optional[Dict[int, object]] = None):
    global _mesh, _ring_axes
    _mesh = mesh
    if ring_axes is not None:
        _ring_axes = dict(ring_axes)
    return _mesh


def reset_mesh():
    global _mesh, _ring_axes
    _mesh = None
    _ring_axes = {}


def ring_axes() -> Dict[int, object]:
    return dict(_ring_axes)


def get_world_size() -> int:
    """Data-parallel world size (reference nranks): size of the dp axis,
    else the whole mesh, else 1."""
    if _mesh is None:
        return 1
    if "dp" in _mesh.axis_names:
        return int(_mesh.shape["dp"])
    return _mesh.size


def get_rank() -> int:
    # host-level rank (reference trainer_id is per device; on TPU the
    # process drives all local devices, so rank == process index)
    rid = os.environ.get("PADDLE_TRAINER_ID")
    if rid not in (None, ""):
        return int(rid)
    try:
        import jax

        return int(jax.process_index())
    except ImportError:  # pragma: no cover
        return 0


class ParallelEnv:
    """Reference fluid.dygraph.ParallelEnv parity."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return max(get_world_size(),
                   int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1))

    @property
    def device_id(self):
        return 0

    local_rank = rank
    nranks = world_size
