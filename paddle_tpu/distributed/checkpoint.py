"""Sharded (multi-host) checkpointing over ``paddle_tpu.ckpt``.

Role parity: the reference saves per-var LoDTensor streams through
save/load ops (save_op.cc:85) — single-host, full tensors.  TPU-native:
scope state can be GLOBAL jax arrays sharded over a mesh (ZeRO optimizer
shards, dp-replicated params, multi-process runs), so checkpoints go
through the :class:`~paddle_tpu.ckpt.CheckpointManager`: every process
writes exactly its shards (``shard_r<k>.npz``), rank 0 commits an
atomic SHA-256 manifest after the fleet barrier, restore re-assembles
the full values host-side and the next executor run re-distributes them
onto the CURRENT mesh — so a checkpoint written on one topology resumes
on any other (elastic).  This is the "exceed the reference" item SURVEY
§5 calls for in the failure-recovery row.

The single-host var_io format (fluid/io.py) remains the default for
plain programs; use this module when state lives on a mesh.
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

from ..ckpt import CheckpointError, CheckpointManager

# one manager per directory for the process lifetime (the old orbax
# path re-created its checkpointer object on every call)
_MANAGERS: Dict[str, CheckpointManager] = {}


def _manager(dirname: str) -> CheckpointManager:
    key = os.path.abspath(dirname)
    m = _MANAGERS.get(key)
    if m is None:
        # synchronous by design: save_sharded's contract is "returns ==
        # checkpoint durable" (callers sequence their own step loops);
        # use CheckpointManager directly for async saves
        m = _MANAGERS[key] = CheckpointManager(key, async_save=False)
    return m


def save_sharded(scope, dirname, var_names: Optional[Sequence[str]] = None,
                 step: Optional[int] = None):
    """Write the scope's state as a committed checkpoint step under
    ``dirname``.  Sharded arrays are written distributed (each process
    stores its own axis-0 block); call from EVERY process of a
    multi-process run.  Returns the sorted saved variable names.

    ``step`` defaults to one past the newest committed step in
    ``dirname``.  That inference reads the LOCAL directory listing, so
    on a multi-process run over a filesystem with metadata visibility
    lag (NFS attribute caching, object-store mounts) ranks could
    disagree and stall the commit barrier — pass the training step
    explicitly there; all ranks already agree on it."""
    m = _manager(dirname)
    if step is None:
        step = m.next_step()
    return m.save(step, scope=scope, var_names=var_names, wait=True)


def load_sharded(scope, dirname, var_names: Optional[Sequence[str]] = None):
    """Restore the newest intact checkpoint under ``dirname`` into the
    scope.  Values land as host arrays; the next executor run places
    and re-shards them per the compiled step's input specs (run the
    startup program — and for lazily-materialized sharded state, one
    step — first so the step is compiled for the right layout)."""
    dirname = os.path.abspath(dirname)
    if not os.path.isdir(dirname):
        raise CheckpointError(
            f"load_sharded: checkpoint directory {dirname!r} does not "
            f"exist (nothing was ever saved here, or the path is wrong)")
    m = _manager(dirname)
    meta = m.restore(scope=scope, var_names=var_names)
    if meta is None:
        raise CheckpointError(
            f"load_sharded: {dirname!r} contains no committed "
            f"checkpoint (empty directory, or only torn .tmp saves "
            f"from a crashed run)")
    return list(meta["vars"]) if var_names is None else sorted(
        n for n in meta["vars"] if n in set(var_names))
