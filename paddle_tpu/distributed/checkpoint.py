"""Sharded (multi-host) checkpointing over orbax.

Role parity: the reference saves per-var LoDTensor streams through
save/load ops (save_op.cc:85) — single-host, full tensors.  TPU-native:
scope state can be GLOBAL jax arrays sharded over a mesh (ZeRO optimizer
shards, dp-replicated params, multi-process runs), so checkpoints go
through orbax: every process writes exactly its shards, restore
re-assembles onto the current mesh, and replicated arrays are written
once.  This is the "exceed the reference" item SURVEY §5 calls for in
the failure-recovery row.

The single-host var_io format (fluid/io.py) remains the default for
plain programs; use this module when state lives on a mesh.
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _collect(scope, var_names: Optional[Sequence[str]]):
    from ..framework.executor import RNG_VAR

    if var_names is None:
        var_names = [n for n in scope.local_var_names()
                     if n != RNG_VAR and scope.get_var(n) is not None]
    return {n: scope.get_var(n) for n in var_names}


def save_sharded(scope, dirname, var_names: Optional[Sequence[str]] = None):
    """Write the scope's state as an orbax checkpoint.  Sharded arrays
    are written distributed (each process stores its own shards); call
    from EVERY process of a multi-process run."""
    state = _collect(scope, var_names)
    ckptr = _checkpointer()
    ckptr.save(os.path.join(os.path.abspath(dirname), "state"), state,
               force=True)
    ckptr.wait_until_finished()
    return sorted(state)


def load_sharded(scope, dirname, var_names: Optional[Sequence[str]] = None):
    """Restore into the scope.  Each var's target shape/dtype/sharding is
    taken from the CURRENT scope value (run the startup program — and for
    lazily-materialized sharded state, one step — first), so arrays come
    back distributed exactly as the executor expects them."""
    import jax

    state = _collect(scope, var_names)
    target = {}
    for n, v in state.items():
        if hasattr(v, "sharding") and hasattr(v, "dtype"):
            target[n] = jax.ShapeDtypeStruct(v.shape, v.dtype,
                                             sharding=v.sharding)
        else:
            target[n] = np.asarray(v)
    ckptr = _checkpointer()
    restored = ckptr.restore(os.path.join(os.path.abspath(dirname), "state"),
                             target=target)
    for n, v in restored.items():
        scope.set_var(n, v)
    return sorted(restored)
