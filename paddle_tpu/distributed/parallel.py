"""Dygraph DataParallel + spawn.

Role parity: reference python/paddle/fluid/dygraph/parallel.py
(`DataParallel`:335, `scale_loss`:432, `apply_collective_grads`:441) and
distributed/spawn.py:231.  TPU-native: within one host the mesh/SPMD
path (to_static or the fleet static flow) is the performant route; this
wrapper keeps eager multi-process semantics — grads are psum'd across
processes via a tiny pjit'd all-reduce when jax.distributed is live, and
it is the world-size-1 identity otherwise.
"""
from __future__ import annotations

from ..dygraph.layers import Layer
from .parallel_env import ParallelEnv, get_world_size, init_parallel_env


def prepare_context(strategy=None):
    init_parallel_env()
    return ParallelEnv()


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1):
        super().__init__()
        self._layers = layers
        self._nranks = max(get_world_size(),
                           ParallelEnv().world_size)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        if self._nranks <= 1:
            return loss
        from ..tensor.math import scale

        return scale(loss, 1.0 / self._nranks)

    def apply_collective_grads(self):
        if self._nranks <= 1:
            return
        import jax

        if jax.process_count() <= 1:
            return  # single process drives all devices; grads already global
        from jax.experimental import multihost_utils

        for p in self._layers.parameters():
            if p.grad is not None:
                summed = multihost_utils.process_allgather(p.grad._value)
                p.grad._set_raw(summed.sum(axis=0))

    # delegation so DataParallel looks like the wrapped layer
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Reference distributed/spawn.py: one process per device.  On TPU one
    process drives every local chip, so spawn runs func in THIS process
    with the parallel env initialized (nprocs>1 across hosts is the
    launcher's job)."""
    init_parallel_env()
    result = func(*args)
    return result
