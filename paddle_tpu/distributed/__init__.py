"""`paddle.distributed` equivalent (reference python/paddle/distributed/).

SURVEY §2.8/2.9: collective functions, fleet facade, parallel env (mesh),
launcher.  The communication backend is XLA collectives over ICI/DCN —
see ops/collective.py for the c_* lowerings.
"""
from . import embedding  # noqa: F401
from . import fleet  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    barrier,
    broadcast,
    get_rank,
    get_world_size,
    reduce,
    scatter,
)
from .parallel import DataParallel, prepare_context, spawn  # noqa: F401
from .parallel_env import (  # noqa: F401
    ParallelEnv,
    get_mesh,
    init_parallel_env,
    reset_mesh,
    set_mesh,
)
