"""paddle_tpu.distributed.embedding — sharded embedding tables.

The TPU-native replacement for the reference's parameter-server
distributed-embedding stack (fleet.distributed_embedding over gRPC PS
workers, SelectedRows sparse gradients): large tables live ROW-SHARDED
over the mesh's 'mp' axis and lookups route ids to their owning shards
with one all-to-all (ops/embedding_ops.py is the engine; the
ShardingPropagationPass stamps lookups whose table it row-sharded).

Three entry levels:

- :func:`distributed_embedding` — static-graph builder (the
  ``fleet.distributed_embedding`` facade): a ``lookup_table_v2`` op
  with ``is_sparse=True``, which the sharding pass row-shards over
  'mp' by default (no partition rule needed).  Identical to
  ``layers.embedding(is_sparse=True)``.
- :func:`lookup` — eager/host helper over a concrete table (dense
  custom_vjp reference), recording the ``emb_lookup_seconds``
  histogram and the ``emb_oov_ids`` gauge.
- :func:`sharded_lookup` — the raw per-shard engine for code already
  inside shard_map (re-export of
  :func:`~paddle_tpu.ops.embedding_ops.sharded_embedding_lookup`).

:func:`partition_rules` builds explicit row-sharding rules for tables
NOT flagged sparse; :func:`shard_info` reports the physical layout of
a planned table (rows per shard, per-chip bytes — what the README's
"table exceeds one chip" sizing math reads).
"""
from __future__ import annotations

import re
import time

from ..ops.embedding_ops import (alltoall_bytes_per_lookup,
                                 embedding_lookup_ref,
                                 sharded_embedding_lookup as sharded_lookup)

__all__ = [
    "distributed_embedding",
    "lookup",
    "sharded_lookup",
    "partition_rules",
    "shard_info",
    "alltoall_bytes_per_lookup",
]


def distributed_embedding(input, size, param_attr=None, padding_idx=None,
                          dtype="float32", name=None):
    """Static-graph sharded embedding: rows of the ``size[0] ×
    size[1]`` table live distributed over the mesh's 'mp' axis (the
    pass seeds P('mp', None) for is_sparse tables), and the gradient
    is a dense scatter-add on the owning shard.  Outside a tensor-
    parallel fleet program the table degrades to dense replicated —
    loudly (``emb_sparse_fallback_dense``)."""
    from ..layers import embedding as _layers_embedding

    return _layers_embedding(
        input, size, is_sparse=True, padding_idx=padding_idx,
        param_attr=param_attr, dtype=dtype, name=name)


def lookup(table, ids, padding_idx=None):
    """Eager lookup over a concrete (host/global) table with the
    engine's exact gradient semantics (custom_vjp dense scatter-add,
    padding row pinned zero).  Telemetry: ``emb_lookup_seconds``
    histogram + ``emb_oov_ids`` gauge (ids outside ``[0, vocab)``,
    which the engine maps to zero rows)."""
    import numpy as np

    from ..monitor import stat_add, stat_time

    t0 = time.perf_counter()
    pad = -1 if padding_idx is None else int(padding_idx)
    out = embedding_lookup_ref(table, ids, pad)
    try:
        idh = np.asarray(ids)
        vocab = int(table.shape[0])
        oov = int(((idh < 0) | (idh >= vocab)).sum())
        if oov:
            stat_add("emb_oov_ids", oov)
    except Exception:  # noqa: BLE001 — telemetry only
        pass
    stat_time("emb_lookup_seconds", time.perf_counter() - t0)
    return out


def partition_rules(*table_names):
    """Explicit row-sharding rules for named tables — merge into
    ``DistributedStrategy.tensor_parallel_configs['partition_rules']``
    when a table is built without ``is_sparse`` (the flag already
    seeds the layout by itself)."""
    return [(rf"^{re.escape(str(n))}$", "mp,None") for n in table_names]


def shard_info(program, table_name, mesh=None):
    """Physical layout of a planned table: where its rows live and
    what one chip holds.  Requires the post-pass program (the plan is
    ``program._tp_plan``); ``mesh`` defaults to the active parallel
    env's."""
    import numpy as np

    from ..framework import dtypes as _dtypes
    from .parallel_env import get_mesh

    plan = getattr(program, "_tp_plan", None)
    if plan is None:
        raise ValueError(
            "program has no sharding plan (_tp_plan); run it through a "
            "tensor-parallel fleet executor first")
    mesh = mesh if mesh is not None else get_mesh()
    var = program.global_block._find_var_recursive(table_name)
    if var is None:
        raise KeyError(f"no var {table_name!r} in program")
    spec = plan.spec_tuple(table_name)
    divisor = plan.shard_divisor(table_name, mesh)
    vocab = int(var.shape[0])
    itemsize = np.dtype(_dtypes.to_str(var.dtype)).itemsize
    global_bytes = int(np.prod([int(s) for s in var.shape])) * itemsize
    row_sharded = bool(spec) and spec[0] == "mp"
    return {
        "table": table_name,
        "spec": spec,
        "row_sharded": row_sharded,
        "shard_divisor": divisor,
        "rows_per_shard": vocab // divisor if row_sharded else vocab,
        "global_bytes": global_bytes,
        "bytes_per_chip": global_bytes // divisor,
    }
