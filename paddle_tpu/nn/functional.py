"""`paddle.nn.functional` equivalent (reference python/paddle/nn/functional/).

Dual-mode: every function runs eagerly on Tensors or appends IR ops for
Variables (see dispatch.op_call).
"""
from __future__ import annotations

from ..dispatch import op_call
from ..framework import dtypes

# -- activations -------------------------------------------------------------


def _unary(op_type, **fixed):
    def fn(x, name=None, **kw):
        attrs = dict(fixed)
        attrs.update(kw)
        return op_call(op_type, {"X": x}, attrs, name=name)

    fn.__name__ = op_type
    return fn


relu = _unary("relu")
relu6 = _unary("relu6")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
softplus = _unary("softplus")
softsign = _unary("softsign")
silu = _unary("silu")
mish = _unary("mish")
tanhshrink = _unary("tanh_shrink")
log_sigmoid = _unary("logsigmoid")


def gelu(x, approximate=False, name=None):
    return op_call("gelu", {"X": x}, {"approximate": bool(approximate)}, name=name)


def leaky_relu(x, negative_slope=0.01, name=None):
    return op_call("leaky_relu", {"X": x}, {"alpha": float(negative_slope)}, name=name)


def elu(x, alpha=1.0, name=None):
    return op_call("elu", {"X": x}, {"alpha": float(alpha)}, name=name)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return op_call("selu", {"X": x}, {"scale": scale, "alpha": alpha}, name=name)


def celu(x, alpha=1.0, name=None):
    return op_call("celu", {"X": x}, {"alpha": float(alpha)}, name=name)


def hardswish(x, name=None):
    return op_call("hard_swish", {"X": x},
                   {"threshold": 6.0, "scale": 6.0, "offset": 3.0}, name=name)


def hardsigmoid(x, slope=1 / 6, offset=0.5, name=None):
    return op_call("hard_sigmoid", {"X": x}, {"slope": slope, "offset": offset}, name=name)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return op_call("brelu", {"X": x}, {"t_min": float(min), "t_max": float(max)}, name=name)


def hardshrink(x, threshold=0.5, name=None):
    return op_call("hard_shrink", {"X": x}, {"threshold": float(threshold)}, name=name)


def softshrink(x, threshold=0.5, name=None):
    return op_call("softshrink", {"X": x}, {"lambda": float(threshold)}, name=name)


def thresholded_relu(x, threshold=1.0, name=None):
    return op_call("thresholded_relu", {"X": x}, {"threshold": float(threshold)}, name=name)


def swish(x, name=None):
    return op_call("swish", {"X": x}, {"beta": 1.0}, name=name)


def prelu(x, weight, name=None):
    mode = "all" if int(_numel(weight)) == 1 else "channel"
    return op_call("prelu", {"X": x, "Alpha": weight}, {"mode": mode}, name=name)


def maxout(x, groups, axis=1, name=None):
    return op_call("maxout", {"X": x}, {"groups": int(groups), "axis": int(axis)},
                   name=name)


def softmax(x, axis=-1, dtype=None, name=None):
    out = op_call("softmax", {"X": x}, {"axis": int(axis)}, name=name)
    if dtype is not None:
        from ..tensor.math import cast

        out = cast(out, dtype)
    return out


def log_softmax(x, axis=-1, dtype=None, name=None):
    out = op_call("log_softmax", {"X": x}, {"axis": int(axis)}, name=name)
    if dtype is not None:
        from ..tensor.math import cast

        out = cast(out, dtype)
    return out


def _numel(x):
    import numpy as np

    return int(np.prod(x.shape)) if x.shape else 1


# -- linear / conv -----------------------------------------------------------


def linear(x, weight, bias=None, name=None):
    out = op_call("matmul_v2", {"X": x, "Y": weight},
                  {"trans_x": False, "trans_y": False}, name=name)
    if bias is not None:
        out = op_call("elementwise_add", {"X": out, "Y": bias}, {"axis": -1})
    return out


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    stride = [stride] * 2 if isinstance(stride, int) else list(stride)
    dilation = [dilation] * 2 if isinstance(dilation, int) else list(dilation)
    if isinstance(padding, str):
        pad_attr, pad_alg = [0, 0], padding.upper()
    else:
        pad_attr = [padding] * 2 if isinstance(padding, int) else list(padding)
        pad_alg = "EXPLICIT"
    out = op_call("conv2d", {"Input": x, "Filter": weight},
                  {"strides": stride, "paddings": pad_attr, "dilations": dilation,
                   "groups": int(groups), "padding_algorithm": pad_alg,
                   "data_format": data_format},
                  outs=("Output",), name=name)
    if bias is not None:
        out = op_call("elementwise_add", {"X": out, "Y": bias},
                      {"axis": 1 if data_format == "NCHW" else -1})
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCHW", name=None):
    stride = [stride] * 2 if isinstance(stride, int) else list(stride)
    dilation = [dilation] * 2 if isinstance(dilation, int) else list(dilation)
    pad_attr = [padding] * 2 if isinstance(padding, int) else list(padding)
    out = op_call("conv2d_transpose", {"Input": x, "Filter": weight},
                  {"strides": stride, "paddings": pad_attr, "dilations": dilation,
                   "groups": int(groups), "data_format": data_format,
                   "output_padding": ([output_padding] * 2 if isinstance(output_padding, int)
                                      else list(output_padding)),
                   "output_size": list(output_size) if output_size else []},
                  outs=("Output",), name=name)
    if bias is not None:
        out = op_call("elementwise_add", {"X": out, "Y": bias},
                      {"axis": 1 if data_format == "NCHW" else -1})
    return out


# -- pooling -----------------------------------------------------------------


def _pool(x, kernel, pooling_type, stride, padding, ceil_mode, global_pooling,
          adaptive=False, exclusive=True, name=None):
    kernel = [kernel] * 2 if isinstance(kernel, int) else list(kernel)
    stride = kernel if stride is None else ([stride] * 2 if isinstance(stride, int) else list(stride))
    padding = [padding] * 2 if isinstance(padding, int) else list(padding)
    return op_call("pool2d", {"X": x},
                   {"ksize": kernel, "pooling_type": pooling_type, "strides": stride,
                    "paddings": padding, "ceil_mode": bool(ceil_mode),
                    "global_pooling": bool(global_pooling), "adaptive": bool(adaptive),
                    "exclusive": bool(exclusive)}, name=name)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, "max", stride, padding, ceil_mode, False, name=name)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, "avg", stride, padding, ceil_mode, False,
                 exclusive=exclusive, name=name)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    size = [output_size] * 2 if isinstance(output_size, int) else list(output_size)
    return op_call("pool2d", {"X": x},
                   {"ksize": size, "pooling_type": "avg", "strides": [1, 1],
                    "paddings": [0, 0], "ceil_mode": False, "global_pooling": False,
                    "adaptive": True, "exclusive": True}, name=name)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    size = [output_size] * 2 if isinstance(output_size, int) else list(output_size)
    return op_call("pool2d", {"X": x},
                   {"ksize": size, "pooling_type": "max", "strides": [1, 1],
                    "paddings": [0, 0], "ceil_mode": False, "global_pooling": False,
                    "adaptive": True, "exclusive": True}, name=name)


# -- norm --------------------------------------------------------------------


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    ns = [normalized_shape] if isinstance(normalized_shape, int) else list(normalized_shape)
    begin = len(x.shape) - len(ns)
    outs = op_call("layer_norm", {"X": x, "Scale": weight, "Bias": bias},
                   {"epsilon": float(epsilon), "begin_norm_axis": begin},
                   outs=("Y", "Mean", "Variance"), name=name)
    return outs[0]


def batch_norm(x, running_mean, running_var, weight, bias, training=False,
               momentum=0.9, epsilon=1e-05, data_format="NCHW", name=None):
    outs = op_call("batch_norm",
                   {"X": x, "Scale": weight, "Bias": bias,
                    "Mean": running_mean, "Variance": running_var},
                   {"momentum": float(momentum), "epsilon": float(epsilon),
                    "is_test": not training, "data_layout": data_format,
                    "use_global_stats": not training},
                   outs=("Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"),
                   name=name)
    y, mean_out, var_out = outs[0], outs[1], outs[2]
    if training and hasattr(running_mean, "_set_raw") and mean_out is not None:
        running_mean._set_raw(mean_out._value)
        running_var._set_raw(var_out._value)
    return y


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    outs = op_call("group_norm", {"X": x, "Scale": weight, "Bias": bias},
                   {"epsilon": float(epsilon), "groups": int(num_groups),
                    "data_layout": data_format},
                   outs=("Y", "Mean", "Variance"), name=name)
    return outs[0]


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    outs = op_call("instance_norm", {"X": x, "Scale": weight, "Bias": bias},
                   {"epsilon": float(eps)},
                   outs=("Y", "SavedMean", "SavedVariance"), name=name)
    return outs[0]


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    from ..tensor import linalg, math

    n = linalg.norm(x, p=float(p), axis=axis, keepdim=True)
    return math.divide(x, math.maximum(n, _full_like_scalar(n, epsilon)))


def _full_like_scalar(x, v):
    from ..tensor.creation import full_like

    return full_like(x, v)


# -- dropout / embedding -----------------------------------------------------


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    impl = "upscale_in_train" if mode == "upscale_in_train" else "downgrade_in_infer"
    return op_call("dropout", {"X": x},
                   {"dropout_prob": float(p), "is_test": not training,
                    "dropout_implementation": impl},
                   outs=("Out",), name=name)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    # sparse=True requests the distributed row-sharded engine: the
    # is_sparse attr is what ShardingPropagationPass keys the table's
    # P('mp', None) seeding on (historically the flag was silently
    # dropped; without an active sharding plan the lowering now counts
    # emb_sparse_fallback_dense and warns)
    return op_call("lookup_table_v2", {"Ids": x, "W": weight},
                   {"padding_idx": -1 if padding_idx is None else int(padding_idx),
                    "is_sparse": bool(sparse)},
                   name=name)


def one_hot(x, num_classes, name=None):
    return op_call("one_hot_v2", {"X": x}, {"depth": int(num_classes)},
                   dtype="float32", name=name)


# -- losses ------------------------------------------------------------------


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1,
                               ignore_index=-100, return_softmax=False):
    loss, sm = op_call("softmax_with_cross_entropy",
                       {"Logits": logits, "Label": label},
                       {"soft_label": bool(soft_label), "axis": int(axis),
                        "ignore_index": int(ignore_index)},
                       outs=("Loss", "Softmax"))
    return (loss, sm) if return_softmax else loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    from ..tensor import math as _m
    from ..tensor.manipulation import squeeze

    if weight is not None and not soft_label:
        lp = log_softmax(input, axis) if use_softmax else input
        return nll_loss(lp, label, weight, ignore_index, reduction)
    if use_softmax:
        loss = softmax_with_cross_entropy(input, label, soft_label, axis, ignore_index)
    else:
        loss = op_call("cross_entropy2", {"X": input, "Label": label},
                       {"ignore_index": int(ignore_index)}, outs=("Y",))
    if len(loss.shape) > 1 and loss.shape[-1] == 1:
        loss = squeeze(loss, -1)
    if reduction == "mean":
        return _m.mean(loss)
    if reduction == "sum":
        return _m.sum(loss)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    from ..tensor import math as _m

    loss = op_call("square_error_cost", {"X": input, "Y": label}, {})
    if reduction == "mean":
        return _m.mean(loss)
    if reduction == "sum":
        return _m.sum(loss)
    return loss


def l1_loss(input, label, reduction="mean", name=None):
    from ..tensor import math as _m

    loss = _m.abs(_m.subtract(input, label))
    if reduction == "mean":
        return _m.mean(loss)
    if reduction == "sum":
        return _m.sum(loss)
    return loss


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    loss = op_call("huber_loss", {"X": input, "Y": label}, {"delta": float(delta)},
                   outs=("Out",))
    from ..tensor import math as _m

    if reduction == "mean":
        return _m.mean(loss)
    if reduction == "sum":
        return _m.sum(loss)
    return loss


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    from ..tensor import math as _m

    if pos_weight is not None:
        # -[pw*y*log(sig(x)) + (1-y)*log(1-sig(x))], numerically stable form
        log_sig = _m.neg(softplus(_m.neg(logit)))
        log_one_minus = _m.neg(softplus(logit))
        loss = _m.neg(_m.add(_m.multiply(_m.multiply(label, pos_weight), log_sig),
                             _m.multiply(_m.subtract(
                                 _full_like_scalar(label, 1.0), label),
                                 log_one_minus)))
    else:
        loss = op_call("sigmoid_cross_entropy_with_logits",
                       {"X": logit, "Label": label}, {"ignore_index": -100})
    if weight is not None:
        loss = _m.multiply(loss, weight)
    if reduction == "mean":
        return _m.mean(loss)
    if reduction == "sum":
        return _m.sum(loss)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    """input is log-probabilities (reference nn/functional/loss.py nll_loss)."""
    from ..tensor import logic, math as _m
    from ..tensor.math import cast
    from .functional_helpers import gather_label_scores

    loss = _m.neg(gather_label_scores(input, label))
    w = None
    if weight is not None:
        w = gather_label_scores(
            _broadcast_rows(weight, input), label)
        loss = _m.multiply(loss, w)
    if ignore_index >= 0:
        keep = cast(logic.not_equal(
            label, _full_like_scalar(label, ignore_index)), input.dtype)
        if len(keep.shape) > len(loss.shape):
            from ..tensor.manipulation import squeeze

            keep = squeeze(keep, -1)
        loss = _m.multiply(loss, keep)
        if reduction == "mean":
            denom = _m.sum(_m.multiply(w, keep) if w is not None else keep)
            return _m.divide(_m.sum(loss), _m.maximum(
                denom, _full_like_scalar(denom, 1e-12)))
    if reduction == "mean":
        if w is not None:
            return _m.divide(_m.sum(loss), _m.sum(w))
        return _m.mean(loss)
    if reduction == "sum":
        return _m.sum(loss)
    return loss


def _broadcast_rows(weight, like):
    """[C] class-weight vector viewed as rows compatible with like [N, C]."""
    from ..tensor.manipulation import expand, unsqueeze

    w = unsqueeze(weight, 0)
    return expand(w, [like.shape[0], weight.shape[0]])


def kl_div(input, label, reduction="mean", name=None):
    from ..tensor import math as _m

    # input is log-prob, label is prob: label * (log(label) - input)
    eps = 1e-12
    term = _m.multiply(label, _m.subtract(_m.log(_m.maximum(
        label, _full_like_scalar(label, eps))), input))
    if reduction == "mean":
        return _m.mean(term)
    if reduction == "sum":
        return _m.sum(term)
    return term


# -- shape/pad/misc ----------------------------------------------------------


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if mode != "constant":
        # reflect/replicate/circular ride the pad2d/pad3d op (reference
        # operators/pad2d_op); `pad` here is the spatial-only pair list
        op_type = "pad3d" if len(x.shape) == 5 else "pad2d"
        return op_call(op_type, {"X": x},
                       {"paddings": [int(p) for p in pad], "mode": mode,
                        "value": float(value), "data_format": data_format},
                       name=name)
    if len(pad) == len(x.shape) * 2:
        paddings = list(pad)
    else:
        # paddle 2.x: pad only the trailing dims, [left, right, ...] per dim pair
        n_pre = len(x.shape) - len(pad) // 2
        paddings = [0, 0] * n_pre
        # reference order: last-dim pairs come first in `pad`
        dims = len(pad) // 2
        per_dim = [pad[2 * i:2 * i + 2] for i in range(dims)]
        for pr in reversed(per_dim):
            paddings.extend(pr)
    return op_call("pad", {"X": x}, {"paddings": paddings, "pad_value": float(value)},
                   name=name)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from ..dygraph.eager import apply_jax
    import jax

    ks = [kernel_sizes] * 2 if isinstance(kernel_sizes, int) else list(kernel_sizes)
    st = [strides] * 2 if isinstance(strides, int) else list(strides)
    pd = [paddings] * 2 if isinstance(paddings, int) else list(paddings)
    dl = [dilations] * 2 if isinstance(dilations, int) else list(dilations)

    def fn(v):
        import jax.numpy as jnp

        n, c, h, w = v.shape
        v = jnp.pad(v, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
        patches = jax.lax.conv_general_dilated_patches(
            v, ks, st, "VALID", rhs_dilation=dl)
        n2, ckk, oh, ow = patches.shape
        return patches.reshape(n2, ckk, oh * ow)

    return apply_jax(fn, x)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    from ..dygraph.eager import apply_jax
    import jax

    h, w = int(x.shape[2]), int(x.shape[3])
    if size is not None:
        oh, ow = int(size[0]), int(size[1])
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * 2
        oh, ow = int(h * sf[0]), int(w * sf[1])
    method = {"nearest": "nearest", "bilinear": "bilinear", "bicubic": "cubic"}[mode]

    def fn(v):
        return jax.image.resize(v, (v.shape[0], v.shape[1], oh, ow), method=method)

    return apply_jax(fn, x)


upsample = interpolate


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    from ..tensor import math as _m

    k = label.shape[-1]
    smoothed = _m.scale(label, 1.0 - epsilon, bias=0.0)
    return _m.add(smoothed, _full_like_scalar(label, epsilon / k))


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    from ..dygraph.eager import apply_jax
    import jax.numpy as jnp

    m = int(maxlen) if maxlen is not None else None
    if m is None:
        raise ValueError("maxlen must be given (static shapes on TPU)")

    def fn(v):
        return (jnp.arange(m)[None, :] < v[:, None]).astype(dtypes.to_np(dtype))

    return apply_jax(fn, lengths)
