"""Common layers (reference python/paddle/nn/layer/common.py)."""
from __future__ import annotations

from ...dygraph.layers import Layer
from .. import functional as F


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        self.bias = (self.create_parameter([out_features], attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self.weight.shape[0]}, out={self.weight.shape[1]}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    """Zeroes whole channels of NCHW maps (reference nn.Dropout2D)."""

    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        if not self.training or self.p == 0.0:
            return x
        from ...dygraph import base
        from ...dygraph.eager import apply_jax
        import jax

        key = base.next_eager_key()
        p = self.p
        ch_axis = 1 if self.data_format == "NCHW" else -1

        def fn(v):
            import jax.numpy as jnp

            shape = [1] * v.ndim
            shape[0] = v.shape[0]
            shape[ch_axis] = v.shape[ch_axis]
            keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)

        return apply_jax(fn, x)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None,
                 is_sparse=None):
        super().__init__()
        from ...initializer import NormalInitializer

        self.padding_idx = padding_idx
        # 2.x spells it `sparse`, the 1.x dygraph layer `is_sparse`;
        # accept both (explicit is_sparse wins)
        self.sparse = bool(sparse if is_sparse is None else is_sparse)
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=NormalInitializer(0.0, 1.0))
        if padding_idx is not None:
            import jax.numpy as jnp

            w = self.weight._value
            self.weight._set_raw(w.at[padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx,
                           sparse=self.sparse)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ...tensor.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        self.mode, self.value, self.data_format = mode, value, data_format

    def forward(self, x):
        return F.pad(x, list(self.padding), self.mode, self.value, self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW"):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners, self.align_mode = mode, align_corners, align_mode

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        from ...tensor import math as m

        a = F.normalize(x1, axis=self.axis, epsilon=self.eps)
        b = F.normalize(x2, axis=self.axis, epsilon=self.eps)
        return m.sum(m.multiply(a, b), axis=self.axis)
