"""Layer containers (reference python/paddle/nn/layer/container.py)."""
from __future__ import annotations

from ...dygraph.layers import Layer
from ...dygraph.tensor import Parameter


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], tuple):
            for name, l in layers[0]:
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                self.add_sublayer(str(i), l)

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, l in enumerate(sublayers or []):
            self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def insert(self, index, sublayer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self.add_sublayer(str(i), l)

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx if idx >= 0 else len(self) + idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def append(self, parameter: Parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx if idx >= 0 else len(self) + idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())
