"""Transformer layers (reference python/paddle/nn/layer/transformer.py:1-1214).

TPU-native: the attention core is plain matmul/softmax jax ops so XLA
fuses them onto the MXU; the fused/flash path (Pallas splash kernel)
plugs in underneath `_core_attention` without changing this API.
"""
from __future__ import annotations

import numpy as np

from ...dygraph.layers import Layer
from ...tensor import linalg, manipulation, math as pmath
from .. import functional as F
from .common import Dropout, Linear
from .container import LayerList
from .norm import LayerNorm


def _convert_attention_mask(attn_mask, dtype="float32"):
    """bool mask (True=keep) or additive float mask -> additive float."""
    if attn_mask is None:
        return None
    if str(attn_mask.dtype).endswith("bool"):
        from ...tensor.math import cast, scale

        return scale(cast(attn_mask, dtype), 1e4, bias=-1e4, bias_after_scale=False)
    return attn_mask


class MultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout_p = dropout
        self.need_weights = need_weights
        kdim, vdim = kdim or embed_dim, vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _shape(self, x):
        # [B, S, E] -> [B, H, S, D]
        b, s = x.shape[0], x.shape[1]
        x = manipulation.reshape(x, [b, s, self.num_heads, self.head_dim])
        return manipulation.transpose(x, [0, 2, 1, 3])

    def _core_attention(self, q, k, v, attn_mask):
        scores = linalg.matmul(q, k, transpose_y=True)
        scores = pmath.scale(scores, 1.0 / np.sqrt(self.head_dim))
        if attn_mask is not None:
            scores = pmath.add(scores, attn_mask)
        weights = F.softmax(scores, axis=-1)
        if self.dropout_p:
            weights = F.dropout(weights, self.dropout_p, training=self.training)
        out = linalg.matmul(weights, v)
        return out, weights

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._shape(self.q_proj(query))
        k = self._shape(self.k_proj(key))
        v = self._shape(self.v_proj(value))
        if cache is not None:
            k = manipulation.concat([cache.k, k], axis=2)
            v = manipulation.concat([cache.v, v], axis=2)
            cache = type(cache)(k, v)
        attn_mask = _convert_attention_mask(attn_mask)
        out, weights = self._core_attention(q, k, v, attn_mask)
        b, s = query.shape[0], query.shape[1]
        out = manipulation.transpose(out, [0, 2, 1, 3])
        out = manipulation.reshape(out, [b, s, self.embed_dim])
        out = self.out_proj(out)
        results = [out]
        if self.need_weights:
            results.append(weights)
        if cache is not None:
            results.append(cache)
        return out if len(results) == 1 else tuple(results)

    class Cache:
        def __init__(self, k, v):
            self.k, self.v = k, v

    def gen_cache(self, key, value=None, type=None):
        b = key.shape[0]
        from ...tensor.creation import zeros

        k = zeros([b, self.num_heads, 0, self.head_dim])
        v = zeros([b, self.num_heads, 0, self.head_dim])
        return MultiHeadAttention.Cache(k, v)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, src, src, src_mask)
        src = pmath.add(residual, self.dropout1(src))
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = pmath.add(residual, self.dropout2(src))
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] +
                                [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        tgt = pmath.add(residual, self.dropout1(tgt))
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = pmath.add(residual, self.dropout2(tgt))
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = pmath.add(residual, self.dropout3(tgt))
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] +
                                [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask, memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model, self.nhead = d_model, nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(d_model, nhead, dim_feedforward,
                                                dropout, activation, attn_dropout,
                                                act_dropout, normalize_before)
            self.encoder = TransformerEncoder(
                enc_layer, num_encoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(d_model, nhead, dim_feedforward,
                                                dropout, activation, attn_dropout,
                                                act_dropout, normalize_before)
            self.decoder = TransformerDecoder(
                dec_layer, num_decoder_layers,
                LayerNorm(d_model) if normalize_before else None)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        from ...tensor.creation import full, tril

        m = tril(full([length, length], 0.0))
        # upper triangle (excl diag) gets -inf-ish
        import jax.numpy as jnp

        from ...dygraph.tensor import Tensor

        mask = jnp.where(jnp.tril(jnp.ones((length, length))) == 1, 0.0, -1e9)
        return Tensor(mask.astype("float32"))
