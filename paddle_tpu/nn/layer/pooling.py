"""Pooling layers (reference python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from ...dygraph.layers import Layer
from .. import functional as F


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self.k, self.s, self.p, self.ceil = kernel_size, stride, padding, ceil_mode

    def forward(self, x):
        return F.max_pool2d(x, self.k, self.s, self.p, self.ceil)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__()
        self.k, self.s, self.p, self.ceil = kernel_size, stride, padding, ceil_mode
        self.exclusive = exclusive

    def forward(self, x):
        return F.avg_pool2d(x, self.k, self.s, self.p, self.ceil, self.exclusive)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)
