"""RNN layer classes: SimpleRNN / LSTM / GRU over the fused `rnn` op.

Role parity: reference python/paddle/nn/layer/rnn.py (RNNBase:1000,
LSTM/GRU/SimpleRNN classes) whose cudnn path emits the `rnn` op with a
flat WeightList.  TPU-native: the op lowers to `lax.scan` per
(layer, direction) with the whole-sequence input projection batched onto
the MXU (ops/rnn_ops.py); the same WeightList layout is kept so programs
round-trip.
"""
from __future__ import annotations

import numpy as np

from ...dispatch import op_call
from ...dygraph.layers import Layer
from ...dygraph.tensor import Tensor


_GATES = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}


class RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if mode not in _GATES:
            raise ValueError(f"unknown rnn mode {mode!r}")
        if direction in ("forward",):
            self._n_dir = 1
        elif direction in ("bidirect", "bidirectional"):
            self._n_dir = 2
        else:
            raise ValueError(f"unknown direction {direction!r}")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = float(dropout)
        g = _GATES[mode]

        # bias_*_attr=False omits BOTH bias vectors (the flat WeightList
        # layout has no hole for a lone missing bias)
        self._use_bias = bias_ih_attr is not False \
            and bias_hh_attr is not False
        ws, bs = [], []
        for layer in range(num_layers):
            for d in range(self._n_dir):
                in_sz = input_size if layer == 0 \
                    else hidden_size * self._n_dir
                k = 1.0 / np.sqrt(hidden_size)
                w_ih = self.create_parameter(
                    [g * hidden_size, in_sz], attr=weight_ih_attr,
                    default_initializer=_uniform(k))
                w_hh = self.create_parameter(
                    [g * hidden_size, hidden_size], attr=weight_hh_attr,
                    default_initializer=_uniform(k))
                ws += [w_ih, w_hh]
                if self._use_bias:
                    b_ih = self.create_parameter(
                        [g * hidden_size], attr=bias_ih_attr, is_bias=True,
                        default_initializer=_uniform(k))
                    b_hh = self.create_parameter(
                        [g * hidden_size], attr=bias_hh_attr, is_bias=True,
                        default_initializer=_uniform(k))
                    bs += [b_ih, b_hh]
        # reference WeightList layout: all [w_ih, w_hh] pairs, then all
        # [b_ih, b_hh] pairs (nn/layer/rnn.py flatten_parameters)
        self._weight_list = ws + bs
        for i, p in enumerate(self._weight_list):
            setattr(self, f"_flat_w_{i}", p)

    # -- helpers ----------------------------------------------------------
    def _zero_state(self, x):
        import jax.numpy as jnp

        dt = x._value.dtype if isinstance(x, Tensor) else jnp.float32
        batch = x.shape[0] if self.time_major is False else x.shape[1]
        shape = (self.num_layers * self._n_dir, batch, self.hidden_size)
        return Tensor(jnp.zeros(shape, dt), stop_gradient=True)

    def _run_op(self, x, states, weights, n_layers, input_size):
        n_state = 2 if self.mode == "LSTM" else 1
        return op_call(
            "rnn",
            {"Input": x, "PreState": states, "WeightList": list(weights)},
            {"mode": self.mode, "hidden_size": self.hidden_size,
             "num_layers": n_layers, "is_bidirec": self._n_dir == 2,
             "input_size": input_size, "dropout_prob": 0.0},
            outs=("Out", "State"),
            out_counts={"State": n_state},
        )

    def _layer_weights(self, layer):
        nd = self._n_dir
        ws = self._weight_list[2 * layer * nd:2 * (layer + 1) * nd]
        if self._use_bias:
            off = 2 * self.num_layers * nd
            ws = ws + self._weight_list[off + 2 * layer * nd:
                                        off + 2 * (layer + 1) * nd]
        return ws

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat, transpose

        if sequence_length is not None:
            raise NotImplementedError(
                "sequence_length is not supported yet: the scan runs all "
                "T steps; mask padded outputs downstream or pack "
                "sequences (silent wrong states would be worse)")
        x = inputs
        if not self.time_major:
            x = transpose(x, [1, 0, 2])  # op wants [T, B, I]
        if initial_states is None:
            if self.mode == "LSTM":
                initial_states = (self._zero_state(inputs),
                                  self._zero_state(inputs))
            else:
                initial_states = self._zero_state(inputs)
        states = (list(initial_states)
                  if isinstance(initial_states, (list, tuple))
                  else [initial_states])

        use_dropout = (self.dropout > 0.0 and self.num_layers > 1
                       and getattr(self, "training", True))
        if not use_dropout:
            out, state = self._run_op(x, states, self._weight_list,
                                      self.num_layers, self.input_size)
        else:
            # reference semantics: dropout BETWEEN layers (not after the
            # last); run one op per layer so the dropout op's saved-mask
            # gradient path applies
            from .. import functional as F

            nd = self._n_dir
            y = x
            finals = [[] for _ in range(len(states))]
            for layer in range(self.num_layers):
                sub_states = [s[layer * nd:(layer + 1) * nd]
                              for s in states]
                in_sz = self.input_size if layer == 0 \
                    else self.hidden_size * nd
                y, st = self._run_op(y, sub_states,
                                     self._layer_weights(layer), 1, in_sz)
                st = st if isinstance(st, (list, tuple)) else [st]
                for i, s in enumerate(st):
                    finals[i].append(s)
                if layer < self.num_layers - 1:
                    y = F.dropout(y, p=self.dropout, training=True)
            out = y
            state = [concat(f, axis=0) for f in finals]
        if not self.time_major:
            out = transpose(out, [1, 0, 2])
        if self.mode == "LSTM":
            return out, tuple(state)
        return out, (state[0] if isinstance(state, (list, tuple)) else state)


def _uniform(k):
    from ...initializer import UniformInitializer

    return UniformInitializer(-k, k)


class SimpleRNN(RNNBase):
    """Reference paddle.nn.SimpleRNN."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        if activation not in ("tanh", "relu"):
            raise ValueError(
                f"SimpleRNN activation must be 'tanh' or 'relu', got "
                f"{activation!r}")
        mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class LSTM(RNNBase):
    """Reference paddle.nn.LSTM."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(RNNBase):
    """Reference paddle.nn.GRU."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)
