"""Convolution layers (reference python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

from ...dygraph.layers import Layer
from .. import functional as F


class Conv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        ks = [kernel_size] * 2 if isinstance(kernel_size, int) else list(kernel_size)
        self._stride, self._padding, self._dilation = stride, padding, dilation
        self._groups, self._data_format = groups, data_format
        from ...initializer import MSRAInitializer

        self.weight = self.create_parameter(
            [out_channels, in_channels // groups] + ks, attr=weight_attr,
            default_initializer=MSRAInitializer(uniform=True))
        self.bias = (self.create_parameter([out_channels], attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        ks = [kernel_size] * 2 if isinstance(kernel_size, int) else list(kernel_size)
        self._stride, self._padding, self._dilation = stride, padding, dilation
        self._groups, self._output_padding = groups, output_padding
        self._data_format = data_format
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups] + ks, attr=weight_attr)
        self.bias = (self.create_parameter([out_channels], attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._dilation, self._groups, output_size,
                                  self._data_format)
