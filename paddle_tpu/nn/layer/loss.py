"""Loss layers (reference python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from ...dygraph.layers import Layer
from .. import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, name=None):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction
        self.soft_label, self.axis, self.use_softmax = soft_label, axis, use_softmax

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = weight, reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight,
                                                  self.reduction, self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)
