"""Activation layers (reference python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from ...dygraph.layers import Layer
from .. import functional as F


def _mk(name, fn, **fixed):
    class _Act(Layer):
        def __init__(self, *a, **kw):
            super().__init__()
            self._kw = {**fixed}
            # positional args map onto the functional's keyword order
            self._args = a
            self._kw.update(kw)
            self._kw.pop("name", None)

        def forward(self, x):
            return fn(x, *self._args, **self._kw)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _mk("ReLU", F.relu)
ReLU6 = _mk("ReLU6", F.relu6)
GELU = _mk("GELU", F.gelu)
Sigmoid = _mk("Sigmoid", F.sigmoid)
Tanh = _mk("Tanh", F.tanh)
LeakyReLU = _mk("LeakyReLU", F.leaky_relu)
ELU = _mk("ELU", F.elu)
SELU = _mk("SELU", F.selu)
CELU = _mk("CELU", F.celu)
Hardswish = _mk("Hardswish", F.hardswish)
Hardsigmoid = _mk("Hardsigmoid", F.hardsigmoid)
Hardtanh = _mk("Hardtanh", F.hardtanh)
Hardshrink = _mk("Hardshrink", F.hardshrink)
Softshrink = _mk("Softshrink", F.softshrink)
Softplus = _mk("Softplus", F.softplus)
Softsign = _mk("Softsign", F.softsign)
Swish = _mk("Swish", F.swish)
Silu = _mk("Silu", F.silu)
Mish = _mk("Mish", F.mish)
Tanhshrink = _mk("Tanhshrink", F.tanhshrink)
ThresholdedReLU = _mk("ThresholdedReLU", F.thresholded_relu)
LogSigmoid = _mk("LogSigmoid", F.log_sigmoid)
LogSoftmax = _mk("LogSoftmax", F.log_softmax)
Softmax = _mk("Softmax", F.softmax)
Maxout = _mk("Maxout", F.maxout)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, name=None):
        super().__init__()
        from ...initializer import ConstantInitializer

        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=ConstantInitializer(init))

    def forward(self, x):
        return F.prelu(x, self.weight)
