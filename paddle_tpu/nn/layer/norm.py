"""Normalization layers (reference python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...dygraph.layers import Layer
from ...dygraph.tensor import Tensor
from .. import functional as F


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        from ...initializer import ConstantInitializer

        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        training = self.training if self._use_global_stats is None else (
            not self._use_global_stats)
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format)


class BatchNorm(_BatchNormBase):
    """Compat alias for fluid-era BatchNorm."""


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN: moments psum over the mesh data axis
    (reference sync_batch_norm op; lowering does the collective when
    traced under a mesh, plain BN otherwise)."""

    # single-device forward is plain BN; under a mesh the sync_batch_norm
    # lowering psums the moments (distributed milestone wires the mesh axis)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _BatchNormBase) and not isinstance(sub, SyncBatchNorm):
                sb = SyncBatchNorm(sub.weight.shape[0], sub._momentum, sub._epsilon)
                sb.weight, sb.bias = sub.weight, sub.bias
                sb._mean, sb._variance = sub._mean, sub._variance
                layer._sub_layers[name] = sb
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        from ...initializer import ConstantInitializer

        ns = ([normalized_shape] if isinstance(normalized_shape, int)
              else list(normalized_shape))
        self._normalized_shape = ns
        self._epsilon = epsilon
        self.weight = (self.create_parameter(
            ns, attr=weight_attr, default_initializer=ConstantInitializer(1.0))
            if weight_attr is not False else None)
        self.bias = (self.create_parameter(ns, attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        from ...initializer import ConstantInitializer

        self._num_groups, self._epsilon = num_groups, epsilon
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        from ...initializer import ConstantInitializer

        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)
