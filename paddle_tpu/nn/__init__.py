"""`paddle.nn` equivalent (reference python/paddle/nn/__init__.py)."""
from ..dygraph.layers import Layer  # noqa: F401
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
)
from .layer.activation import (  # noqa: F401
    CELU, ELU, GELU, SELU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    LeakyReLU, LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6,
    Sigmoid, Silu, Softmax, Softplus, Softshrink, Softsign, Swish, Tanh,
    Tanhshrink, ThresholdedReLU,
)
from .layer.common import (  # noqa: F401
    CosineSimilarity, Dropout, Dropout2D, Embedding, Flatten, Linear, Pad2D,
    Upsample,
)
from .layer.container import LayerList, ParameterList, Sequential  # noqa: F401
from .layer.conv import Conv2D, Conv2DTranspose  # noqa: F401
from .layer.rnn import GRU, LSTM, RNNBase, SimpleRNN  # noqa: F401
from .layer.loss import (  # noqa: F401
    BCEWithLogitsLoss, CrossEntropyLoss, KLDivLoss, L1Loss, MSELoss, NLLLoss,
    SmoothL1Loss,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm, InstanceNorm2D,
    LayerNorm, SyncBatchNorm,
)
from .layer.pooling import (  # noqa: F401
    AdaptiveAvgPool2D, AdaptiveMaxPool2D, AvgPool2D, MaxPool2D,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder, TransformerDecoderLayer,
    TransformerEncoder, TransformerEncoderLayer,
)

from ..dygraph.tensor import Parameter  # noqa: F401
