"""Gradient clipping (reference python/paddle/fluid/clip.py; 2.0 re-exports
as paddle.nn.ClipGradBy*)."""
from __future__ import annotations

import jax.numpy as jnp


class ClipGradBase:
    def _dygraph_clip(self, params_grads):
        raise NotImplementedError

    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, jnp.clip(g, self.min, self.max)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(g * g))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, g * scale))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _dygraph_clip(self, params_grads):
        sq = [jnp.sum(g * g) for p, g in params_grads if g is not None]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [(p, None if g is None else g * scale) for p, g in params_grads]


# fluid-compat aliases
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
