"""`paddle.nn.utils` (reference python/paddle/nn/utils/):
weight_norm / remove_weight_norm / spectral_norm reparameterizations
over dygraph Layers, plus parameter<->vector helpers.

TPU-native note: the reparameterized weight is recomputed in the
forward pre-hook from its factors, so under `to_static`/jit the
recompute traces into the program and XLA fuses it — same effect as
the reference's dedicated norm ops with no extra kernels.
"""
from __future__ import annotations

import numpy as np

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except(t, dim):
    """||t|| over every axis except ``dim`` (keepdims); ``dim=None``
    means the whole-tensor norm (scalar, reference norm_except_dim with
    dim=-1), eager tensors."""
    import jax.numpy as jnp

    from ...dygraph.eager import apply_jax

    axes = tuple(i for i in range(len(t.shape)) if i != dim)
    keep = dim is not None
    return apply_jax(
        lambda v: jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=keep)
                           + 1e-12), t)


def _wn_dim(t, dim):
    """Reference norm_except_dim convention: dim in (None, -1) selects
    the whole-tensor norm (g is scalar); other negative dims count from
    the back (dim % ndim)."""
    if dim is None or dim == -1:
        return None
    return dim % len(t.shape) if dim < 0 else dim


def weight_norm(layer, name="weight", dim=0):
    """Reference nn/utils/weight_norm_hook.py: w = g * v / ||v||, with
    g (per-``dim`` magnitude) and v (direction) as the trainable
    parameters; recomputed on every forward.  ``dim in (None, -1)``
    normalizes the whole tensor (scalar g)."""
    from ...dygraph.layers import Parameter

    w = layer._parameters.get(name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    dim = _wn_dim(w, dim)
    g0 = _norm_except(w, dim)
    v = Parameter(w._value, name=w.name + "_v", trainable=True)
    g = Parameter(g0._value, name=w.name + "_g", trainable=True)
    del layer._parameters[name]
    layer._parameters[name + "_v"] = v
    layer._parameters[name + "_g"] = g

    def compute(lyr):
        vv = lyr._parameters[name + "_v"]
        gg = lyr._parameters[name + "_g"]
        w_new = gg * (vv / _norm_except(vv, dim))
        object.__setattr__(lyr, name, w_new)

    def pre_hook(lyr, inputs):
        compute(lyr)
        return None

    handle = layer.register_forward_pre_hook(pre_hook)
    layer.__dict__.setdefault("_weight_norm_state", {})[name] = (
        handle, dim, compute)
    compute(layer)  # usable before the first forward too
    return layer


def remove_weight_norm(layer, name="weight"):
    """Bake the current w back into a plain parameter and drop the
    reparameterization."""
    from ...dygraph.layers import Parameter

    state = layer.__dict__.get("_weight_norm_state", {}).pop(name, None)
    if state is None:
        raise ValueError(f"{name!r} is not weight-normed on this layer")
    handle, dim, compute = state
    compute(layer)  # final value from the factors
    w_val = getattr(layer, name)._value
    handle.remove() if hasattr(handle, "remove") else None
    del layer._parameters[name + "_v"]
    del layer._parameters[name + "_g"]
    if name in layer.__dict__:
        del layer.__dict__[name]
    layer._parameters[name] = Parameter(w_val, name=name, trainable=True)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=0):
    """Reference nn/utils/spectral_norm_hook.py: w / sigma_max(w), with
    sigma estimated by power iteration on a persistent u buffer.

    The power-iteration vectors are DETACHED (stop_gradient) before
    sigma = u^T W v, so gradients flow only through W — the reference
    treats u/v as constants per step.  u is registered as a persistent
    layer buffer (``{name}_u``), so it rides state_dict and survives
    save/load instead of restarting the iteration from scratch.
    """
    import jax.numpy as jnp
    from jax import lax

    from ...dygraph.eager import apply_jax
    from ...dygraph.tensor import Tensor

    w = layer._parameters.get(name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    h = int(w.shape[dim])
    rs = np.random.RandomState(0)
    layer.register_buffer(name + "_u",
                          Tensor(rs.randn(h).astype("float32")),
                          persistable=True)

    def pre_hook(lyr, inputs):
        ww = lyr._parameters[name + "_orig"]

        def sn(wv, uv):
            mat = jnp.moveaxis(wv, dim, 0).reshape(h, -1)
            u = uv
            # the iteration runs on the detached weight: u/v are plain
            # estimates, not part of the differentiated graph
            mat_c = lax.stop_gradient(mat)
            for _ in range(n_power_iterations):
                v = mat_c.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat_c @ v
                u = u / (jnp.linalg.norm(u) + eps)
            if n_power_iterations == 0:
                # no update: sigma from the stored u and its derived v
                v = mat_c.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
            u = lax.stop_gradient(u)
            v = lax.stop_gradient(v)
            sigma = u @ (mat @ v)
            return wv / sigma, u

        w_new, u_new = apply_jax(sn, ww, lyr._buffers[name + "_u"],
                                 n_out=2)
        lyr._buffers[name + "_u"] = Tensor(
            lax.stop_gradient(u_new._value))
        object.__setattr__(lyr, name, w_new)
        return None

    orig = w
    del layer._parameters[name]
    layer._parameters[name + "_orig"] = orig
    layer.register_forward_pre_hook(pre_hook)
    return layer


def parameters_to_vector(parameters, name=None):
    """Flatten + concat parameters (reference nn/utils/transform_parameters.py)."""
    import jax.numpy as jnp

    from ...dygraph.tensor import Tensor

    vals = [jnp.ravel(p._value) for p in parameters]
    return Tensor(jnp.concatenate(vals))


def vector_to_parameters(vec, parameters):
    ofs = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p.set_value(vec._value[ofs:ofs + n].reshape(tuple(p.shape)))
        ofs += n
