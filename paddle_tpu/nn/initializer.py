"""`paddle.nn.initializer` namespace (reference python/paddle/nn/
initializer/): the 2.0 spellings over the fluid initializer classes."""
from ..initializer import (  # noqa: F401
    ConstantInitializer as Constant,
    MSRAInitializer,
    NormalInitializer as Normal,
    NumpyArrayInitializer as Assign,
    TruncatedNormalInitializer as TruncatedNormal,
    UniformInitializer as Uniform,
    XavierInitializer,
)


class XavierNormal(XavierInitializer):
    def __init__(self, fan_in=None, fan_out=None, name=None):
        super().__init__(uniform=False, fan_in=fan_in, fan_out=fan_out)


class XavierUniform(XavierInitializer):
    def __init__(self, fan_in=None, fan_out=None, name=None):
        super().__init__(uniform=True, fan_in=fan_in, fan_out=fan_out)


class KaimingNormal(MSRAInitializer):
    def __init__(self, fan_in=None, name=None):
        super().__init__(uniform=False, fan_in=fan_in)


class KaimingUniform(MSRAInitializer):
    def __init__(self, fan_in=None, name=None):
        super().__init__(uniform=True, fan_in=fan_in)
