"""Small shared pieces for nn.functional (dual-mode safe)."""
from __future__ import annotations

from ..tensor.manipulation import take_along_axis, unsqueeze, squeeze


def gather_label_scores(scores, label):
    """Pick scores[i, label[i]] for each row; label is [N] or [N, 1]."""
    lbl = label
    if len(lbl.shape) == len(scores.shape) - 1:
        lbl = unsqueeze(lbl, -1)
    picked = take_along_axis(scores, lbl, axis=-1)
    return squeeze(picked, -1)
