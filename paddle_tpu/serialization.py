"""Top-level paddle.save / paddle.load.

Role parity: reference python/paddle/framework/io.py save:177/load:361 —
pickle-based container for state_dicts / tensors / nested structures,
plus Program protos.  Layer/optimizer ``state_dict()`` round-trips are
the primary contract (train -> save -> new process -> load -> resume).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

_MAGIC = b"PTPUPKL1"


def _to_host(obj):
    """Device arrays / eager tensors -> numpy, recursively."""
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        return type(obj)(*(_to_host(v) for v in obj))
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_host(v) for v in obj)
    if hasattr(obj, "numpy") and callable(obj.numpy):  # eager Tensor
        return np.asarray(obj.numpy())
    if hasattr(obj, "sharding") and hasattr(obj, "dtype"):  # jax array
        return np.asarray(obj)
    return obj


def save(obj, path: str, protocol: int = 4):
    from .framework.program import Program

    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    if isinstance(obj, Program):
        # program protos are self-describing; reference save(Program) writes
        # the desc too
        with open(path, "wb") as f:
            f.write(b"PTPUPROG")
            f.write(obj.serialize_to_string())
        return
    with open(path, "wb") as f:
        f.write(_MAGIC)
        pickle.dump(_to_host(obj), f, protocol=protocol)


def load(path: str):
    from .framework.program import Program

    if not os.path.exists(path):
        raise FileNotFoundError(f"paddle.load: no such file {path!r}")
    with open(path, "rb") as f:
        magic = f.read(8)
        if magic == b"PTPUPROG":
            return Program.parse_from_string(f.read())
        if magic != _MAGIC:
            raise ValueError(
                f"{path!r} was not written by paddle_tpu.save "
                f"(bad magic {magic!r})")
        return pickle.load(f)
