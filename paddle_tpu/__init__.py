"""paddle_tpu: a TPU-native deep learning framework.

Capability parity with the fluid-era PaddlePaddle reference (see SURVEY.md),
built on JAX/XLA/Pallas: programs are a protobuf graph IR whose blocks
compile to single XLA computations; collectives lower to XLA collectives
over a device mesh.
"""
from . import framework  # noqa: F401
from . import ops  # noqa: F401
from . import initializer, layers, optimizer, regularizer  # noqa: F401
from . import dygraph  # noqa: F401
from .dygraph import grad, no_grad, to_variable  # noqa: F401
from .dygraph.base import (  # noqa: F401
    disable_static,
    enable_static,
    in_dygraph_mode,
    seed,
)
from .dygraph.tensor import Tensor  # noqa: F401

# 2.0 flat namespace (reference python/paddle/__init__.py ~210 imports)
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import vision  # noqa: F401
from . import hapi  # noqa: F401
from . import serving  # noqa: F401
from .hapi import Model  # noqa: F401
from .hapi.model import InputSpec  # noqa: F401
from .hapi import callbacks  # noqa: F401
from .tensor import (  # noqa: F401
    abs, add, add_n, all, allclose, any, arange, argmax, argmin, argsort,
    assign, bmm, broadcast_to, cast, ceil, chunk, clip, concat, cos, cumsum,
    diag, divide, dot, equal, equal_all, exp, expand, expand_as, eye, flatten,
    flip, floor, floor_divide, full, full_like, gather, gather_nd,
    greater_equal, greater_than, increment, index_select, isfinite, isinf,
    isnan, less_equal, less_than, linspace, log, log1p, log2, log10,
    logical_and, logical_not, logical_or, logical_xor, logsumexp, masked_select,
    matmul, max, maximum, mean, meshgrid, min, minimum, mm, mod, multinomial,
    multiply, nonzero, norm, normal, not_equal, numel, ones, ones_like, pow,
    prod, rand, randint, randn, randperm, reciprocal, remainder, reshape,
    roll, round, rsqrt, scale, scatter, scatter_nd_add, sign, sin, slice,
    sort, split, sqrt, square, squeeze, stack, std, subtract, sum, t,
    tanh, tile, to_tensor, topk, trace, transpose, tril, triu, uniform,
    unsqueeze, unstack, var, where, zeros, zeros_like,
)
from .tensor.math import kron, neg, stanh  # noqa: F401
from .tensor.search import index_sample  # noqa: F401
from . import fluid  # noqa: F401
from .framework.backward import append_backward, calc_gradient  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from .framework import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Executor,
    Program,
    StepHandle,
    TPUPlace,
    default_main_program,
    default_startup_program,
    get_device,
    global_scope,
    program_guard,
    set_device,
)

from . import autograd  # noqa: F401
from . import distribution  # noqa: F401
from . import utils  # noqa: F401
from . import version  # noqa: F401
from . import inference  # noqa: F401
from . import jit  # noqa: F401
from . import monitor  # noqa: F401
from . import observe  # noqa: F401
from . import ckpt  # noqa: F401
from .hapi.model_stat import flops, summary  # noqa: F401
from . import profiler  # noqa: F401
from . import static  # noqa: F401
from . import text  # noqa: F401
from .serialization import load, save  # noqa: F401
from .framework.flags import get_flags, set_flags  # noqa: F401

__version__ = version.full_version  # single source: version.py
