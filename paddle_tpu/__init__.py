"""paddle_tpu: a TPU-native deep learning framework.

Capability parity with the fluid-era PaddlePaddle reference (see SURVEY.md),
built on JAX/XLA/Pallas: programs are a protobuf graph IR whose blocks
compile to single XLA computations; collectives lower to XLA collectives
over a device mesh.
"""
from . import framework  # noqa: F401
from . import ops  # noqa: F401
from . import initializer, layers, optimizer, regularizer  # noqa: F401
from . import dygraph  # noqa: F401
from .dygraph import grad, no_grad, to_variable  # noqa: F401
from .dygraph.base import in_dygraph_mode, seed  # noqa: F401
from .dygraph.tensor import Tensor  # noqa: F401
from . import fluid  # noqa: F401
from .framework.backward import append_backward, calc_gradient  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from .framework import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Executor,
    Program,
    TPUPlace,
    default_main_program,
    default_startup_program,
    global_scope,
    program_guard,
)

__version__ = "0.1.0"
