"""``paddle.distribution``: Uniform / Normal / Categorical.

Reference parity: python/paddle/distribution.py (:41 Distribution, :168
Uniform, :393 Normal, :646 Categorical).  TPU-native: sampling uses the
dygraph RNG key stream (threefry) and all math is jnp; tensors in/out are
dygraph Tensors so the API composes with the eager autograd tape.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .dygraph import base as _base
from .dygraph.tensor import Tensor

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _as_value(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(np.asarray(x, dtype="float32"))


class Distribution:
    """Abstract base (reference distribution.py:41)."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _as_value(low)
        self.high = _as_value(high)

    def sample(self, shape=(), seed=0):
        key = jax.random.PRNGKey(seed) if seed else _base.next_eager_key()
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(key, shape)
        return Tensor(self.low + u * (self.high - self.low),
                      stop_gradient=True)

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low), stop_gradient=True)

    def log_prob(self, value):
        v = _as_value(value)
        inside = (v >= self.low) & (v < self.high)
        lp = jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)
        return Tensor(lp, stop_gradient=True)

    def probs(self, value):
        return Tensor(jnp.exp(_as_value(self.log_prob(value))),
                      stop_gradient=True)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_value(loc)
        self.scale = _as_value(scale)

    def sample(self, shape=(), seed=0):
        key = jax.random.PRNGKey(seed) if seed else _base.next_eager_key()
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        z = jax.random.normal(key, shape)
        return Tensor(self.loc + z * self.scale, stop_gradient=True)

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale), stop_gradient=True)

    def log_prob(self, value):
        v = _as_value(value)
        var = self.scale * self.scale
        lp = (-jnp.square(v - self.loc) / (2 * var)
              - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))
        return Tensor(lp, stop_gradient=True)

    def probs(self, value):
        return Tensor(jnp.exp(_as_value(self.log_prob(value))),
                      stop_gradient=True)

    def kl_divergence(self, other):
        if not isinstance(other, Normal):
            raise NotImplementedError("KL(Normal || non-Normal)")
        var_ratio = jnp.square(self.scale / other.scale)
        t1 = jnp.square((self.loc - other.loc) / other.scale)
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)),
                      stop_gradient=True)


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _as_value(logits)

    def _logp(self):
        return jax.nn.log_softmax(self.logits, axis=-1)

    def sample(self, shape=(), seed=0):
        key = jax.random.PRNGKey(seed) if seed else _base.next_eager_key()
        out = jax.random.categorical(key, self.logits, shape=tuple(shape)
                                     + self.logits.shape[:-1])
        return Tensor(out.astype(jnp.int64), stop_gradient=True)

    def entropy(self):
        logp = self._logp()
        return Tensor(-jnp.sum(jnp.exp(logp) * logp, axis=-1),
                      stop_gradient=True)

    def log_prob(self, value):
        idx = _as_value(value).astype(jnp.int32)
        logp = self._logp()
        if logp.ndim == 1:
            return Tensor(logp[idx], stop_gradient=True)
        return Tensor(jnp.take_along_axis(logp, idx[..., None], axis=-1)
                      [..., 0], stop_gradient=True)

    def probs(self, value):
        return Tensor(jnp.exp(_as_value(self.log_prob(value))),
                      stop_gradient=True)

    def kl_divergence(self, other):
        if not isinstance(other, Categorical):
            raise NotImplementedError
        p = jnp.exp(self._logp())
        return Tensor(jnp.sum(p * (self._logp() - other._logp()), axis=-1),
                      stop_gradient=True)
