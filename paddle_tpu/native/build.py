"""On-demand build of the native extension.

One g++ invocation, cached by source mtime; no pybind11/cmake (the
extension uses the plain CPython C API).  Returns None when no compiler
is present — callers fall back to pure python with identical semantics.
"""
from __future__ import annotations

import importlib.util
import os
import shutil
import subprocess
import sys
import sysconfig

_SRC = os.path.join(os.path.dirname(__file__), "src", "data_feed.cc")
_OUT_DIR = os.path.join(os.path.dirname(__file__), "_build")


def _so_path() -> str:
    tag = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_OUT_DIR, "_data_feed" + tag)


def _needs_build(so: str) -> bool:
    return (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(_SRC))


def build() -> str:
    so = _so_path()
    if not _needs_build(so):
        return so
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        raise RuntimeError("no C++ compiler found")
    os.makedirs(_OUT_DIR, exist_ok=True)
    include = sysconfig.get_paths()["include"]
    tmp = f"{so}.tmp{os.getpid()}.so"  # per-process: publish stays atomic
    cmd = [cxx, "-O2", "-std=c++17", "-shared", "-fPIC",
           f"-I{include}", _SRC, "-o", tmp]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed: {' '.join(cmd)}\n{proc.stderr}")
    os.replace(tmp, so)  # atomic publish for concurrent builders
    return so


def load_extension():
    """Build (if needed) and import the extension; None on any failure
    (callers use the python fallback)."""
    try:
        so = build()
    except (RuntimeError, OSError):
        # any build-environment failure (missing compiler, unwritable
        # dir, bad CXX) means fallback, never a caller crash
        return None
    spec = importlib.util.spec_from_file_location("_data_feed", so)
    mod = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(mod)
    except ImportError:
        return None
    return mod
