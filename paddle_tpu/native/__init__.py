"""Native (C++) runtime components.

Role parity: the reference's data-feed hot loop is C++
(framework/data_feed.cc); so is ours.  The extension is compiled on
first use with the system toolchain (build.py) and cached next to the
sources; when no compiler is available the pure-python fallback keeps
behavior identical (slower, same bytes out).
"""
from __future__ import annotations

import numpy as np

# lazy: importing this package must not pay a compiler subprocess;
# the extension is built/loaded on the first parse call
_ext = None
_ext_tried = False


def _get_ext():
    global _ext, _ext_tried
    if not _ext_tried:
        from . import build

        _ext = build.load_extension()
        _ext_tried = True
    return _ext


def parse_multislot(data: bytes, slot_types: str):
    """Parse MultiSlot text data into per-slot (values, lod) arrays.

    ``slot_types``: one char per slot — 'f' float32 values, 'u' uint64
    ids.  Returns (n_instances, [(values_ndarray, lod_ndarray), ...]);
    lod holds cumulative offsets (len n_instances+1), reference LoD
    level-0 semantics.
    """
    if isinstance(data, str):
        data = data.encode()
    ext = _get_ext()
    if ext is not None:
        n, packed = ext.parse_multislot(data, slot_types)
        out = []
        for t, (vals, lod) in zip(slot_types, packed):
            dt = np.float32 if t == "f" else np.uint64
            # copy(): frombuffer over bytes is read-only; consumers must
            # see WRITABLE arrays in both native and fallback paths
            out.append((np.frombuffer(vals, dtype=dt).copy(),
                        np.frombuffer(lod, dtype=np.int64).copy()))
        return n, out
    return _parse_multislot_py(data, slot_types)


def _parse_multislot_py(data: bytes, slot_types: str):
    """Pure-python fallback — same outputs AND same errors as the
    extension (malformed input must not silently flip behavior between
    environments with and without a compiler)."""
    vals = [[] for _ in slot_types]
    lods = [[0] for _ in slot_types]
    n = 0
    for line in data.split(b"\n"):
        toks = line.split()
        if not toks:
            continue
        i = 0
        for s, t in enumerate(slot_types):
            try:
                # match strtoll + boundary-check semantics: plain digits
                # only (no python underscore literals)
                if b"_" in toks[i]:
                    raise ValueError
                cnt = int(toks[i])
            except (IndexError, ValueError):
                raise ValueError(f"bad slot count at line {n}")
            if cnt < 0:
                raise ValueError(f"bad slot count at line {n}")
            i += 1
            if i + cnt > len(toks):
                raise ValueError(
                    f"bad {'float' if t == 'f' else 'id'} value at line {n}")
            try:
                for x in toks[i:i + cnt]:
                    if b"_" in x:  # python literals allow _, strtox doesn't
                        raise ValueError
                    if t == "f":
                        vals[s].append(float(x))
                    else:
                        # match strtoull semantics: plain digits only
                        # (no python underscore literals), negatives wrap
                        # into uint64 like the C path; out-of-range
                        # magnitudes are rejected in BOTH paths (the C
                        # side checks ERANGE)
                        if not x.lstrip(b"-+").isdigit():
                            raise ValueError
                        iv = int(x)
                        if not (-(2 ** 64) < iv < 2 ** 64):
                            raise ValueError
                        vals[s].append(iv & 0xFFFFFFFFFFFFFFFF)
            except ValueError:
                raise ValueError(
                    f"bad {'float' if t == 'f' else 'id'} value at line {n}")
            i += cnt
            lods[s].append(len(vals[s]))
        if i != len(toks):
            raise ValueError(f"trailing tokens at line {n}")
        n += 1
    out = []
    for s, t in enumerate(slot_types):
        dt = np.float32 if t == "f" else np.uint64
        out.append((np.asarray(vals[s], dtype=dt),
                    np.asarray(lods[s], dtype=np.int64)))
    return n, out


def has_native() -> bool:
    return _get_ext() is not None
