// Native data-feed core: MultiSlot text parsing.
//
// Role parity: reference paddle/fluid/framework/data_feed.cc
// (MultiSlotDataFeed::ParseOneInstance) — the PS-style training data
// format: each line holds, per slot, a count followed by that many
// values (float slots or uint64 id slots).  Parsing is the host-side
// hot loop of the input pipeline, so like the reference it is C++;
// the Python wrapper (paddle_tpu/native/__init__.py) turns the packed
// buffers into numpy arrays and the io.DataFeed class batches them.
//
// Built on demand with g++ (paddle_tpu/native/build.py); no pybind11 —
// plain CPython C API, zero external deps.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct SlotBuf {
  char type;                     // 'f' float32, 'u' uint64
  std::vector<float> fvals;
  std::vector<uint64_t> uvals;
  std::vector<int64_t> lod;      // cumulative offsets, starts at 0
};

// The python fallback tokenizes on whitespace, so a numeric token must
// be consumed in full; strtox stopping mid-token ("3.5" as count) is a
// parse error, not a value.
inline bool is_tok_ws(char c) {
  // every separator python bytes.split() honors (minus '\n', the line
  // delimiter handled above this level)
  return c == ' ' || c == '\t' || c == '\r' || c == '\v' || c == '\f';
}

inline bool at_token_boundary(const char* c) {
  return *c == '\0' || is_tok_ws(*c);
}

// Parse one buffer of '\n'-separated lines into per-slot value/lod
// buffers.  Returns false + sets err on malformed input.
//
// Each line is copied into a reusable NUL-terminated scratch string so
// strtox can neither run past the logical buffer end (Py_buffer slices
// are not NUL-terminated) nor steal tokens across line boundaries —
// a short line is an error, never silent data corruption.
bool parse_buffer(const char* data, Py_ssize_t len,
                  std::vector<SlotBuf>& slots, std::string& err,
                  int64_t* n_lines_out) {
  const char* p = data;
  const char* end = data + len;
  int64_t n_lines = 0;
  std::string line;
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (line_end == nullptr) line_end = end;
    // skip blank lines, including CRLF/whitespace-only ones (parity with
    // the python fallback's token-split semantics)
    const char* first = p;
    while (first < line_end && is_tok_ws(*first)) ++first;
    if (first < line_end) {
      // an embedded NUL would silently truncate the NUL-terminated
      // scratch copy; the python fallback errors on such tokens — reject
      if (memchr(p, '\0', static_cast<size_t>(line_end - p)) != nullptr) {
        err = "bad value (embedded NUL) at line " + std::to_string(n_lines);
        return false;
      }
      line.assign(p, static_cast<size_t>(line_end - p));
      const char* q = line.c_str();
      for (auto& slot : slots) {
        // parse count.  strtoll alone would accept partial tokens
        // ("3.5" -> 3) the python fallback rejects, so every numeric
        // token must end at whitespace/NUL (token-boundary parity).
        char* next = nullptr;
        long long cnt = strtoll(q, &next, 10);
        if (next == q || cnt < 0 || !at_token_boundary(next)) {
          err = "bad slot count at line " + std::to_string(n_lines);
          return false;
        }
        q = next;
        for (long long i = 0; i < cnt; ++i) {
          if (slot.type == 'f') {
            // python float() rejects C99 hex-float literals strtof
            // accepts; keep the two paths agreeing on what is malformed
            const char* t = q;
            while (is_tok_ws(*t)) ++t;
            if (*t == '+' || *t == '-') ++t;
            if (t[0] == '0' && (t[1] == 'x' || t[1] == 'X')) {
              err = "bad float value at line " + std::to_string(n_lines);
              return false;
            }
            float v = strtof(q, &next);
            if (next == q || !at_token_boundary(next) ||
                memchr(q, '(', static_cast<size_t>(next - q)) != nullptr) {
              // '(' only appears in C99 NAN(n-char-seq), which python
              // float() rejects
              err = "bad float value at line " + std::to_string(n_lines);
              return false;
            }
            slot.fvals.push_back(v);
          } else {
            // out-of-range ids saturate in strtoull but wrap in python's
            // int & mask — reject in both paths instead (errno check
            // here, magnitude check in the fallback)
            errno = 0;
            unsigned long long v = strtoull(q, &next, 10);
            if (next == q || !at_token_boundary(next) || errno == ERANGE) {
              err = "bad id value at line " + std::to_string(n_lines);
              return false;
            }
            slot.uvals.push_back(static_cast<uint64_t>(v));
          }
          q = next;
        }
        slot.lod.push_back(slot.type == 'f'
                               ? static_cast<int64_t>(slot.fvals.size())
                               : static_cast<int64_t>(slot.uvals.size()));
      }
      // trailing tokens mean the line held more data than the slot
      // spec describes — reject, don't silently drop
      while (is_tok_ws(*q)) ++q;
      if (*q != '\0') {
        err = "trailing tokens at line " + std::to_string(n_lines);
        return false;
      }
      ++n_lines;
    }
    p = line_end + 1;
  }
  *n_lines_out = n_lines;
  return true;
}

PyObject* slots_to_py(const std::vector<SlotBuf>& slots, int64_t n_lines) {
  PyObject* out = PyList_New(static_cast<Py_ssize_t>(slots.size()));
  if (!out) return nullptr;
  for (size_t i = 0; i < slots.size(); ++i) {
    const SlotBuf& s = slots[i];
    PyObject* vals;
    if (s.type == 'f') {
      vals = PyBytes_FromStringAndSize(
          reinterpret_cast<const char*>(s.fvals.data()),
          static_cast<Py_ssize_t>(s.fvals.size() * sizeof(float)));
    } else {
      vals = PyBytes_FromStringAndSize(
          reinterpret_cast<const char*>(s.uvals.data()),
          static_cast<Py_ssize_t>(s.uvals.size() * sizeof(uint64_t)));
    }
    PyObject* lod = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(s.lod.data()),
        static_cast<Py_ssize_t>(s.lod.size() * sizeof(int64_t)));
    if (!vals || !lod) {
      Py_XDECREF(vals);
      Py_XDECREF(lod);
      Py_DECREF(out);
      return nullptr;
    }
    PyObject* pair = PyTuple_Pack(2, vals, lod);
    Py_DECREF(vals);
    Py_DECREF(lod);
    if (!pair) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, static_cast<Py_ssize_t>(i), pair);
  }
  PyObject* n_obj = PyLong_FromLongLong(n_lines);
  if (!n_obj) {
    Py_DECREF(out);
    return nullptr;
  }
  PyObject* result = PyTuple_Pack(2, n_obj, out);
  Py_DECREF(n_obj);  // PyTuple_Pack does NOT steal references
  Py_DECREF(out);
  return result;
}

// parse_multislot(data: bytes, types: str) ->
//   (n_instances, [(values_bytes, lod_bytes), ...])
PyObject* parse_multislot(PyObject*, PyObject* args) {
  Py_buffer buf;
  const char* types;
  if (!PyArg_ParseTuple(args, "y*s", &buf, &types)) return nullptr;

  std::vector<SlotBuf> slots;
  for (const char* t = types; *t; ++t) {
    if (*t != 'f' && *t != 'u') {
      PyBuffer_Release(&buf);
      PyErr_Format(PyExc_ValueError,
                   "slot type must be 'f' or 'u', got '%c'", *t);
      return nullptr;
    }
    SlotBuf s;
    s.type = *t;
    s.lod.push_back(0);
    slots.push_back(std::move(s));
  }

  std::string err;
  int64_t n_lines = 0;
  bool ok;
  Py_BEGIN_ALLOW_THREADS  // the parse is pure C++: release the GIL
  ok = parse_buffer(static_cast<const char*>(buf.buf), buf.len, slots, err,
                    &n_lines);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&buf);
  if (!ok) {
    PyErr_SetString(PyExc_ValueError, err.c_str());
    return nullptr;
  }
  return slots_to_py(slots, n_lines);
}

PyMethodDef kMethods[] = {
    {"parse_multislot", parse_multislot, METH_VARARGS,
     "Parse MultiSlot text data (reference data_feed.cc format):\n"
     "parse_multislot(data: bytes, types: str['f'|'u' per slot]) ->\n"
     "  (n_instances, [(values_bytes, lod_offsets_bytes), ...])"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_data_feed",
    "Native MultiSlot data-feed parser (reference data_feed.cc role)",
    -1, kMethods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__data_feed(void) { return PyModule_Create(&kModule); }
