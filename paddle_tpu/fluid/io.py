"""fluid.io: static-graph checkpointing + inference export.

Role parity: reference python/paddle/fluid/io.py — save_vars:407,
save_params:585, save_persistables:620, load_vars:712, load_params:946,
load_persistables:994, save_inference_model:1198, load_inference_model:1424.
Same architecture: the helpers build a small program of save/load ops and
run it through the Executor (reference save_op.cc:85/load_op.cc:67); on
TPU those programs are host-interpreted (framework/executor.py HOST_OPS)
since file I/O cannot live inside a compiled XLA computation.
"""
from __future__ import annotations

import os
from typing import List, Optional

from ..framework.program import Parameter, Program, Variable
from ..framework.scope import global_scope

MODEL_FILENAME = "__model__"


def is_parameter(var) -> bool:
    return isinstance(var, Parameter) or getattr(var, "is_parameter", False)


def is_persistable(var) -> bool:
    if var.name in ("feed", "fetch") or var.name.startswith("@"):
        return False
    return bool(getattr(var, "persistable", False))


def _collect_vars(main_program, vars=None, predicate=None) -> List[Variable]:
    if vars is not None:
        out = []
        for v in vars:
            out.append(main_program.global_block.var(v)
                       if isinstance(v, str) else v)
        return out
    pred = predicate or is_persistable
    return [v for v in main_program.global_block.vars.values() if pred(v)]


def _io_program(var_list, dirname, filename, op_type) -> Program:
    """Build the save/load program (reference io.py save_vars builds the
    same shape of program with save/save_combine ops)."""
    prog = Program()
    block = prog.global_block
    names = []
    for v in var_list:
        block.create_var(name=v.name, shape=v.shape, dtype=v.dtype,
                         persistable=True)
        names.append(v.name)
    if filename is None:
        for n in names:
            path = os.path.join(dirname, n)
            if op_type == "save":
                block.append_op("save", {"X": [n]}, {},
                                {"file_path": path})
            else:
                block.append_op("load", {}, {"Out": [n]},
                                {"file_path": path})
    else:
        path = os.path.join(dirname, filename)
        if op_type == "save":
            block.append_op("save_combine", {"X": names}, {},
                            {"file_path": path})
        else:
            block.append_op("load_combine", {}, {"Out": names},
                            {"file_path": path})
    return prog


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from ..framework.program import default_main_program

    main_program = main_program or default_main_program()
    var_list = _collect_vars(main_program, vars, predicate)
    if not var_list:
        return
    os.makedirs(dirname, exist_ok=True)
    executor.run(_io_program(var_list, dirname, filename, "save"))


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=is_parameter,
              filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=is_persistable,
              filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from ..framework.program import default_main_program

    main_program = main_program or default_main_program()
    var_list = _collect_vars(main_program, vars, predicate)
    if not var_list:
        return
    executor.run(_io_program(var_list, dirname, filename, "load"))


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=is_parameter,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=is_persistable,
              filename=filename)


# ---------------------------------------------------------------------------
# inference export (reference io.py:1198/1424)
# ---------------------------------------------------------------------------


def prune_program(program: Program, feed_names, target_names,
                 for_test: bool = False) -> Program:
    """Backward-slice the program to the ops needed for target_names given
    feed_names (reference framework/prune.cc via Executor.run(use_prune)).
    Unreferenced vars (e.g. optimizer state) are dropped too, so the slice
    carries exactly the serving surface.  One clone total."""
    from ..framework.executor import _ctrl_attr_reads, _sub_external_reads

    pruned = program.clone(for_test=for_test)
    block = pruned.global_block

    def op_reads(op):
        # control-flow ops read their sub-blocks' closures (captured
        # consts/params) and unwritten branch outputs, not just explicit
        # input slots — dropping those breaks the exported params set
        reads = list(op.input_arg_names()) + _ctrl_attr_reads(pruned, op)
        for aname in ("sub_block", "sub_block_t", "sub_block_f"):
            if op.has_attr(aname):
                reads.extend(_sub_external_reads(pruned, int(op.attr(aname))))
        return reads

    feed_set = set(feed_names)
    needed = set(target_names)
    kept = []
    for op in reversed(block.ops):
        if op.type in ("feed", "fetch"):
            continue
        if set(op.output_arg_names()) & needed:
            kept.append(op)
            for n in op_reads(op):
                if n not in feed_set:
                    needed.add(n)
    block.ops[:] = list(reversed(kept))
    referenced = set(feed_set) | set(target_names)
    for op in block.ops:
        referenced.update(op_reads(op))
        referenced.update(op.output_arg_names())
    block.vars = {n: v for n, v in block.vars.items() if n in referenced}
    pruned._bump()
    missing = [n for n in target_names
               if not any(n in op.output_arg_names() for op in block.ops)
               and n not in feed_set]
    if missing:
        raise ValueError(
            f"target vars {missing} are not produced by the program given "
            f"feeds {sorted(feed_set)}")
    return pruned


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """Export a serve-ready (program, params) pair (reference io.py:1198).

    The program is clone(for_test=True)'d (BN/dropout to inference
    behavior) and pruned to the feed->target slice; feed/fetch names are
    stored as program-level attrs in the proto."""
    from ..framework.program import default_main_program

    main_program = main_program or default_main_program()
    target_vars = [v if isinstance(v, Variable)
                   else main_program.global_block.var(v)
                   for v in target_vars]
    target_names = [v.name for v in target_vars]

    infer_prog = prune_program(main_program, feeded_var_names, target_names,
                               for_test=True)
    infer_prog._feed_names = list(feeded_var_names)
    infer_prog._fetch_names = list(target_names)

    os.makedirs(dirname, exist_ok=True)
    proto = infer_prog.to_proto()
    # feed/fetch contract rides in the proto so load needs no side files
    proto.feed_names.extend(feeded_var_names)
    proto.fetch_names.extend(target_names)
    model_path = os.path.join(dirname, model_filename or MODEL_FILENAME)
    with open(model_path, "wb") as f:
        f.write(proto.SerializeToString())
    if not program_only:
        save_vars(executor, dirname, infer_prog, predicate=is_persistable,
                  filename=params_filename)
    return target_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """Returns [program, feed_names, fetch_targets] (reference io.py:1424)."""
    model_path = os.path.join(dirname, model_filename or MODEL_FILENAME)
    with open(model_path, "rb") as f:
        data = f.read()
    from ..framework import ir_pb2

    proto = ir_pb2.ProgramDef()
    proto.ParseFromString(data)
    program = Program.from_proto(proto)
    feed_names = list(proto.feed_names)
    fetch_names = list(proto.fetch_names)
    program._feed_names = feed_names
    program._fetch_names = fetch_names
    load_vars(executor, dirname, program, predicate=is_persistable,
              filename=params_filename)
    fetch_targets = [program.global_block.var(n) for n in fetch_names]
    return [program, feed_names, fetch_targets]
