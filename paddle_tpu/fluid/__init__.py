"""``fluid``-compatible namespace so reference-era user scripts port directly.

Role parity: python/paddle/fluid/__init__.py of the reference.
"""
from .. import initializer, layers, optimizer, regularizer  # noqa: F401
from ..framework import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    Executor,
    Program,
    Scope,
    TPUPlace,
    Variable,
    default_main_program,
    default_startup_program,
    global_scope,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    program_guard,
)
from ..framework import unique_name  # noqa: F401
from ..framework.backward import append_backward, calc_gradient  # noqa: F401
from ..layers import data  # noqa: F401
from ..param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401

from . import io  # noqa: F401
from .. import profiler  # noqa: F401


def scope_guard(scope):
    import contextlib

    from ..framework.scope import _switch_scope

    @contextlib.contextmanager
    def _guard():
        old = _switch_scope(scope)
        try:
            yield
        finally:
            _switch_scope(old)

    return _guard()
