"""AMP op lists (reference fluid/contrib/mixed_precision/fp16_lists.py +
imperative/amp_auto_cast.cc AmpOperators).

White = compute-bound, run in low precision (MXU ops).  Black = numerically
sensitive, keep fp32.  Gray = follow their inputs.
"""

WHITE_LIST = {
    "conv2d", "depthwise_conv2d", "conv2d_transpose", "matmul", "matmul_v2",
    "mul", "bmm", "fc", "fused_multihead_attention",
}

BLACK_LIST = {
    "exp", "square", "log", "mean", "sum", "cos_sim",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "cross_entropy", "cross_entropy2", "log_softmax",
    "reduce_sum", "reduce_mean", "p_norm", "frobenius_norm",
    "group_norm",
    "instance_norm", "update_loss_scaling", "check_finite_and_unscale",
}

# gray ops whose fp32 inputs are cast down once another input is already
# low precision (reference fp16_utils.py:193 does this for every gray op).
# Without it jnp type promotion silently lifts bf16+fp32 -> fp32, and the
# fp32 poison spreads down the whole residual stream: bias adds after
# white matmuls, residual adds, and every backward dot then runs fp32 on
# the vector units instead of bf16 on the MXU (~8x slower).
GRAY_FOLLOW_CAST = {
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "concat", "stack", "where",
}

# batch_norm/sync_batch_norm/layer_norm/softmax are deliberately NOT
# black on TPU: their lowerings compute in fp32 internally and return Y
# in the input dtype, so keeping them gray lets the activation chain
# (conv->bn->relu->pool, matmul->layer_norm->gelu, attention
# scores->softmax->context) stay bf16 end-to-end — halving HBM traffic vs
# the reference's fp32 black-listing, which exists for CUDA kernel
# reasons we don't have (fp16_lists.py keeps them black).

# everything else is gray: it runs in whatever dtype its inputs carry


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(WHITE_LIST)
        self.black_list = set(BLACK_LIST)
        self.gray_follow_cast = set(GRAY_FOLLOW_CAST)
        self.black_varnames = set(custom_black_varnames or [])
        if custom_white_list:
            self.white_list |= set(custom_white_list)
            self.black_list -= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)
