"""Static-graph AMP: program rewrite + loss scaling.

Role parity: reference fluid/contrib/mixed_precision/decorator.py:235
(`decorate` -> OptimizerWithMixedPrecision) and fp16_utils.py:193
(`rewrite_program` inserting casts per white/black lists), with the
dynamic loss-scale state machine as ops (operators/amp/).

TPU-native default is bf16: same exponent range as fp32, so the loss
scaling machinery is skipped entirely (`use_bf16=True`) — white-list ops
just run with bf16 inputs and XLA keeps MXU accumulation in fp32.
"""
from __future__ import annotations

from ..framework import dtypes, unique_name
from ..framework.program import GRAD_SUFFIX
from .lists import AutoMixedPrecisionLists

_FLOAT = dtypes.to_enum("float32")


def _cast_slot(block, op_idx, op, slot, names_to_cast, dest_dtype, cache):
    """Insert cast ops before `op` for the given input names; returns the
    number of ops inserted."""
    inserted = 0
    slot_names = op.inputs[slot]
    for i, name in enumerate(list(slot_names)):
        if name not in names_to_cast:
            continue
        key = (name, dest_dtype)
        if key not in cache:
            # NOT stop_gradient: casts sit on the differentiable path and
            # must pass gradients through to the fp32 master params
            out = block.create_var(
                name=unique_name.generate(name + ".cast"),
                dtype=dest_dtype, stop_gradient=False)
            from ..framework.program import Operator

            cast_op = Operator(block, "cast", {"X": [name]}, {"Out": [out.name]},
                               {"out_dtype": dest_dtype})
            block.ops.insert(op_idx + inserted, cast_op)
            inserted += 1
            cache[key] = out.name
        slot_names[i] = cache[key]
    return inserted


def rewrite_program(main_program, amp_lists: AutoMixedPrecisionLists,
                    dest_dtype="float16"):
    """Walk ops: white-list ops get their float inputs cast to dest_dtype;
    black-list ops get them cast back to fp32 (reference fp16_utils.py:193)."""
    block = main_program.global_block
    dest_enum = dtypes.to_enum(dest_dtype)
    float_vars = set()
    for var in block.vars.values():
        if var.dtype == _FLOAT:
            float_vars.add(var.name)

    i = 0
    low_vars = set()  # names currently known to be dest_dtype
    while i < len(block.ops):
        op = block.ops[i]
        cache = {}
        if op.type in amp_lists.white_list:
            ins = 0
            for slot, names in list(op.inputs.items()):
                to_cast = {n for n in names
                           if n in float_vars and n not in low_vars
                           and n not in amp_lists.black_varnames}
                if to_cast:
                    ins += _cast_slot(block, i, op, slot,
                                      to_cast, dest_enum, cache)
            low_vars.update(op.output_arg_names())
            i += ins + 1
        elif op.type in amp_lists.black_list:
            ins = 0
            for slot, names in list(op.inputs.items()):
                to_cast = {n for n in names if n in low_vars}
                if to_cast:
                    ins += _cast_slot(block, i, op, slot,
                                      to_cast, _FLOAT, cache)
            i += ins + 1
        else:
            # gray: propagate low precision through; for pure-compute
            # elementwise ops also cast any remaining fp32 inputs down so
            # jnp promotion cannot lift the chain back to fp32 (reference
            # fp16_utils.py:193 gray handling) — bias adds and residual
            # adds are the load-bearing cases
            ins = 0
            if any(n in low_vars for n in op.input_arg_names()):
                if op.type in getattr(amp_lists, "gray_follow_cast", ()):
                    for slot, names in list(op.inputs.items()):
                        to_cast = {n for n in names
                                   if n in float_vars and n not in low_vars
                                   and n not in amp_lists.black_varnames}
                        if to_cast:
                            ins += _cast_slot(block, i, op, slot,
                                              to_cast, dest_enum, cache)
                low_vars.update(op.output_arg_names())
            i += ins + 1
    main_program._bump()
    return main_program


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2.0 ** 15,
                 use_dynamic_loss_scaling=True, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.5,
                 use_bf16=True):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._init_loss_scaling = init_loss_scaling
        self._dynamic = use_dynamic_loss_scaling
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._use_bf16 = use_bf16
        self._loss_scaling = None

    def _create_scale_state(self, block, startup):
        from ..initializer import ConstantInitializer

        def make(name, value, dtype="float32"):
            v = block.create_var(name=unique_name.generate(name), shape=[1],
                                 dtype=dtype, persistable=True,
                                 stop_gradient=True)
            sb = startup.global_block
            sv = sb.create_var(name=v.name, shape=[1], dtype=dtype,
                               persistable=True)
            ConstantInitializer(value)(sv, sb)
            return v

        self._loss_scaling = make("loss_scaling", self._init_loss_scaling)
        self._good_steps = make("good_steps", 0, "int32")
        self._bad_steps = make("bad_steps", 0, "int32")

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ..framework.program import default_startup_program

        program = loss.block.program
        dest = "bfloat16" if self._use_bf16 else "float16"
        if not self._use_bf16:
            # the norms' bf16-transparent treatment (fp32 stats inside,
            # low-precision Y) is only safe with bf16's fp32 exponent
            # range; under fp16 + loss scaling keep them fp32 islands as
            # the reference does (fp16_lists.py)
            import copy

            lists = copy.deepcopy(self._amp_lists)
            lists.black_list |= {"batch_norm", "sync_batch_norm",
                                 "layer_norm", "softmax"} - lists.white_list
            self._amp_lists = lists
        rewrite_program(program, self._amp_lists, dest)

        if self._use_bf16:
            # bf16 keeps fp32 range: no loss scaling needed (TPU-native)
            return self._optimizer.minimize(loss, startup_program,
                                            parameter_list, no_grad_set)

        startup = startup_program or default_startup_program()
        block = program.global_block
        self._create_scale_state(block, startup)
        scaled_loss = block.create_var(
            name=unique_name.generate(loss.name + ".scaled"),
            shape=list(loss.shape) or [1],
            dtype="float32", stop_gradient=False)
        block.append_op("elementwise_mul",
                        {"X": [loss.name], "Y": [self._loss_scaling.name]},
                        {"Out": [scaled_loss.name]}, {"axis": -1})

        def unscale_and_update(params_grads):
            grad_names = [g.name if hasattr(g, "name") else g
                          for _, g in params_grads]
            found_inf = block.create_var(
                name=unique_name.generate("found_inf"), dtype="bool",
                stop_gradient=True)
            block.append_op(
                "check_finite_and_unscale",
                {"X": grad_names, "Scale": self._loss_scaling.name},
                {"Out": grad_names, "FoundInfinite": found_inf.name})
            if self._dynamic:
                block.append_op(
                    "update_loss_scaling",
                    {"X": grad_names, "FoundInfinite": found_inf.name,
                     "PrevLossScaling": self._loss_scaling.name,
                     "InGoodSteps": self._good_steps.name,
                     "InBadSteps": self._bad_steps.name},
                    {"Out": grad_names,
                     "LossScaling": self._loss_scaling.name,
                     "OutGoodSteps": self._good_steps.name,
                     "OutBadSteps": self._bad_steps.name},
                    {"incr_every_n_steps": self._incr_every,
                     "decr_every_n_nan_or_inf": self._decr_every,
                     "incr_ratio": self._incr_ratio,
                     "decr_ratio": self._decr_ratio})
            return params_grads

        if getattr(self._optimizer, "supports_grad_transform", False):
            # gradient_merge composition: the merge optimizer drives
            # backward/apply itself, so the unscale + scaling-state
            # update ride its grad-transform hook — they land inside the
            # masked region, and the merge machinery select-restores the
            # loss-scaling counters on non-update steps (otherwise the
            # masked zero-grads would count as "good steps" every step)
            return self._optimizer.minimize(
                scaled_loss, startup, parameter_list, no_grad_set,
                grad_transform=unscale_and_update)

        params_grads = self._optimizer.backward(
            scaled_loss, startup, parameter_list, no_grad_set)
        unscale_and_update(params_grads)
        opt_ops = self._optimizer.apply_gradients(params_grads)
        return opt_ops, params_grads

    def backward(self, *args, **kwargs):
        return self._optimizer.backward(*args, **kwargs)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def __getattr__(self, name):
        if name == "_optimizer":  # not yet set (unpickling/deepcopy)
            raise AttributeError(name)
        return getattr(self._optimizer, name)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.0 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.5, use_dynamic_loss_scaling=True,
             use_bf16=None, use_pure_fp16=False, use_fp16_guard=None):
    """Reference fluid.contrib.mixed_precision.decorate.  On TPU the
    default low precision is bf16 (no loss scaling); pass use_bf16=False
    for fp16 + dynamic scaling parity."""
    if use_bf16 is None:
        use_bf16 = True
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        use_bf16=use_bf16)
