"""`paddle.amp` equivalent: auto_cast + GradScaler (+ static decorate).

Role parity: reference python/paddle/amp/ (auto_cast.py:91 `amp_guard`,
grad_scaler.py) and imperative/amp_auto_cast.{h,cc}.  TPU-native notes:
bf16 is the TPU-native low precision — same exponent range as fp32, so
loss scaling is mathematically unnecessary (GradScaler with bf16 is a
transparent passthrough kept for API parity); fp16 + dynamic loss
scaling is implemented for parity and for the check_finite/update ops.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import numpy as np

from .lists import AutoMixedPrecisionLists
from .static_amp import decorate as static_decorate  # noqa: F401


class _AmpState:
    def __init__(self):
        self.enabled = False
        self.dtype = "bfloat16"
        self.level = "O1"
        self.lists = AutoMixedPrecisionLists()


_state = _AmpState()


def amp_state() -> _AmpState:
    return _state


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """Dygraph autocast guard (reference amp_guard): eager ops on the white
    list run in `dtype`; black-list ops in fp32; gray ops follow inputs.
    Implemented as an input-cast hook in the eager dispatcher."""
    prev = (_state.enabled, _state.dtype, _state.level, _state.lists)
    _state.enabled = bool(enable)
    _state.dtype = {"float16": "float16", "bfloat16": "bfloat16"}[dtype]
    _state.level = level
    _state.lists = AutoMixedPrecisionLists(custom_white_list, custom_black_list)
    try:
        yield
    finally:
        _state.enabled, _state.dtype, _state.level, _state.lists = prev


amp_guard = auto_cast


class GradScaler:
    """Dynamic loss scaling (reference paddle/amp/grad_scaler.py).

    The scale/unscale math reuses the check_finite_and_unscale and
    update_loss_scaling op rules so eager and static AMP share one
    state machine implementation.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good = 0
        self._bad = 0
        self._found_inf = False

    def scale(self, loss):
        if not self._enable:
            return loss
        from ..tensor.math import scale as _scale

        return _scale(loss, self._scale)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        import jax.numpy as jnp

        params = getattr(optimizer, "_parameter_list", None) or []
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p.grad is None:
                continue
            g = p.grad._value * inv
            finite = bool(jnp.isfinite(g).all())
            found = found or not finite
            p.grad._set_raw(g)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad += 1
            self._good = 0
            if self._bad >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad = 0
        else:
            self._good += 1
            self._bad = 0
            if self._good >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good = 0

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good,
                "bad_steps": self._bad}

    def set_state_dict(self, state):
        self._scale = float(state.get("scale", self._scale))
        self._good = int(state.get("good_steps", 0))
        self._bad = int(state.get("bad_steps", 0))


def decorate(models=None, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None, **kwargs):
    """Dygraph decorate (reference paddle.amp.decorate): O1 needs no model
    surgery (autocast handles it); O2 casts parameters to `dtype`."""
    if level == "O2" and models is not None:
        from ..framework import dtypes

        jd = dtypes.to_jnp(dtype)
        model_list = models if isinstance(models, (list, tuple)) else [models]
        for m in model_list:
            for p in m.parameters():
                p._set_raw(p._value.astype(jd))
    if optimizers is None:
        return models
    return models, optimizers
