"""Runtime stat registry (reference paddle/fluid/platform/monitor.h:77
``StatRegistry`` / ``STAT_ADD``/``STAT_RESET`` macros and monitor.py's
exposed counters).

TPU-native framing: the reference tracks GPU mem/NCCL counters per
device; here the interesting runtime facts are compile-cache behavior
and dispatch counts (XLA owns memory).  The registry is a process-wide,
thread-safe name -> int64 counter map; the Executor feeds it
(executor_compile / executor_cache_hit / executor_run), and user code
can register its own counters with the same API.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Tuple

__all__ = ["StatRegistry", "stat_add", "stat_get", "stat_reset",
           "stat_set", "stat_max", "stat_time", "export_stats"]


class _Stat:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, increment: int = 1) -> None:
        with self._lock:
            self._value += int(increment)

    def set(self, value: int) -> None:
        """Gauge semantics (queue depth, last-batch size, ...)."""
        with self._lock:
            self._value = int(value)

    def max_update(self, value: int) -> None:
        """High-water-mark semantics: keep the max ever seen."""
        value = int(value)
        with self._lock:
            if value > self._value:
                self._value = value

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    def get(self) -> int:
        with self._lock:
            return self._value


class StatRegistry:
    """Process-wide singleton (reference monitor.h StatRegistry::Instance)."""

    _instance: "StatRegistry" = None  # type: ignore[assignment]
    _instance_lock = threading.Lock()

    def __init__(self):
        self._stats: Dict[str, _Stat] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StatRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def stat(self, name: str) -> _Stat:
        with self._lock:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = _Stat(name)
            return s

    def add(self, name: str, increment: int = 1) -> None:
        self.stat(name).add(increment)

    def set(self, name: str, value: int) -> None:
        self.stat(name).set(value)

    def max_update(self, name: str, value: int) -> None:
        self.stat(name).max_update(value)

    def get(self, name: str) -> int:
        return self.stat(name).get()

    def reset(self, name: str = None) -> None:
        if name is not None:
            self.stat(name).reset()
            return
        with self._lock:
            stats = list(self._stats.values())
        for s in stats:
            s.reset()

    def export(self) -> List[Tuple[str, int]]:
        """Sorted (name, value) snapshot (reference StatRegistry::publish)."""
        with self._lock:
            stats = list(self._stats.items())
        return sorted((n, s.get()) for n, s in stats)


def stat_add(name: str, increment: int = 1) -> None:
    """Reference STAT_ADD macro."""
    StatRegistry.instance().add(name, increment)


def stat_set(name: str, value: int) -> None:
    """Gauge write (queue depth, occupancy high-water marks use stat_max)."""
    StatRegistry.instance().set(name, value)


def stat_max(name: str, value: int) -> None:
    """Keep the maximum ever observed for ``name``."""
    StatRegistry.instance().max_update(name, value)


def stat_get(name: str) -> int:
    return StatRegistry.instance().get(name)


def stat_reset(name: str = None) -> None:
    """Reference STAT_RESET macro (no name: reset everything)."""
    StatRegistry.instance().reset(name)


def stat_time(name: str, seconds: float) -> None:
    """Latency observation — the timing sibling of STAT_ADD.  Feeds the
    log-bucketed histogram registry (observe/histogram.py); p50/p95/p99
    come back through ``export_stats()``/``/stats``/``/metrics``."""
    from .observe.histogram import stat_time as _stat_time

    _stat_time(name, seconds)


def export_stats() -> List[Tuple[str, float]]:
    """Counters plus flattened histogram summaries (``<name>_p50`` ...),
    one sorted snapshot — counters stay ints, histogram rows are floats."""
    out = list(StatRegistry.instance().export())
    from .observe.histogram import histogram_summaries

    out.extend(histogram_summaries())
    return sorted(out)
