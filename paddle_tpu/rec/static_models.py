"""Static-graph recommender builders (wide&deep / DLRM family).

Role parity: the reference's PaddleRec wide_deep & DLRM models over
the Criteo layout — dense float features + multi-field sparse ids into
embedding tables, a wide (linear-in-ids) side and a deep MLP tower,
binary click loss.  TPU-native: both tables are built
``is_sparse=True``, which under a tensor-parallel fleet program makes
the ShardingPropagationPass row-shard them P('mp', None) and the
lookup ride the distributed engine (ops/embedding_ops.py) — no
parameter server.  Shared by tests/test_sharded_embedding.py,
bench.py::bench_dlrm and the __graft_entry__ MULTICHIP embedding leg.
"""
from __future__ import annotations

from .. import layers
from ..initializer import NormalInitializer
from ..param_attr import ParamAttr


def wide_deep_net(sparse_ids, dense, vocab_size, emb_dim=16,
                  n_fields=8, hidden=(64, 32), padding_idx=None,
                  sparse=True, name="wd"):
    """Wide&deep trunk -> [B, 2] click logits.

    ``sparse_ids`` [B, n_fields] int64 (all fields share one
    ``vocab_size × emb_dim`` table — the DLRM "one big table" shape
    that forces sharding), ``dense`` [B, n_dense] float32.  The wide
    side is a second dim-1 table over the same ids (a linear model in
    the categorical features)."""
    emb_attr = lambda n: ParamAttr(  # noqa: E731
        name=n, initializer=NormalInitializer(0.0, 0.01))
    # deep side: [B, F, emb_dim] -> [B, F*emb_dim]
    emb = layers.embedding(sparse_ids, (vocab_size, emb_dim),
                           is_sparse=sparse, padding_idx=padding_idx,
                           param_attr=emb_attr(name + "_table"))
    deep = layers.reshape(emb, [0, int(n_fields) * int(emb_dim)],
                          name=name + "_flat")
    deep = layers.concat([deep, dense], axis=1, name=name + "_in")
    deep.shape = (int(dense.shape[0]),
                  int(n_fields) * int(emb_dim) + int(dense.shape[1]))
    for i, h in enumerate(hidden):
        deep = layers.fc(deep, int(h), act="relu",
                         name=f"{name}_deep{i}")
    deep_logit = layers.fc(deep, 2, name=name + "_deep_out")
    # wide side: per-id scalar weights -> [B, F] -> linear head
    wide = layers.embedding(sparse_ids, (vocab_size, 1),
                            is_sparse=sparse, padding_idx=padding_idx,
                            param_attr=emb_attr(name + "_wide_table"))
    wide = layers.reshape(wide, [0, int(n_fields)], name=name + "_wide_f")
    wide_logit = layers.fc(wide, 2, name=name + "_wide_out")
    return layers.elementwise_add(deep_logit, wide_logit,
                                  name=name + "_logits")


def wide_deep_program(batch_size=64, vocab_size=65536, emb_dim=16,
                      n_fields=8, n_dense=13, hidden=(64, 32),
                      padding_idx=None, sparse=True, lr=1e-2):
    """Build (main, startup, feeds, loss, optimizer) for one wide&deep
    training step — the recommender flagship.

    Feeds: sparse_ids [B, n_fields] int64, dense_x [B, n_dense]
    float32, labels [B, 1] int64 (click / no-click).
    """
    from ..framework.program import Program, program_guard
    from ..optimizer import SGDOptimizer

    main, startup = Program(), Program()
    with program_guard(main, startup):
        sparse_ids = layers.data("sparse_ids", [batch_size, n_fields],
                                 dtype="int64", append_batch_size=False)
        dense_x = layers.data("dense_x", [batch_size, n_dense],
                              dtype="float32", append_batch_size=False)
        labels = layers.data("labels", [batch_size, 1],
                             dtype="int64", append_batch_size=False)
        logits = wide_deep_net(
            sparse_ids, dense_x, vocab_size, emb_dim=emb_dim,
            n_fields=n_fields, hidden=hidden, padding_idx=padding_idx,
            sparse=sparse)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, labels),
            name="wd_loss")
        opt = SGDOptimizer(learning_rate=lr)
    feeds = (sparse_ids, dense_x, labels)
    return main, startup, feeds, loss, opt
