"""Recommender model zoo (reference PaddleRec's wide_deep / DLRM
flagships) — the workload the sharded embedding engine
(paddle_tpu.distributed.embedding) exists for: sparse categorical
fields over a vocabulary far larger than one chip's HBM."""
from .static_models import wide_deep_program  # noqa: F401

__all__ = ["wide_deep_program"]
