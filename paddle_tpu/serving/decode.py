"""KV-cache autoregressive decode engine with continuous batching.

Role parity: the generative-serving half of Paddle Serving / the
reference's inference deployment story — the piece the PR-1 one-shot
bucket batcher cannot cover, because autoregressive decode re-enters
the model once PER TOKEN.  Recomputing the prefix every token is
O(len^2) per request; waiting for a shape bucket adds whole-batch
latency to every new arrival.  This engine is the TPU-native fix:

- **Persistent per-slot KV cache** (`kv_cache.py`): each of the
  ``slots`` concurrent requests owns paged key/value blocks inside two
  device-resident pool arrays.  The pools ride
  ``Executor.run_persistent`` with donation, so the cache NEVER
  round-trips to host between steps — per-token work is O(1) in the
  prefix length.
- **Continuous batching** (Orca's iteration-level scheduling): one
  jitted step decodes every live slot jointly; new requests claim free
  slots at step boundaries (prefill fills the slot's pages, decode
  proceeds with the batch that's already in flight), and a slot whose
  request finishes — EOS, token budget, or deadline — frees
  IMMEDIATELY instead of padding to the longest neighbor.
- **Deadline reap mid-decode**: a lapsed deadline is honored at every
  step boundary (not just at dequeue), so a stalled client cannot pin
  a slot for the full max_new_tokens.
- **Streaming replies**: each sampled token is pushed to the request's
  stream the step it is produced — consume via the ``tokens()``
  generator or an ``on_token`` callback; ``result()`` blocks for the
  full sequence.
- **Deterministic sampling** (`ops/sampling_ops.py`): greedy / top-k /
  top-p run INSIDE the compiled step with an explicit per-request PRNG
  key (seed + fold_in(token index)), so a request's tokens are
  independent of slot assignment, batch composition, and replica —
  the property multi-replica scale-out (serving/server.py
  ``DecodeServer``) relies on.

Attention reads the page pool through
``ops/pallas_decode_attention.py``: the Pallas kernel on TPU (page
table as scalar-prefetch operands — one page DMA per grid step), the
pure-jnp gather+mask reference on CPU so tier-1 stays green.  Prefill
and decode share one masked-softmax formulation at one width
(max_seq_len), which is what makes decode-with-cache logits
bitwise-equal to a full recompute (`tests/test_decode_engine.py` pins it at
every step).

Observability: ``decode_*`` counters/gauges plus ``ttft_seconds`` /
``tpot_seconds`` / ``decode_step_seconds`` histograms — all on
``/metrics`` wherever a fleet KV HTTP server runs.
"""
from __future__ import annotations

import collections
import math
import queue as _queue
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..monitor import stat_add, stat_max, stat_set
from ..observe import tracer as otrace
from ..observe.histogram import stat_time
from .batcher import _UNSET, RequestBase
from .buckets import (BucketSpec, DeadlineExceededError, QueueFullError,
                      RequestTooLargeError, ServerClosedError,
                      prefill_bucket_grid)
from . import kv_cache
from .kv_cache import CacheConfig, PagedKVCache, K_PAGES_VAR, V_PAGES_VAR

_STATE_VARS = (K_PAGES_VAR, V_PAGES_VAR)
_DONE = object()  # stream sentinel


# ---------------------------------------------------------------------------
# model


class TransformerLM:
    """A decoder-only transformer sized by constructor args — the
    engine's reference model (bench, tests, demos).  Any model works
    with the engine if it exposes this class's surface: ``num_layers``
    / ``num_heads`` / ``head_dim`` / ``vocab_size`` plus the pure
    per-row pieces below, which prefill and decode COMPOSE IDENTICALLY
    so cached decode stays bitwise-comparable to a full recompute
    (layer norm, QKV/out projections, MLP are all row-independent)."""

    def __init__(self, vocab_size: int, d_model: int = 64,
                 num_layers: int = 2, num_heads: int = 2,
                 ffn_dim: Optional[int] = None, max_seq_len: int = 256):
        self.vocab_size = int(vocab_size)
        self.d_model = int(d_model)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        if d_model % num_heads:
            raise ValueError("d_model must divide by num_heads")
        self.head_dim = self.d_model // self.num_heads
        self.ffn_dim = int(ffn_dim) if ffn_dim else 4 * self.d_model
        self.max_seq_len = int(max_seq_len)

    def init_weights(self, key):
        import jax
        import jax.numpy as jnp

        dm, f, v = self.d_model, self.ffn_dim, self.vocab_size
        n_per_layer = 6
        keys = jax.random.split(key, 3 + self.num_layers * n_per_layer)

        def dense(k, shape, scale=None):
            scale = scale if scale is not None else 1.0 / math.sqrt(shape[0])
            return (jax.random.normal(k, shape) * scale).astype(jnp.float32)

        w = {
            "tok_emb": dense(keys[0], (v, dm), 0.02),
            "pos_emb": dense(keys[1], (self.max_seq_len, dm), 0.02),
            "lm_head": dense(keys[2], (dm, v)),
            "lnf_g": jnp.ones((dm,), jnp.float32),
            "lnf_b": jnp.zeros((dm,), jnp.float32),
            "layers": [],
        }
        for i in range(self.num_layers):
            k = keys[3 + i * n_per_layer: 3 + (i + 1) * n_per_layer]
            w["layers"].append({
                "ln1_g": jnp.ones((dm,), jnp.float32),
                "ln1_b": jnp.zeros((dm,), jnp.float32),
                "wq": dense(k[0], (dm, dm)),
                "wk": dense(k[1], (dm, dm)),
                "wv": dense(k[2], (dm, dm)),
                "wo": dense(k[3], (dm, dm)),
                "ln2_g": jnp.ones((dm,), jnp.float32),
                "ln2_b": jnp.zeros((dm,), jnp.float32),
                "w1": dense(k[4], (dm, f)),
                "w2": dense(k[5], (f, dm)),
            })
        return w

    # -- pure per-row pieces (shared verbatim by prefill and decode) ------
    @staticmethod
    def _ln(x, g, b):
        import jax.numpy as jnp

        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * g + b

    def _embed(self, w, tokens, positions):
        return w["tok_emb"][tokens] + w["pos_emb"][positions]

    def _qkv(self, lw, h):
        n, d = self.num_heads, self.head_dim
        q = (h @ lw["wq"]).reshape(*h.shape[:-1], n, d)
        k = (h @ lw["wk"]).reshape(*h.shape[:-1], n, d)
        v = (h @ lw["wv"]).reshape(*h.shape[:-1], n, d)
        return q, k, v

    def _attn_out(self, lw, ctx):
        return ctx.reshape(*ctx.shape[:-2], self.d_model) @ lw["wo"]

    def _mlp(self, lw, h):
        import jax

        return jax.nn.gelu(h @ lw["w1"]) @ lw["w2"]

    def _head(self, w, x):
        return self._ln(x, w["lnf_g"], w["lnf_b"]) @ w["lm_head"]


# ---------------------------------------------------------------------------
# requests


class DecodeRequest(RequestBase):
    """Streaming future for one generation request.

    Tokens arrive on an internal stream as the engine produces them:
    iterate ``tokens()`` for a generator, pass ``on_token=`` for a
    callback (called from the engine thread — keep it cheap), or call
    ``result()`` for the completed id list.  ``generated`` always
    holds the ids produced so far (partial output survives a deadline
    reap)."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "top_k",
                 "top_p", "seed", "on_token", "generated", "_stream",
                 "t_first_token", "record_logits", "logits_trace")

    _deadline_stat = "decode_deadline_exceeded"

    def __init__(self, prompt, max_new_tokens, deadline, temperature,
                 top_k, top_p, seed, on_token, record_logits=False):
        super().__init__(deadline)
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        self.on_token = on_token
        self.generated: List[int] = []
        self._stream: _queue.Queue = _queue.Queue()
        self.t_first_token: Optional[float] = None
        self.record_logits = bool(record_logits)
        self.logits_trace: List[np.ndarray] = []

    # engine side ---------------------------------------------------------
    def _emit(self, token: int) -> None:
        if self.t_first_token is None:
            self.t_first_token = time.monotonic()
            stat_time("ttft_seconds", self.t_first_token - self.t_enqueue)
        self.generated.append(int(token))
        self._stream.put(int(token))
        if self.on_token is not None:
            try:
                self.on_token(int(token))
            except Exception:  # noqa: BLE001 — user callback, isolate
                stat_add("decode_callback_errors")

    def _finish(self, error=None) -> bool:
        won = self._complete(result=list(self.generated), error=error)
        self._stream.put(_DONE)  # always: a racing client-side reap
        # must still terminate a tokens() reader
        return won

    # client side ---------------------------------------------------------
    def tokens(self, timeout: Optional[float] = None):
        """Generator over streamed token ids; raises the request's
        error (after yielding everything produced) if it failed."""
        while True:
            budget = timeout
            if self.deadline is not None:
                # the engine reaps at the next step boundary; the small
                # grace covers its in-flight step
                rem = max(self.deadline - time.monotonic(), 0.0) + 1.0
                budget = rem if budget is None else min(budget, rem)
            try:
                item = self._stream.get(timeout=budget)
            except _queue.Empty:
                raise TimeoutError(
                    "no token within the wait budget") from None
            if item is _DONE:
                break
            yield item
        if self._error is not None:
            raise self._error


class _SlotState:
    __slots__ = ("req", "base_key", "n_generated", "last_token", "t_last")

    def __init__(self, req, base_key):
        self.req = req
        self.base_key = base_key
        self.n_generated = 0
        self.last_token = 0
        self.t_last = time.monotonic()


# ---------------------------------------------------------------------------
# engine


class DecodeConfig:
    """Engine knobs; defaults come from the ``FLAGS_decode_*`` flags."""

    def __init__(self, slots: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 max_queue: int = 256,
                 default_deadline_ms: Optional[float] = None,
                 use_pallas: str = "auto",
                 interpret: bool = False,
                 cache_dtype="float32"):
        from ..framework import flags

        self.slots = int(slots if slots is not None
                         else flags.flag("decode_slots"))
        self.max_seq_len = int(max_seq_len if max_seq_len is not None
                               else flags.flag("decode_max_seq_len"))
        self.page_size = int(page_size if page_size is not None
                             else flags.flag("decode_page_size"))
        self.num_pages = num_pages
        self.max_new_tokens = int(
            max_new_tokens if max_new_tokens is not None
            else flags.flag("decode_max_new_tokens"))
        self.eos_id = eos_id
        self.max_queue = int(max_queue)
        self.default_deadline_ms = default_deadline_ms
        self.use_pallas = use_pallas
        self.interpret = bool(interpret)
        self.cache_dtype = cache_dtype


class DecodeEngine:
    """One decode replica: a slot batch, its paged KV cache, and the
    consumer thread that runs admission -> prefill -> joint decode
    step, forever.  ``continuous=False`` degrades admission to the
    one-shot group mode (a new group only starts when EVERY slot is
    free) — the static-batching baseline bench.py's A/B uses."""

    def __init__(self, model, weights, config: Optional[DecodeConfig] = None,
                 place=None, name: str = "replica-0", continuous: bool = True):
        import jax

        from ..framework.executor import Executor
        from ..framework.scope import Scope

        self.model = model
        self.config = config or DecodeConfig()
        self.name = name
        self._continuous = bool(continuous)
        c = self.config
        if c.max_seq_len > model.max_seq_len:
            raise ValueError(
                f"DecodeConfig.max_seq_len {c.max_seq_len} exceeds the "
                f"model's positional table ({model.max_seq_len})")
        self._scope = Scope()
        self._exe = Executor(place)
        self._cache = PagedKVCache(
            CacheConfig(model.num_layers, model.num_heads, model.head_dim,
                        c.slots, c.max_seq_len, c.page_size,
                        num_pages=c.num_pages, dtype=c.cache_dtype),
            self._scope)
        self.weights = jax.tree_util.tree_map(jax.numpy.asarray, weights)
        self._buckets = BucketSpec(
            (1,), prefill_bucket_grid(c.max_seq_len, c.page_size))
        self._step_fn = self._build_step_fn()
        self._prefill_fns = {}
        self._slots: List[Optional[_SlotState]] = [None] * c.slots
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._closing = False
        self._abort = False
        self._thread = None
        self._seq = 0  # default-seed counter
        self.tokens_total = 0

    # -- jitted step builders --------------------------------------------
    def _attend(self, q, k_pages, v_pages, layer, page_table, lengths):
        from ..ops.pallas_decode_attention import paged_decode_attention

        # all backend dispatch (auto/always/never, Pallas vs the
        # gather+mask reference) lives in ONE place: the op itself
        return paged_decode_attention(
            q, k_pages[layer], v_pages[layer], page_table, lengths,
            use_pallas=self.config.use_pallas,
            interpret=self.config.interpret)

    def _build_step_fn(self):
        import jax
        import jax.numpy as jnp

        from ..ops.sampling_ops import sample_tokens

        model = self.model

        def step(state, weights, tokens, positions, live, page_table,
                 write_page, write_off, base_keys, counters, temp, top_k,
                 top_p):
            k_pages, v_pages = state
            x = model._embed(weights, tokens, positions)       # [S, Dm]
            lengths = positions + 1  # the token written THIS step included
            for l in range(model.num_layers):
                lw = weights["layers"][l]
                h = model._ln(x, lw["ln1_g"], lw["ln1_b"])
                q, k, v = model._qkv(lw, h)                    # [S, H, D]
                k_pages = kv_cache.scatter_token_layer(
                    k_pages, l, k, write_page, write_off)
                v_pages = kv_cache.scatter_token_layer(
                    v_pages, l, v, write_page, write_off)
                ctx = self._attend(q, k_pages, v_pages, l, page_table,
                                   lengths)
                x = x + model._attn_out(lw, ctx)
                x = x + model._mlp(
                    lw, model._ln(x, lw["ln2_g"], lw["ln2_b"]))
            logits = model._head(weights, x)                   # [S, V]
            keys = jax.vmap(jax.random.fold_in)(base_keys, counters)
            nxt = sample_tokens(keys, logits, temp, top_k, top_p)
            nxt = jnp.where(live, nxt, 0)
            return (nxt, logits), (k_pages, v_pages)

        return jax.jit(step, donate_argnums=(0,))

    def _build_prefill_fn(self, t_pad: int):
        import jax
        import jax.numpy as jnp

        from ..ops.pallas_decode_attention import \
            decode_attention_reference
        from ..ops.sampling_ops import sample_tokens

        model = self.model
        cc = self._cache.config
        t_max = cc.max_seq_len
        n_bp = t_pad // cc.page_size
        cdt = cc.dtype

        def prefill(state, weights, tokens, length, pages, base_key,
                    temp, top_k, top_p):
            k_pages, v_pages = state
            positions = jnp.arange(t_pad, dtype=jnp.int32)
            x = model._embed(weights, tokens, positions)    # [T_pad, Dm]
            row_lengths = positions + 1
            for l in range(model.num_layers):
                lw = weights["layers"][l]
                h = model._ln(x, lw["ln1_g"], lw["ln1_b"])
                q, k, v = model._qkv(lw, h)                 # [T_pad, H, D]
                k_pages = kv_cache.scatter_prompt_layer(
                    k_pages, l, k, pages[:n_bp])
                v_pages = kv_cache.scatter_prompt_layer(
                    v_pages, l, v, pages[:n_bp])
                # attention at FULL cache width through the SAME cache
                # dtype the pages store — each row's numerics are the
                # ones decode will reproduce from the pages, which is
                # the bitwise prefix-cache contract
                shape = (t_max, model.num_heads, model.head_dim)
                kf = jnp.zeros(shape, cdt).at[:t_pad].set(k.astype(cdt))
                vf = jnp.zeros(shape, cdt).at[:t_pad].set(v.astype(cdt))
                ctx = decode_attention_reference(
                    q, jnp.broadcast_to(kf[None], (t_pad,) + shape),
                    jnp.broadcast_to(vf[None], (t_pad,) + shape),
                    row_lengths)
                x = x + model._attn_out(lw, ctx)
                x = x + model._mlp(
                    lw, model._ln(x, lw["ln2_g"], lw["ln2_b"]))
            logits = model._head(weights, x)                # [T_pad, V]
            last = jax.lax.dynamic_index_in_dim(
                logits, length - 1, 0, keepdims=False)
            key0 = jax.random.fold_in(base_key, 0)
            tok = sample_tokens(key0[None], last[None], temp[None],
                                top_k[None], top_p[None])[0]
            return (tok, last), (k_pages, v_pages)

        return jax.jit(prefill, donate_argnums=(0,))

    def _prefill_fn(self, t_pad: int):
        fn = self._prefill_fns.get(t_pad)
        if fn is None:
            fn = self._prefill_fns[t_pad] = self._build_prefill_fn(t_pad)
            stat_add("decode_prefill_compiles")
        return fn

    # -- client side ------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens=None,
               deadline_ms=_UNSET, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0,
               seed: Optional[int] = None,
               on_token: Optional[Callable[[int], None]] = None,
               record_logits: bool = False) -> DecodeRequest:
        c = self.config
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must hold at least one token id")
        if max_new_tokens is None:
            max_new_tokens = c.max_new_tokens
        if len(prompt) + int(max_new_tokens) > c.max_seq_len:
            raise RequestTooLargeError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the slot capacity "
                f"({c.max_seq_len}); raise FLAGS_decode_max_seq_len or "
                f"shorten the request")
        cc = self._cache.config
        need = cc.pages_for(len(prompt) + int(max_new_tokens))
        if need > cc.num_pages - 1:  # page 0 is trash, never allocatable
            # an unsatisfiable reservation must be rejected HERE: queued
            # it would head-of-line-block the engine forever (no finish
            # can ever free enough pages)
            raise RequestTooLargeError(
                f"request needs {need} cache pages but the pool only "
                f"has {cc.num_pages - 1}; raise num_pages or shorten "
                f"the request")
        self._buckets.seq_bucket(len(prompt))  # raises RequestTooLarge
        if deadline_ms is _UNSET:
            deadline_ms = c.default_deadline_ms
        deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        with self._cond:
            if self._closing:
                raise ServerClosedError("decode engine is stopping")
            if len(self._queue) >= c.max_queue:
                stat_add("decode_rejected_queue_full")
                raise QueueFullError(
                    f"decode queue is at capacity ({c.max_queue})")
            if seed is None:
                seed = self._seq
            self._seq += 1
            req = DecodeRequest(prompt, max_new_tokens, deadline,
                                temperature, top_k, top_p, seed,
                                on_token, record_logits=record_logits)
            self._queue.append(req)
            stat_add("decode_requests")
            stat_set("decode_queue_depth", len(self._queue))
            self._cond.notify_all()
        return req

    def generate(self, prompt, **kw) -> List[int]:
        return self.submit(prompt, **kw).result()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "DecodeEngine":
        with self._cond:
            if self._thread is not None:
                return self
            self._closing = self._abort = False
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"decode-{self.name}")
            self._thread.start()
        from ..observe import flight as _flight

        _flight.record("serving/decode_start", name=self.name,
                       slots=self.config.slots,
                       max_seq_len=self.config.max_seq_len,
                       page_size=self.config.page_size)
        return self

    def stop(self, drain: bool = True):
        with self._cond:
            self._closing = True
            if not drain:
                self._abort = True
                while self._queue:
                    req = self._queue.popleft()
                    if req._finish(error=ServerClosedError(
                            "engine stopped before the request ran")):
                        stat_add("decode_cancelled")
                stat_set("decode_queue_depth", 0)
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        from ..observe import flight as _flight

        _flight.record("serving/decode_stop", name=self.name,
                       drain=bool(drain))

    def __enter__(self) -> "DecodeEngine":
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=exc[0] is None)
        return False

    # -- scheduler --------------------------------------------------------
    @property
    def live_slots(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    @property
    def free_slots(self) -> int:
        return self.config.slots - self.live_slots

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def _expire(self, req, where: str) -> None:
        if req._finish(error=DeadlineExceededError(
                f"deadline exceeded {where}")):
            stat_add("decode_deadline_exceeded")

    def _reap_queue_locked(self):
        now = time.monotonic()
        live = []
        for r in self._queue:
            if r.done():
                continue
            if r.expired(now):
                self._expire(r, "while queued")
                continue
            live.append(r)
        if len(live) != len(self._queue):
            self._queue = collections.deque(live)
            stat_set("decode_queue_depth", len(self._queue))

    def _admit_locked(self):
        import jax

        if not self._continuous and self.live_slots:
            return []  # one-shot baseline: groups never mix
        admitted = []
        while self._queue:
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                break
            req = self._queue[0]
            if req.done():
                self._queue.popleft()
                continue
            if req.expired():
                self._queue.popleft()
                self._expire(req, "while queued")
                continue
            # conservative reservation: pages for the worst case, so a
            # decode step can never die on cache exhaustion mid-flight
            need = len(req.prompt) + req.max_new_tokens
            if not self._cache.claim(free[0], need):
                stat_add("decode_admission_blocked_pages")
                break  # FIFO head-of-line: wait for pages to free
            self._queue.popleft()
            slot = free[0]
            self._slots[slot] = _SlotState(
                req, jax.random.PRNGKey(req.seed))
            admitted.append((slot, req))
        stat_set("decode_queue_depth", len(self._queue))
        return admitted

    def _release(self, slot: int):
        self._slots[slot] = None
        self._cache.release(slot)
        stat_set("decode_free_pages", self._cache.allocator.num_free)

    def _finish_slot(self, slot: int, error=None):
        st = self._slots[slot]
        if error is None:
            if st.req._finish():
                stat_add("decode_completed")
        else:
            if st.req._finish(error=error):
                stat_add("decode_failed")
        self._release(slot)

    def _reap_live(self):
        """The mid-decode deadline reap: runs at EVERY step boundary so
        a stalled/abandoned client frees its slot now, not after
        max_new_tokens."""
        now = time.monotonic()
        for i, st in enumerate(self._slots):
            if st is None:
                continue
            if st.req.done():  # client-side reap/abandon won the race
                stat_add("decode_abandoned")
                self._release(i)
            elif st.req.expired(now):
                self._expire(st.req, "mid-decode (slot freed)")
                self._release(i)

    def _loop(self):
        while True:
            with self._cond:
                if self._abort:
                    for i, st in enumerate(self._slots):
                        if st is not None:
                            self._finish_slot(i, ServerClosedError(
                                "engine stopped mid-generation"))
                    return
                self._reap_queue_locked()
                admitted = self._admit_locked()
                if not admitted and not self.live_slots:
                    if self._closing and not self._queue:
                        return
                    # short cap keeps queued deadlines (and a pages-
                    # blocked head) honest while idle
                    self._cond.wait(0.05 if self._queue else None)
                    continue
            for slot, req in admitted:
                self._run_prefill(slot, req)
            self._reap_live()
            if self.live_slots:
                self._run_step()

    # -- device work ------------------------------------------------------
    def _run_prefill(self, slot: int, req: DecodeRequest):
        import jax.numpy as jnp

        st = self._slots[slot]
        try:
            t_pad = self._buckets.seq_bucket(len(req.prompt))
            tokens = np.zeros((t_pad,), np.int32)
            tokens[:len(req.prompt)] = req.prompt
            t0 = time.monotonic()
            with otrace.span("serving/decode_prefill", slot=slot,
                             bucket=t_pad):
                tok, last = self._exe.run_persistent(
                    self._prefill_fn(t_pad), _STATE_VARS,
                    args=(self.weights, jnp.asarray(tokens),
                          np.int32(len(req.prompt)),
                          jnp.asarray(self._cache.page_table[slot]),
                          st.base_key,
                          np.float32(req.temperature),
                          np.int32(req.top_k),
                          np.float32(req.top_p)),
                    scope=self._scope)
            stat_time("decode_prefill_seconds", time.monotonic() - t0)
            stat_add("decode_prefills")
            self._cache.lengths[slot] = len(req.prompt)
            if req.record_logits:
                req.logits_trace.append(np.asarray(last))
            self._deliver(slot, int(np.asarray(tok)))
        except Exception as e:  # noqa: BLE001 — fault isolation per req
            stat_add("decode_prefill_errors")
            self._finish_slot(slot, e)

    def _deliver(self, slot: int, token: int):
        """Account one sampled token for a live slot; finish + free the
        slot the moment its request is done."""
        st = self._slots[slot]
        now = time.monotonic()
        if st.n_generated > 0:
            stat_time("tpot_seconds", now - st.t_last)
        st.t_last = now
        st.n_generated += 1
        st.last_token = token
        self.tokens_total += 1
        stat_add("decode_tokens_total")
        st.req._emit(token)
        eos = self.config.eos_id
        if (eos is not None and token == eos) \
                or st.n_generated >= st.req.max_new_tokens:
            self._finish_slot(slot)

    def _run_step(self):
        import jax.numpy as jnp

        c = self._cache.config
        s = c.num_slots
        live_idx = [i for i, st in enumerate(self._slots)
                    if st is not None]
        if not live_idx:
            return
        tokens = np.zeros((s,), np.int32)
        positions = np.zeros((s,), np.int32)
        live = np.zeros((s,), bool)
        write_page = np.zeros((s,), np.int32)
        write_off = np.zeros((s,), np.int32)
        counters = np.zeros((s,), np.int32)
        temp = np.zeros((s,), np.float32)
        top_k = np.zeros((s,), np.int32)
        top_p = np.ones((s,), np.float32)
        base_keys = np.zeros((s, 2), np.uint32)
        for i in live_idx:
            st = self._slots[i]
            tokens[i] = st.last_token
            positions[i] = self._cache.lengths[i]
            live[i] = True
            write_page[i], write_off[i] = self._cache.write_coords(i)
            counters[i] = st.n_generated
            temp[i] = st.req.temperature
            top_k[i] = st.req.top_k
            top_p[i] = st.req.top_p
            base_keys[i] = np.asarray(st.base_key)
        t0 = time.monotonic()
        try:
            with otrace.span("serving/decode_step", live=len(live_idx)):
                nxt, logits = self._exe.run_persistent(
                    self._step_fn, _STATE_VARS,
                    args=(self.weights, jnp.asarray(tokens),
                          jnp.asarray(positions), jnp.asarray(live),
                          jnp.asarray(self._cache.page_table),
                          jnp.asarray(write_page),
                          jnp.asarray(write_off),
                          jnp.asarray(base_keys), jnp.asarray(counters),
                          jnp.asarray(temp), jnp.asarray(top_k),
                          jnp.asarray(top_p)),
                    scope=self._scope)
                nxt = np.asarray(nxt)  # THE per-step sync point
        except Exception as e:  # noqa: BLE001 — fail the batch loudly,
            # free every slot, keep the consumer thread alive
            stat_add("decode_step_errors")
            for i in live_idx:
                self._finish_slot(i, e)
            return
        stat_time("decode_step_seconds", time.monotonic() - t0)
        logits_np = None
        for i in live_idx:
            st = self._slots[i]
            self._cache.lengths[i] += 1
            if st.req.record_logits:
                if logits_np is None:
                    logits_np = np.asarray(logits)
                st.req.logits_trace.append(logits_np[i].copy())
            self._deliver(i, int(nxt[i]))
        occ = self.live_slots
        stat_set("decode_slot_occupancy", occ)
        stat_max("decode_slot_occupancy_max", len(live_idx))
        stat_add("decode_steps")

    # -- oracle / observability ------------------------------------------
    def recompute_logits(self, tokens: Sequence[int]) -> np.ndarray:
        """Full-recompute oracle: run the ENTIRE sequence through the
        prefill path from scratch (no cache reuse) and return the last
        position's logits.  Runs on THROWAWAY page pools — the prefill
        body only ever WRITES pages (its attention reads the locally
        built K/V, so fresh zero pools are numerically identical), and
        touching the live pools would race the engine thread's donating
        step.  Safe to call while the engine is serving.
        ``tests/test_decode_engine.py`` compares this bitwise against
        the streamed decode logits at every step."""
        import jax
        import jax.numpy as jnp

        tokens = [int(t) for t in tokens]
        t_pad = self._buckets.seq_bucket(len(tokens))
        arr = np.zeros((t_pad,), np.int32)
        arr[:len(tokens)] = tokens
        cc = self._cache.config
        shape = (cc.num_layers, cc.num_pages, cc.page_size, cc.num_heads,
                 cc.head_dim)
        scratch = (jnp.zeros(shape, cc.dtype), jnp.zeros(shape, cc.dtype))
        (tok, last), _ = self._prefill_fn(t_pad)(
            scratch, self.weights, jnp.asarray(arr),
            np.int32(len(tokens)),
            jnp.zeros((cc.pages_per_slot,), jnp.int32),
            jax.random.PRNGKey(0), np.float32(0.0), np.int32(0),
            np.float32(1.0))
        return np.asarray(last)

    def stats(self) -> dict:
        with self._cond:
            depth = len(self._queue)
        return {
            "name": self.name,
            "slots": self.config.slots,
            "live_slots": self.live_slots,
            "free_slots": self.free_slots,
            "queue_depth": depth,
            "tokens_total": self.tokens_total,
            "free_pages": self._cache.allocator.num_free,
            "num_pages": self._cache.config.num_pages,
            "cache_bytes": self._cache.config.cache_bytes(),
            "continuous": self._continuous,
        }
