"""KV-cache autoregressive decode engine with continuous batching,
prefix-cache page sharing, chunked prefill, and speculative decoding.

Role parity: the generative-serving half of Paddle Serving / the
reference's inference deployment story — the piece the PR-1 one-shot
bucket batcher cannot cover, because autoregressive decode re-enters
the model once PER TOKEN.  Recomputing the prefix every token is
O(len^2) per request; waiting for a shape bucket adds whole-batch
latency to every new arrival.  This engine is the TPU-native fix:

- **Persistent per-slot KV cache** (`kv_cache.py`): each of the
  ``slots`` concurrent requests owns paged key/value blocks inside two
  device-resident pool arrays.  The pools ride
  ``Executor.run_persistent`` with donation, so the cache NEVER
  round-trips to host between steps — per-token work is O(1) in the
  prefix length.
- **Prefix sharing** (``FLAGS_decode_prefix_cache``, default on): at
  millions of users most prompts open with the same system/template
  prefix.  Finished requests register their pages in an exact-content
  trie; admission shares every matched page into the new slot's table
  with a refcount bump — skipping both the HBM reservation AND the
  prefill compute for hit pages (an exactly-matched prompt skips
  prefill entirely: the first token comes out of the first decode
  step).  A borrowed partial tail page is copy-on-written at the first
  divergent token, from a spare reserved at admission so a decode step
  still can never die on cache exhaustion.
- **Chunked prefill** (``FLAGS_decode_prefill_chunk_pages``): a long
  prompt fills its pages across SEVERAL step boundaries (one chunk per
  engine-loop iteration) instead of stalling the whole slot batch on
  one long prefill dispatch — the slots already decoding keep emitting
  tokens, protecting ``ttft_ms_p99`` for everyone else.
- **Speculative decoding** (``FLAGS_decode_spec_k`` + a draft model):
  a small draft proposes k tokens in ONE device dispatch (its own page
  pools share the target's page ids, so prefix sharing and CoW cover
  it for free) and the target verifies all k+1 positions in ONE
  batched step.  Greedy output is BITWISE-identical to non-speculative
  decode: every emitted token is the target's own argmax, proposals
  only decide how many arrive per dispatch.
- **Continuous batching** (Orca's iteration-level scheduling): one
  jitted step decodes every live slot jointly; new requests claim free
  slots at step boundaries, and a slot whose request finishes — EOS,
  token budget, or deadline — frees IMMEDIATELY instead of padding to
  the longest neighbor.
- **Deadline reap mid-decode**: a lapsed deadline is honored at every
  step boundary (not just at dequeue), so a stalled client cannot pin
  a slot for the full max_new_tokens.
- **Streaming replies**: each sampled token is pushed to the request's
  stream the step it is produced — consume via the ``tokens()``
  generator or an ``on_token`` callback; ``result()`` blocks for the
  full sequence.
- **Deterministic sampling** (`ops/sampling_ops.py`): greedy / top-k /
  top-p run INSIDE the compiled step with an explicit per-request PRNG
  key (seed + fold_in(token index)), so a request's tokens are
  independent of slot assignment, batch composition, and replica —
  the property multi-replica scale-out (serving/server.py
  ``DecodeServer``) relies on.

Attention reads the page pool through
``ops/pallas_decode_attention.py``: the Pallas kernels on TPU (page
table as scalar-prefetch operands — one page DMA per grid step), the
pure-jnp gather+mask reference on CPU so tier-1 stays green.  Every
path — prefill, chunked prefill, decode, speculative verify — shares
ONE masked-softmax formulation at one width, which is what makes
decode-with-cache logits bitwise-equal to a full recompute
(`tests/test_decode_engine.py` + `tests/test_decode_prefix_spec.py`
pin it at every step on every path).

Observability: ``decode_*`` counters/gauges (``decode_cache_hit_rate``,
``decode_shared_pages``, ``decode_cow_copies``, ``spec_accept_rate``,
``prefill_chunks``, ...) plus ``ttft_seconds`` / ``tpot_seconds`` /
``decode_step_seconds`` histograms — all on ``/metrics`` wherever a
fleet KV HTTP server runs.
"""
from __future__ import annotations

import collections
import math
import queue as _queue
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..monitor import stat_add, stat_get, stat_max, stat_set
from ..observe import tracer as otrace
from ..observe.histogram import stat_time
from .batcher import _UNSET, RequestBase
from .buckets import (BucketSpec, DeadlineExceededError, QueueFullError,
                      RequestTooLargeError, ServerClosedError,
                      prefill_bucket_grid, record_pad_waste)
from . import kv_cache
from .kv_cache import (CacheConfig, PagedKVCache, K_PAGES_VAR,
                       V_PAGES_VAR, K_SCALES_VAR, V_SCALES_VAR)

DRAFT_K_PAGES_VAR = "__decode_draft_k_pages__"
DRAFT_V_PAGES_VAR = "__decode_draft_v_pages__"
DRAFT_K_SCALES_VAR = "__decode_draft_k_scales__"
DRAFT_V_SCALES_VAR = "__decode_draft_v_scales__"

# the target-model state tuple comes from PagedKVCache.state_var_names()
# (page pools + scale pools when quantized); only the draft tuple is
# assembled here
_DRAFT_VARS = (DRAFT_K_PAGES_VAR, DRAFT_V_PAGES_VAR)
_DONE = object()  # stream sentinel


def _split_state(state, quantized):
    """Persistent-state tuple -> (k_pages, v_pages, k_scales,
    v_scales); the scale pools exist only under FLAGS_decode_kv_quant."""
    if quantized:
        kp, vp, ks, vs = state
        return kp, vp, ks, vs
    kp, vp = state
    return kp, vp, None, None


def _join_state(kp, vp, ks, vs, quantized):
    return (kp, vp, ks, vs) if quantized else (kp, vp)


# ---------------------------------------------------------------------------
# model


class TransformerLM:
    """A decoder-only transformer sized by constructor args — the
    engine's reference model (bench, tests, demos).  Any model works
    with the engine if it exposes this class's surface: ``num_layers``
    / ``num_heads`` / ``head_dim`` / ``vocab_size`` plus the pure
    per-row pieces below, which prefill and decode COMPOSE IDENTICALLY
    so cached decode stays bitwise-comparable to a full recompute
    (layer norm, QKV/out projections, MLP are all row-independent)."""

    def __init__(self, vocab_size: int, d_model: int = 64,
                 num_layers: int = 2, num_heads: int = 2,
                 ffn_dim: Optional[int] = None, max_seq_len: int = 256,
                 moe_experts: int = 0, moe_top_k: int = 2,
                 moe_capacity_factor: float = 0.0, moe_mesh=None):
        self.vocab_size = int(vocab_size)
        self.d_model = int(d_model)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        if d_model % num_heads:
            raise ValueError("d_model must divide by num_heads")
        self.head_dim = self.d_model // self.num_heads
        self.ffn_dim = int(ffn_dim) if ffn_dim else 4 * self.d_model
        self.max_seq_len = int(max_seq_len)
        # MoE FFN (ops/moe_ops.moe_ffn_ref): moe_experts > 0 replaces
        # the dense MLP with a top-k routed expert FFN.  The default
        # capacity factor 0.0 means DROPLESS (cap = E/K * S*K/E = S):
        # with no drops the routed output is row-independent
        # MATHEMATICALLY, so cached decode agrees with a prefill
        # recompute to float tolerance — but not bitwise: the dispatch
        # buffer's capacity tracks the row count, and XLA's reduction
        # strategy is shape-dependent (~1 ulp).  A finite factor
        # additionally reintroduces batch-dependent drops (fine for
        # training, wrong for the serving oracle).
        # ``moe_mesh`` with an 'ep' axis turns on expert-parallel
        # decode: the stacked expert weights live P('ep', ...) and the
        # dispatch/combine all-to-alls materialize around the FFN.
        self.moe_experts = int(moe_experts)
        self.moe_top_k = int(moe_top_k)
        self.moe_capacity_factor = float(moe_capacity_factor)
        self.moe_mesh = moe_mesh
        if self.moe_experts:
            if self.moe_top_k > self.moe_experts:
                raise ValueError(
                    f"moe_top_k={moe_top_k} exceeds "
                    f"moe_experts={moe_experts}")
            if moe_mesh is not None and "ep" not in getattr(
                    moe_mesh, "axis_names", ()):
                raise ValueError(
                    "moe_mesh needs an 'ep' axis for expert-parallel "
                    "decode; build one with init_parallel_env("
                    "mesh_shape=(dp, ep), axis_names=('dp', 'ep'))")

    def init_weights(self, key):
        import jax
        import jax.numpy as jnp

        dm, f, v = self.d_model, self.ffn_dim, self.vocab_size
        n_per_layer = 7 if self.moe_experts else 6
        keys = jax.random.split(key, 3 + self.num_layers * n_per_layer)

        def dense(k, shape, scale=None):
            scale = scale if scale is not None else 1.0 / math.sqrt(shape[0])
            return (jax.random.normal(k, shape) * scale).astype(jnp.float32)

        w = {
            "tok_emb": dense(keys[0], (v, dm), 0.02),
            "pos_emb": dense(keys[1], (self.max_seq_len, dm), 0.02),
            "lm_head": dense(keys[2], (dm, v)),
            "lnf_g": jnp.ones((dm,), jnp.float32),
            "lnf_b": jnp.zeros((dm,), jnp.float32),
            "layers": [],
        }
        for i in range(self.num_layers):
            k = keys[3 + i * n_per_layer: 3 + (i + 1) * n_per_layer]
            lw = {
                "ln1_g": jnp.ones((dm,), jnp.float32),
                "ln1_b": jnp.zeros((dm,), jnp.float32),
                "wq": dense(k[0], (dm, dm)),
                "wk": dense(k[1], (dm, dm)),
                "wv": dense(k[2], (dm, dm)),
                "wo": dense(k[3], (dm, dm)),
                "ln2_g": jnp.ones((dm,), jnp.float32),
                "ln2_b": jnp.zeros((dm,), jnp.float32),
            }
            if self.moe_experts:
                e = self.moe_experts
                lw["gate"] = dense(k[4], (dm, e), 0.02)
                lw["moe_w1"] = dense(k[5], (e, dm, f))
                lw["moe_b1"] = jnp.zeros((e, f), jnp.float32)
                lw["moe_w2"] = dense(k[6], (e, f, dm),
                                     1.0 / math.sqrt(f))
                lw["moe_b2"] = jnp.zeros((e, dm), jnp.float32)
            else:
                lw["w1"] = dense(k[4], (dm, f))
                lw["w2"] = dense(k[5], (f, dm))
            w["layers"].append(lw)
        return w

    # -- pure per-row pieces (shared verbatim by prefill and decode) ------
    @staticmethod
    def _ln(x, g, b):
        import jax.numpy as jnp

        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * g + b

    def _embed(self, w, tokens, positions):
        return w["tok_emb"][tokens] + w["pos_emb"][positions]

    def _qkv(self, lw, h):
        n, d = self.num_heads, self.head_dim
        q = (h @ lw["wq"]).reshape(*h.shape[:-1], n, d)
        k = (h @ lw["wk"]).reshape(*h.shape[:-1], n, d)
        v = (h @ lw["wv"]).reshape(*h.shape[:-1], n, d)
        return q, k, v

    def _attn_out(self, lw, ctx):
        return ctx.reshape(*ctx.shape[:-2], self.d_model) @ lw["wo"]

    def _mlp(self, lw, h):
        import jax

        if self.moe_experts:
            return self._moe_mlp(lw, h)
        return jax.nn.gelu(h @ lw["w1"]) @ lw["w2"]

    def _moe_mlp(self, lw, h):
        """Routed expert FFN, dropless by default (see __init__).
        Quantized expert carriers (``quantize_moe_weights``) dequantize
        per expert at the einsum's doorstep; a ``moe_mesh`` with an
        'ep' axis adds the GSPMD constraints that make the dispatch and
        combine all-to-alls real."""
        from ..ops.moe_ops import _dequant_stacked, moe_ffn_ref

        if "moe_w1_q" in lw:
            w1 = _dequant_stacked(lw["moe_w1_q"], lw["moe_w1_scale"])
            w2 = _dequant_stacked(lw["moe_w2_q"], lw["moe_w2_scale"])
        else:
            w1, w2 = lw["moe_w1"], lw["moe_w2"]
        cf = self.moe_capacity_factor or (
            self.moe_experts / self.moe_top_k)
        out, _aux, _load, _chunked = moe_ffn_ref(
            h, lw["gate"], w1, lw["moe_b1"], w2, lw["moe_b2"],
            num_experts=self.moe_experts, top_k=self.moe_top_k,
            capacity_factor=cf, mesh=self.moe_mesh,
            ep=self.moe_mesh is not None)
        return out.astype(h.dtype)

    def _head(self, w, x):
        return self._ln(x, w["lnf_g"], w["lnf_b"]) @ w["lm_head"]


def quantize_moe_weights(weights, mode: str = "int8"):
    """Post-training quantization of a TransformerLM weight dict's
    stacked expert tensors — the serving twin of the
    PostTrainingWeightQuantPass moe_ffn branch (slim/quantization.py):
    every layer's ``moe_w1``/``moe_w2`` becomes an int8 (or fp8)
    carrier plus a per-expert ``[E, out]`` scale
    (ops/quant_ops.quantize_weight_stacked), which ``_moe_mlp``
    dequantizes at the expert einsum's doorstep.  Gate, biases, and
    everything dense stay full precision (they're a rounding error of
    the byte footprint).  Returns a NEW dict; the original is
    untouched (it stays the full-precision oracle)."""
    from ..ops.quant_ops import quantize_weight_stacked

    out = dict(weights)
    layers = []
    n_quantized = 0
    for lw in weights["layers"]:
        lw = dict(lw)
        if "moe_w1" in lw:
            for nm in ("moe_w1", "moe_w2"):
                q, s = quantize_weight_stacked(lw.pop(nm), 2, mode)
                lw[nm + "_q"] = q
                lw[nm + "_scale"] = s
                n_quantized += 1
        layers.append(lw)
    if not n_quantized:
        raise ValueError(
            "quantize_moe_weights found no stacked expert weights; "
            "build the model with moe_experts > 0")
    out["layers"] = layers
    stat_add("serving_moe_weights_quantized", n_quantized)
    return out


def shard_moe_weights(weights, mesh):
    """Place a TransformerLM weight dict's stacked expert tensors (raw
    or quantized carriers+scales alike) ``P('ep', ...)`` on ``mesh`` so
    each chip holds only its 1/ep slice of the experts — the serving
    counterpart of the ShardingPropagationPass 'ep' seed.  Everything
    else replicates.  Returns a NEW dict of device-resident arrays."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if "ep" not in getattr(mesh, "axis_names", ()):
        raise ValueError(
            "shard_moe_weights needs a mesh with an 'ep' axis; build "
            "one with init_parallel_env(mesh_shape=(dp, ep), "
            "axis_names=('dp', 'ep'))")
    ep = int(mesh.shape["ep"])

    def put(val, spec):
        return jax.device_put(val, NamedSharding(mesh, spec))

    rep = PartitionSpec()
    out = {k: put(v, rep) for k, v in weights.items() if k != "layers"}
    layers = []
    for lw in weights["layers"]:
        placed = {}
        for nm, val in lw.items():
            stacked = nm.startswith("moe_w") and val.ndim >= 2 \
                or nm in ("moe_b1", "moe_b2")
            if stacked and int(val.shape[0]) % ep == 0:
                placed[nm] = put(val, PartitionSpec(
                    "ep", *([None] * (val.ndim - 1))))
            else:
                placed[nm] = put(val, rep)
        layers.append(placed)
    out["layers"] = layers
    return out


# ---------------------------------------------------------------------------
# requests


class DecodeRequest(RequestBase):
    """Streaming future for one generation request.

    Tokens arrive on an internal stream as the engine produces them:
    iterate ``tokens()`` for a generator, pass ``on_token=`` for a
    callback (called from the engine thread — keep it cheap), or call
    ``result()`` for the completed id list.  ``generated`` always
    holds the ids produced so far (partial output survives a deadline
    reap)."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "top_k",
                 "top_p", "seed", "on_token", "generated", "_stream",
                 "t_first_token", "t_last_token", "record_logits",
                 "logits_trace", "speculative", "finish_reason",
                 "extract_kv", "kv_import", "kv_export")

    _deadline_stat = "decode_deadline_exceeded"
    _outcome_prefix = "decode"

    def __init__(self, prompt, max_new_tokens, deadline, temperature,
                 top_k, top_p, seed, on_token, record_logits=False,
                 speculative=None, extract_kv=False, kv_import=None):
        super().__init__(deadline)
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        self.on_token = on_token
        self.generated: List[int] = []
        self._stream: _queue.Queue = _queue.Queue()
        self.t_first_token: Optional[float] = None
        self.t_last_token: Optional[float] = None
        self.record_logits = bool(record_logits)
        self.logits_trace: List[np.ndarray] = []
        self.speculative = speculative  # None=auto, False=opt out
        self.finish_reason: Optional[str] = None
        # disaggregated serving (serving/disagg.py): an extract_kv
        # request is the INTERNAL prefill leg — on success its slot's
        # prompt pages are gathered into ``kv_export`` (a
        # kv_cache.KVPageExport) before release, and it is exempt from
        # the client-facing SLO plane (ttft histogram + goodput/burn
        # accounting) because the logical request's first token is the
        # decode replica's.  ``kv_import`` carries such a payload INTO
        # an engine: admission installs the pages and starts at the
        # first decode step instead of prefilling.
        self.extract_kv = bool(extract_kv)
        self.kv_import = kv_import
        self.kv_export = None

    # terminal accounting (RequestBase._on_terminal hooks) ---------------
    def _finish_stats(self, outcome, latency):
        # unlike the batcher, decode had NO terminal-latency series at
        # all — record it for EVERY outcome (submit-time rejections
        # observe it separately in DecodeEngine.submit) so error-rate
        # denominators cover deadline/abandon/reject alike
        stat_time("decode_request_latency_seconds", latency)

    def _summary(self, outcome, latency):
        n = len(self.generated)
        ttft = None if self.t_first_token is None \
            else self.t_first_token - self.t_enqueue
        tpot = None
        if n >= 2 and self.t_last_token is not None \
                and self.t_first_token is not None:
            # per-request MEAN time-per-output-token (what the tpot_p50
            # SLO objective judges)
            tpot = (self.t_last_token - self.t_first_token) / (n - 1)
        return {
            "outcome": outcome,
            "latency_s": round(latency, 6),
            "ttft_s": None if ttft is None else round(ttft, 6),
            "tpot_s": None if tpot is None else round(tpot, 6),
            "n_tokens": n,
            "prompt_len": len(self.prompt),
            "reason": self.finish_reason,
        }

    def _slo_check(self, summary):
        if self.extract_kv:
            # internal disagg prefill leg: the logical request is
            # observed once, by its decode-side request — feeding this
            # half too would double-count every disagg request in
            # goodput/burn
            return ()
        from ..observe import slo as _slo

        return _slo.observe_request(summary)

    # engine side ---------------------------------------------------------
    def _emit(self, token: int) -> None:
        now = time.monotonic()
        if self.t_first_token is None:
            self.t_first_token = now
            if not self.extract_kv:
                stat_time("ttft_seconds",
                          self.t_first_token - self.t_enqueue)
        self.t_last_token = now
        self.generated.append(int(token))
        self._stream.put(int(token))
        if self.on_token is not None:
            try:
                self.on_token(int(token))
            except Exception:  # noqa: BLE001 — user callback, isolate
                stat_add("decode_callback_errors")

    def _finish(self, error=None) -> bool:
        won = self._complete(result=list(self.generated), error=error)
        self._stream.put(_DONE)  # always: a racing client-side reap
        # must still terminate a tokens() reader
        return won

    # client side ---------------------------------------------------------
    def tokens(self, timeout: Optional[float] = None):
        """Generator over streamed token ids; raises the request's
        error (after yielding everything produced) if it failed."""
        while True:
            budget = timeout
            if self.deadline is not None:
                # the engine reaps at the next step boundary; the small
                # grace covers its in-flight step
                rem = max(self.deadline - time.monotonic(), 0.0) + 1.0
                budget = rem if budget is None else min(budget, rem)
            try:
                item = self._stream.get(timeout=budget)
            except _queue.Empty:
                raise TimeoutError(
                    "no token within the wait budget") from None
            if item is _DONE:
                break
            yield item
        if self._error is not None:
            raise self._error


class _SlotState:
    __slots__ = ("req", "base_key", "n_generated", "last_token", "t_last",
                 "phase", "prefill_pos", "write_trash_once", "spec",
                 "draft_lag", "chunks", "t_admit")

    def __init__(self, req, base_key):
        self.req = req
        self.base_key = base_key
        self.n_generated = 0
        self.last_token = 0
        self.t_last = time.monotonic()
        self.t_admit = self.t_last
        self.chunks = 0             # prefill chunks dispatched
        self.phase = "prefill"      # "prefill" -> "decode"
        self.prefill_pos = 0        # next prompt position to prefill
        self.write_trash_once = False  # cache-hit path: first decode
        # write re-derives a position the shared pages already hold
        self.spec = False           # speculative-decode eligible
        self.draft_lag = 0          # trailing positions written by the
        # normal step (target-only) on a spec slot — the draft pool is
        # stale there, so registration excludes them


# ---------------------------------------------------------------------------
# engine


class DecodeConfig:
    """Engine knobs; defaults come from the ``FLAGS_decode_*`` flags."""

    def __init__(self, slots: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 max_queue: int = 256,
                 default_deadline_ms: Optional[float] = None,
                 use_pallas: str = "auto",
                 interpret: bool = False,
                 cache_dtype="float32",
                 prefix_cache: Optional[bool] = None,
                 prefill_chunk_pages: Optional[int] = None,
                 ragged_prefill_rows: Optional[int] = None,
                 spec_k: Optional[int] = None,
                 kv_quant: Optional[bool] = None):
        from ..framework import flags

        self.slots = int(slots if slots is not None
                         else flags.flag("decode_slots"))
        self.max_seq_len = int(max_seq_len if max_seq_len is not None
                               else flags.flag("decode_max_seq_len"))
        self.page_size = int(page_size if page_size is not None
                             else flags.flag("decode_page_size"))
        self.num_pages = num_pages
        self.max_new_tokens = int(
            max_new_tokens if max_new_tokens is not None
            else flags.flag("decode_max_new_tokens"))
        self.eos_id = eos_id
        self.max_queue = int(max_queue)
        self.default_deadline_ms = default_deadline_ms
        self.use_pallas = use_pallas
        self.interpret = bool(interpret)
        self.cache_dtype = cache_dtype
        self.prefix_cache = bool(
            prefix_cache if prefix_cache is not None
            else flags.flag("decode_prefix_cache"))
        self.prefill_chunk_pages = int(
            prefill_chunk_pages if prefill_chunk_pages is not None
            else flags.flag("decode_prefill_chunk_pages"))
        self.ragged_prefill_rows = int(
            ragged_prefill_rows if ragged_prefill_rows is not None
            else flags.flag("decode_ragged_prefill"))
        self.spec_k = int(spec_k if spec_k is not None
                          else flags.flag("decode_spec_k"))
        self.kv_quant = bool(kv_quant if kv_quant is not None
                             else flags.flag("decode_kv_quant"))


class DecodeEngine:
    """One decode replica: a slot batch, its paged KV cache, and the
    consumer thread that runs admission -> prefill -> joint decode
    step, forever.  ``continuous=False`` degrades admission to the
    one-shot group mode (a new group only starts when EVERY slot is
    free) — the static-batching baseline bench.py's A/B uses.

    ``draft_model``/``draft_weights`` arm speculative decoding (with
    ``spec_k > 0``): the draft's page pools are indexed by the SAME
    page ids as the target's, so prefix sharing, reservation
    accounting, and copy-on-write cover both for free."""

    def __init__(self, model, weights, config: Optional[DecodeConfig] = None,
                 place=None, name: str = "replica-0", continuous: bool = True,
                 draft_model=None, draft_weights=None):
        import jax
        import jax.numpy as jnp

        from ..framework.executor import Executor
        from ..framework.scope import Scope

        self.model = model
        self.config = config or DecodeConfig()
        self.name = name
        self._continuous = bool(continuous)
        c = self.config
        if c.max_seq_len > model.max_seq_len:
            raise ValueError(
                f"DecodeConfig.max_seq_len {c.max_seq_len} exceeds the "
                f"model's positional table ({model.max_seq_len})")
        self._draft_model = draft_model
        if draft_model is not None:
            if draft_weights is None:
                raise ValueError(
                    "draft_model needs draft_weights for speculative "
                    "decoding")
            if int(draft_model.vocab_size) != int(model.vocab_size):
                raise ValueError(
                    f"speculative draft/target vocab mismatch: draft "
                    f"{draft_model.vocab_size} vs target "
                    f"{model.vocab_size} — the draft's proposals would "
                    f"index a different token space; re-export the "
                    f"draft with the target's vocabulary")
            if int(draft_model.max_seq_len) < c.max_seq_len:
                raise ValueError(
                    f"draft positional table ({draft_model.max_seq_len})"
                    f" is shorter than max_seq_len ({c.max_seq_len})")
        self._scope = Scope()
        self._exe = Executor(place)
        self._cache = PagedKVCache(
            CacheConfig(model.num_layers, model.num_heads, model.head_dim,
                        c.slots, c.max_seq_len, c.page_size,
                        num_pages=c.num_pages, dtype=c.cache_dtype,
                        quantized=c.kv_quant),
            self._scope, prefix_cache=c.prefix_cache)
        # per-request timeline hook: claim/CoW/register/evict events
        # from the cache land on the owning request's trace
        self._cache.on_event = self._on_cache_event
        self._admitting = None  # request whose claim() is in flight
        self.weights = jax.tree_util.tree_map(jax.numpy.asarray, weights)
        # persistent-state tuples every jitted step threads (the scale
        # pools join them under FLAGS_decode_kv_quant)
        self._state_vars = self._cache.state_var_names()
        self._draft_state_vars = ()
        if draft_model is not None:
            self.draft_weights = jax.tree_util.tree_map(
                jax.numpy.asarray, draft_weights)
            cc = self._cache.config
            dshape = (draft_model.num_layers, cc.num_pages, cc.page_size,
                      draft_model.num_heads, draft_model.head_dim)
            self._scope.set_var(DRAFT_K_PAGES_VAR,
                                jnp.zeros(dshape, cc.store_dtype))
            self._scope.set_var(DRAFT_V_PAGES_VAR,
                                jnp.zeros(dshape, cc.store_dtype))
            self._draft_state_vars = _DRAFT_VARS
            if cc.quantized:
                dsshape = (draft_model.num_layers, cc.num_pages,
                           cc.page_size, draft_model.num_heads)
                for nm in (DRAFT_K_SCALES_VAR, DRAFT_V_SCALES_VAR):
                    self._scope.set_var(
                        nm, jnp.full(dsshape, kv_cache.SCALE_EPS,
                                     cc.scale_dtype))
                self._draft_state_vars = _DRAFT_VARS + (
                    DRAFT_K_SCALES_VAR, DRAFT_V_SCALES_VAR)
                # freed-page scale resets + the debug_check audit must
                # cover the draft pools too (same page ids)
                self._cache.scale_vars += [DRAFT_K_SCALES_VAR,
                                           DRAFT_V_SCALES_VAR]
        self._buckets = BucketSpec(
            (1,), prefill_bucket_grid(c.max_seq_len, c.page_size))
        self._step_fn = self._build_step_fn(model)
        self._prefill_fns = {}   # (t_pad, which, qz) -> jitted prefill
        self._rows_fns = {}      # (rows, slots, which) -> jitted multirow
        self._propose_fn = None  # draft k-token burst (lazy)
        self._cow_fn = None      # page copy across every pool (lazy)
        self._cow_state = self._state_vars + self._draft_state_vars
        self._slots: List[Optional[_SlotState]] = [None] * c.slots
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._closing = False
        self._abort = False
        self._thread = None
        self._seq = 0  # default-seed counter
        self._prefill_rr = 0  # chunked-prefill round-robin cursor
        self.tokens_total = 0
        # per-replica tentpole accounting (stats()/DecodeServer /stats)
        self._hit_pages = 0
        self._prompt_pages = 0
        self._cow_copies = 0
        self._prefill_chunk_count = 0
        self._spec_proposed = 0
        self._spec_accepted = 0

    @property
    def spec_enabled(self) -> bool:
        return self._draft_model is not None and self.config.spec_k > 0

    # -- per-request tracing helpers -------------------------------------
    @staticmethod
    def _tev(req, name, **attrs) -> None:
        tr = req.trace
        if tr is not None:
            tr.event(name, **attrs)

    def _on_cache_event(self, slot, name, **attrs):
        """PagedKVCache event hook: attribute cache lifecycle events
        (claim / cow_swap / evict / register) to the owning request's
        timeline.  During admission the slot state does not exist yet,
        so the claim-in-flight request is the fallback owner (evictions
        triggered by its allocation ARE its wait)."""
        st = self._slots[slot] if slot is not None \
            and 0 <= slot < len(self._slots) else None
        req = st.req if st is not None else self._admitting
        if req is not None:
            self._tev(req, f"cache/{name}",
                      **({"slot": slot} if slot is not None else {}),
                      **attrs)

    # -- jitted step builders --------------------------------------------
    def _attend(self, q, k_pages, v_pages, k_scales, v_scales, layer,
                page_table, lengths):
        from ..ops.pallas_decode_attention import paged_decode_attention

        # all backend dispatch (auto/always/never, Pallas vs the
        # gather+mask reference) lives in ONE place: the op itself —
        # including the quantized dequant-inline paths
        return paged_decode_attention(
            q, k_pages[layer], v_pages[layer], page_table, lengths,
            use_pallas=self.config.use_pallas,
            interpret=self.config.interpret,
            k_scales=None if k_scales is None else k_scales[layer],
            v_scales=None if v_scales is None else v_scales[layer])

    def _token_step_body(self, model, weights, k_pages, v_pages,
                         k_scales, v_scales, tokens, positions,
                         page_table, write_page, write_off):
        """One single-token step of ``model`` over the page pools:
        embed -> per-layer (write K/V at (write_page, write_off),
        attend over the slot's live history) -> logits.  Shared
        VERBATIM by the target step and the draft proposal burst so
        both read the cache through the one formulation.  Quantized
        pools (scales not None) write int8 + per-position scales and
        attention dequantizes inline."""
        x = model._embed(weights, tokens, positions)       # [S, Dm]
        lengths = positions + 1  # the token written THIS step included
        for l in range(model.num_layers):
            lw = weights["layers"][l]
            h = model._ln(x, lw["ln1_g"], lw["ln1_b"])
            q, k, v = model._qkv(lw, h)                    # [S, H, D]
            k_pages, k_scales = kv_cache.write_token_layer(
                k_pages, k_scales, l, k, write_page, write_off)
            v_pages, v_scales = kv_cache.write_token_layer(
                v_pages, v_scales, l, v, write_page, write_off)
            ctx = self._attend(q, k_pages, v_pages, k_scales, v_scales,
                               l, page_table, lengths)
            x = x + model._attn_out(lw, ctx)
            x = x + model._mlp(
                lw, model._ln(x, lw["ln2_g"], lw["ln2_b"]))
        logits = model._head(weights, x)                   # [S, V]
        return logits, k_pages, v_pages, k_scales, v_scales

    def _build_step_fn(self, model):
        import jax
        import jax.numpy as jnp

        from ..ops.sampling_ops import sample_tokens

        qz = self.config.kv_quant

        def step(state, weights, tokens, positions, live, page_table,
                 write_page, write_off, base_keys, counters, temp, top_k,
                 top_p):
            kp, vp, ks, vs = _split_state(state, qz)
            logits, kp, vp, ks, vs = self._token_step_body(
                model, weights, kp, vp, ks, vs, tokens, positions,
                page_table, write_page, write_off)
            keys = jax.vmap(jax.random.fold_in)(base_keys, counters)
            nxt = sample_tokens(keys, logits, temp, top_k, top_p)
            nxt = jnp.where(live, nxt, 0)
            return (nxt, logits), _join_state(kp, vp, ks, vs, qz)

        return jax.jit(step, donate_argnums=(0,))

    def _build_prefill_fn(self, t_pad: int, model,
                          quantized: Optional[bool] = None):
        import jax
        import jax.numpy as jnp

        from ..ops.pallas_decode_attention import \
            decode_attention_reference
        from ..ops.sampling_ops import sample_tokens

        cc = self._cache.config
        t_max = cc.max_seq_len
        n_bp = t_pad // cc.page_size
        cdt = cc.dtype
        qz = cc.quantized if quantized is None else bool(quantized)

        def prefill(state, weights, tokens, length, pages, base_key,
                    temp, top_k, top_p):
            k_pages, v_pages, k_scales, v_scales = _split_state(state, qz)
            positions = jnp.arange(t_pad, dtype=jnp.int32)
            x = model._embed(weights, tokens, positions)    # [T_pad, Dm]
            row_lengths = positions + 1
            for l in range(model.num_layers):
                lw = weights["layers"][l]
                h = model._ln(x, lw["ln1_g"], lw["ln1_b"])
                q, k, v = model._qkv(lw, h)                 # [T_pad, H, D]
                k_pages, k_scales = kv_cache.write_prompt_layer(
                    k_pages, k_scales, l, k, pages[:n_bp])
                v_pages, v_scales = kv_cache.write_prompt_layer(
                    v_pages, v_scales, l, v, pages[:n_bp])
                # attention at FULL cache width through the SAME cache
                # representation the pages store — each row's numerics
                # are the ones decode will reproduce from the pages,
                # which is the bitwise prefix-cache contract.  In
                # quantized mode that representation is the local
                # quant-dequant round trip (identical bytes to what
                # write_prompt_layer just stored).
                if qz:
                    kq, ksc = kv_cache.quantize_kv(k)
                    vq, vsc = kv_cache.quantize_kv(v)
                    kl = kv_cache.dequantize_kv(kq, ksc, cdt)
                    vl = kv_cache.dequantize_kv(vq, vsc, cdt)
                else:
                    kl, vl = k.astype(cdt), v.astype(cdt)
                shape = (t_max, model.num_heads, model.head_dim)
                kf = jnp.zeros(shape, cdt).at[:t_pad].set(kl)
                vf = jnp.zeros(shape, cdt).at[:t_pad].set(vl)
                ctx = decode_attention_reference(
                    q, jnp.broadcast_to(kf[None], (t_pad,) + shape),
                    jnp.broadcast_to(vf[None], (t_pad,) + shape),
                    row_lengths)
                x = x + model._attn_out(lw, ctx)
                x = x + model._mlp(
                    lw, model._ln(x, lw["ln2_g"], lw["ln2_b"]))
            logits = model._head(weights, x)                # [T_pad, V]
            last = jax.lax.dynamic_index_in_dim(
                logits, length - 1, 0, keepdims=False)
            key0 = jax.random.fold_in(base_key, 0)
            tok = sample_tokens(key0[None], last[None], temp[None],
                                top_k[None], top_p[None])[0]
            return (tok, last), _join_state(k_pages, v_pages, k_scales,
                                            v_scales, qz)

        return jax.jit(prefill, donate_argnums=(0,))

    def _build_rows_fn(self, n_rows: int, n_slots: int, model):
        """Multi-row step: R query rows per slot written at explicit
        (page, offset) coords, attending over the slot's page table
        with per-row causal lengths.  ONE executable family serves
        chunked/suffix prefill (S=1, R=chunk rows) AND speculative
        verification (S=slots, R=spec_k+1): both are 'rows of a
        sequence extended through the cache', which is what keeps
        their logits bitwise-equal to the decode step and the
        full-recompute oracle."""
        import jax
        import jax.numpy as jnp

        from ..ops.pallas_decode_attention import paged_chunk_attention
        from ..ops.sampling_ops import greedy_sample, sample_tokens

        R, S = n_rows, n_slots
        qz = self._cache.config.quantized

        def rows_fn(state, weights, tokens, start, last_row, page_table,
                    write_page, write_off, base_keys, counters, temp,
                    top_k, top_p):
            k_pages, v_pages, k_scales, v_scales = _split_state(state, qz)
            positions = start[:, None] \
                + jnp.arange(R, dtype=jnp.int32)[None, :]   # [S, R]
            # clip keeps padded/dead rows inside the positional table;
            # live rows are in range by the reservation accounting
            pos_c = jnp.clip(positions, 0, model.max_seq_len - 1)
            x = model._embed(weights, tokens, pos_c)        # [S, R, Dm]
            row_lengths = positions + 1
            for l in range(model.num_layers):
                lw = weights["layers"][l]
                h = model._ln(x, lw["ln1_g"], lw["ln1_b"])
                q, k, v = model._qkv(lw, h)                 # [S, R, H, D]
                flat = (S * R, model.num_heads, model.head_dim)
                k_pages, k_scales = kv_cache.write_token_layer(
                    k_pages, k_scales, l, k.reshape(flat),
                    write_page.reshape(-1), write_off.reshape(-1))
                v_pages, v_scales = kv_cache.write_token_layer(
                    v_pages, v_scales, l, v.reshape(flat),
                    write_page.reshape(-1), write_off.reshape(-1))
                ctx = paged_chunk_attention(
                    q, k_pages[l], v_pages[l], page_table, row_lengths,
                    use_pallas=self.config.use_pallas,
                    interpret=self.config.interpret,
                    k_scales=None if k_scales is None else k_scales[l],
                    v_scales=None if v_scales is None else v_scales[l])
                x = x + model._attn_out(lw, ctx)
                x = x + model._mlp(
                    lw, model._ln(x, lw["ln2_g"], lw["ln2_b"]))
            logits = model._head(weights, x)                # [S, R, V]
            greedy = greedy_sample(logits)                  # [S, R]
            last = jnp.take_along_axis(
                logits, last_row[:, None, None], axis=1)[:, 0]  # [S, V]
            keys = jax.vmap(jax.random.fold_in)(base_keys, counters)
            tok = sample_tokens(keys, last, temp, top_k, top_p)
            return (tok, greedy, logits), _join_state(
                k_pages, v_pages, k_scales, v_scales, qz)

        return jax.jit(rows_fn, donate_argnums=(0,))

    def _build_propose_fn(self, k_steps: int):
        """Draft proposal burst: k_steps+1 sequential draft-model steps
        in ONE dispatch (the +1 keeps the draft's own cache synced
        through the bonus position when every proposal is accepted).
        Write coords come from the page table in-fn; dead slots and
        out-of-range positions aim at the trash page."""
        import jax
        import jax.numpy as jnp

        from ..ops.sampling_ops import greedy_sample

        model = self._draft_model
        cc = self._cache.config
        p = cc.page_size
        pps = cc.pages_per_slot
        qz = cc.quantized

        def propose(state, weights, tok0, start, live, trash_first,
                    page_table):
            dk, dv, dks, dvs = _split_state(state, qz)
            cur = tok0
            props = []
            for j in range(k_steps + 1):
                pos = start + j                              # [S]
                idx = jnp.clip(pos // p, 0, pps - 1)
                pid = jnp.take_along_axis(
                    page_table, idx[:, None], axis=1)[:, 0]
                pid = jnp.where(live & (pos < cc.max_seq_len), pid, 0)
                if j == 0:
                    pid = jnp.where(trash_first, 0, pid)
                off = pos % p
                logits, dk, dv, dks, dvs = self._token_step_body(
                    model, weights, dk, dv, dks, dvs, cur,
                    jnp.clip(pos, 0, model.max_seq_len - 1),
                    page_table, pid, off)
                cur = greedy_sample(logits)                  # [S]
                props.append(cur)
            return (jnp.stack(props, axis=1),), _join_state(
                dk, dv, dks, dvs, qz)

        return jax.jit(propose, donate_argnums=(0,))

    def _build_cow_fn(self):
        """Copy page ``src`` onto page ``dst`` across EVERY pool (all
        layers; target K/V + draft K/V when present) — the device half
        of copy-on-write."""
        import jax

        def cow(state, src, dst):
            return ((), tuple(pool.at[:, dst].set(pool[:, src])
                              for pool in state))

        return jax.jit(cow, donate_argnums=(0,))

    def _prefill_fn(self, t_pad: int, which: str = "target",
                    quantized: Optional[bool] = None):
        qz = self._cache.config.quantized if quantized is None \
            else bool(quantized)
        key = (t_pad, which, qz)
        fn = self._prefill_fns.get(key)
        if fn is None:
            model = self.model if which == "target" else self._draft_model
            fn = self._prefill_fns[key] = self._build_prefill_fn(
                t_pad, model, quantized=qz)
            stat_add("decode_prefill_compiles")
        return fn

    def _rows_fn(self, n_rows: int, n_slots: int, which: str = "target"):
        key = (n_rows, n_slots, which)
        fn = self._rows_fns.get(key)
        if fn is None:
            model = self.model if which == "target" else self._draft_model
            fn = self._rows_fns[key] = self._build_rows_fn(
                n_rows, n_slots, model)
            stat_add("decode_prefill_compiles")
        return fn

    # -- client side ------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens=None,
               deadline_ms=_UNSET, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0,
               seed: Optional[int] = None,
               on_token: Optional[Callable[[int], None]] = None,
               record_logits: bool = False,
               speculative: Optional[bool] = None,
               extract_kv: bool = False,
               kv_import=None) -> DecodeRequest:
        from ..observe.request_trace import get_trace_store

        c = self.config
        prompt = [int(t) for t in prompt]
        trace = get_trace_store().start(
            "decode", replica=self.name, prompt_len=len(prompt),
            max_new_tokens=None if max_new_tokens is None
            else int(max_new_tokens))
        try:
            return self._submit_traced(
                trace, prompt, max_new_tokens, deadline_ms, temperature,
                top_k, top_p, seed, on_token, record_logits, speculative,
                extract_kv, kv_import)
        except Exception as e:
            # submit-time rejection IS a terminal outcome: count it,
            # record its (instant) terminal latency so error-rate
            # denominators include rejects, and tail-retain the (tiny)
            # trace so /debug/request/<id> can answer "why did my
            # request never run".  Only SERVER-fault rejections burn
            # the SLO budget (overload shedding, draining) — a buggy
            # client hammering an invalid prompt must not page anyone.
            outcome = "cancelled" if isinstance(e, ServerClosedError) \
                else "rejected"
            stat_add(f"decode_requests_total_{outcome}")
            latency = time.monotonic() - trace.t_start
            stat_time("decode_request_latency_seconds", latency)
            summary = {"outcome": outcome,
                       "latency_s": round(latency, 6),
                       "ttft_s": None, "tpot_s": None, "n_tokens": 0,
                       "prompt_len": len(prompt)}
            violations = ()
            if isinstance(e, (QueueFullError, ServerClosedError)):
                try:
                    from ..observe import slo as _slo

                    violations = _slo.observe_request(summary)
                except Exception:  # noqa: BLE001 — never mask the
                    stat_add("request_trace_errors")  # rejection
            summary.pop("outcome")  # stored top-level on the trace
            get_trace_store().finish(
                trace, outcome=outcome,
                reason=f"{type(e).__name__}: {e}",
                violations=violations, **summary)
            raise

    def _submit_traced(self, trace, prompt, max_new_tokens, deadline_ms,
                       temperature, top_k, top_p, seed, on_token,
                       record_logits, speculative, extract_kv=False,
                       kv_import=None) -> DecodeRequest:
        c = self.config
        if not prompt:
            raise ValueError("prompt must hold at least one token id")
        if kv_import is not None:
            # migrated admission (serving/disagg.py): validate the
            # payload against THIS engine's pool geometry at submit
            # time — a mismatch must reject loudly, never corrupt pools
            cc = self._cache.config
            if extract_kv:
                raise ValueError(
                    "kv_import and extract_kv are mutually exclusive "
                    "(a request is either the prefill leg or the "
                    "decode leg of a disagg handoff, not both)")
            if speculative:
                raise ValueError(
                    "kv_import cannot be speculative: the migration "
                    "payload carries the target pools only — the "
                    "draft pools never saw the prompt K/V")
            if bool(kv_import.quantized) != bool(cc.quantized):
                raise ValueError(
                    f"kv_import quantized={kv_import.quantized} but "
                    f"this engine's cache quantized={cc.quantized} — "
                    f"prefill and decode replicas must agree on "
                    f"FLAGS_decode_kv_quant")
            if int(kv_import.page_size) != cc.page_size:
                raise ValueError(
                    f"kv_import page_size {kv_import.page_size} != "
                    f"engine page_size {cc.page_size}")
            if int(kv_import.n_tokens) != len(prompt):
                raise ValueError(
                    f"kv_import covers {kv_import.n_tokens} tokens but "
                    f"the prompt has {len(prompt)}")
            if int(kv_import.n_pages) != cc.pages_for(len(prompt)):
                raise ValueError(
                    f"kv_import carries {kv_import.n_pages} pages but "
                    f"the prompt needs {cc.pages_for(len(prompt))}")
        if speculative:
            # loud submit-time rejection: a request that ASKS for
            # speculative decoding must get it or fail, never silently
            # degrade
            if self._draft_model is None:
                raise ValueError(
                    "speculative=True but the engine has no draft "
                    "model (DecodeEngine(draft_model=, draft_weights=))")
            if c.spec_k <= 0:
                raise ValueError(
                    "speculative=True but FLAGS_decode_spec_k / "
                    "DecodeConfig.spec_k is 0")
            if float(temperature) > 0.0:
                raise ValueError(
                    "speculative decoding is greedy-only (bitwise "
                    "acceptance); submit with temperature=0")
        if max_new_tokens is None:
            max_new_tokens = c.max_new_tokens
        if len(prompt) + int(max_new_tokens) > c.max_seq_len:
            raise RequestTooLargeError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the slot capacity "
                f"({c.max_seq_len}); raise FLAGS_decode_max_seq_len or "
                f"shorten the request")
        cc = self._cache.config
        need = cc.pages_for(len(prompt) + int(max_new_tokens))
        if need > cc.num_pages - 1:  # page 0 is trash, never allocatable
            # an unsatisfiable reservation must be rejected HERE: queued
            # it would head-of-line-block the engine forever (no finish
            # can ever free enough pages)
            raise RequestTooLargeError(
                f"request needs {need} cache pages but the pool only "
                f"has {cc.num_pages - 1}; raise num_pages or shorten "
                f"the request")
        self._buckets.seq_bucket(len(prompt))  # raises RequestTooLarge
        if deadline_ms is _UNSET:
            deadline_ms = c.default_deadline_ms
        deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        with self._cond:
            if self._closing:
                raise ServerClosedError("decode engine is stopping")
            if len(self._queue) >= c.max_queue:
                stat_add("decode_rejected_queue_full")
                raise QueueFullError(
                    f"decode queue is at capacity ({c.max_queue})")
            if seed is None:
                seed = self._seq
            self._seq += 1
            req = DecodeRequest(prompt, max_new_tokens, deadline,
                                temperature, top_k, top_p, seed,
                                on_token, record_logits=record_logits,
                                speculative=speculative,
                                extract_kv=extract_kv,
                                kv_import=kv_import)
            req.trace = trace
            self._queue.append(req)
            # resolved defaults ride the event, not trace.attrs: the
            # trace is already visible to concurrent /debug readers
            # and attrs must stay structurally frozen after start()
            trace.event("enqueue", queue_depth=len(self._queue),
                        max_new_tokens=int(max_new_tokens),
                        seed=int(seed),
                        deadline_ms=None if deadline_ms is None
                        else float(deadline_ms))
            stat_add("decode_requests")
            stat_set("decode_queue_depth", len(self._queue))
            self._cond.notify_all()
        return req

    def generate(self, prompt, **kw) -> List[int]:
        return self.submit(prompt, **kw).result()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "DecodeEngine":
        with self._cond:
            if self._thread is not None:
                return self
            self._closing = self._abort = False
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"decode-{self.name}")
            self._thread.start()
        from ..observe import flight as _flight

        _flight.record("serving/decode_start", name=self.name,
                       slots=self.config.slots,
                       max_seq_len=self.config.max_seq_len,
                       page_size=self.config.page_size,
                       prefix_cache=self.config.prefix_cache,
                       kv_quant=self.config.kv_quant,
                       spec_k=self.config.spec_k
                       if self.spec_enabled else 0)
        stat_set("decode_kv_quant_enabled",
                 1 if self.config.kv_quant else 0)
        stat_set("decode_kv_page_bytes", self._cache.config.page_bytes())
        return self

    def stop(self, drain: bool = True):
        with self._cond:
            self._closing = True
            if not drain:
                self._abort = True
                while self._queue:
                    req = self._queue.popleft()
                    if req._finish(error=ServerClosedError(
                            "engine stopped before the request ran")):
                        stat_add("decode_cancelled")
                stat_set("decode_queue_depth", 0)
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        from ..observe import flight as _flight

        _flight.record("serving/decode_stop", name=self.name,
                       drain=bool(drain))

    def __enter__(self) -> "DecodeEngine":
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=exc[0] is None)
        return False

    # -- scheduler --------------------------------------------------------
    @property
    def live_slots(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    @property
    def free_slots(self) -> int:
        return self.config.slots - self.live_slots

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def _expire(self, req, where: str) -> None:
        if req._finish(error=DeadlineExceededError(
                f"deadline exceeded {where}")):
            stat_add("decode_deadline_exceeded")

    def _reap_queue_locked(self):
        now = time.monotonic()
        live = []
        for r in self._queue:
            if r.done():
                continue
            if r.expired(now):
                self._expire(r, "while queued")
                continue
            live.append(r)
        if len(live) != len(self._queue):
            self._queue = collections.deque(live)
            stat_set("decode_queue_depth", len(self._queue))

    def _admit_locked(self):
        import jax

        if not self._continuous and self.live_slots:
            return []  # one-shot baseline: groups never mix
        admitted = []
        while self._queue:
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                break
            req = self._queue[0]
            if req.done():
                self._queue.popleft()
                continue
            if req.expired():
                self._queue.popleft()
                self._expire(req, "while queued")
                continue
            # shared-aware worst-case reservation: pages for prompt +
            # max_new minus every prefix-cache hit, with a CoW spare
            # held back for a borrowed partial page — a decode step can
            # still never die on cache exhaustion mid-flight
            slot = free[0]
            need = len(req.prompt) + req.max_new_tokens
            self._admitting = req
            try:
                # a migrated admission claims ALL-FRESH pages (no
                # prefix lookup): the installed pages must be solely
                # owned — cross-engine sharing of migrated bytes is
                # exactly what the disagg refcount contract forbids
                info = self._cache.claim(
                    slot, need,
                    prompt=None if req.kv_import is not None
                    else req.prompt)
            finally:
                self._admitting = None
            if info is None:
                stat_add("decode_admission_blocked_pages")
                self._tev(req, "admission_blocked",
                          reason="pages",
                          free_pages=self._cache.allocator.num_free)
                break  # FIFO head-of-line: wait for pages to free
            self._queue.popleft()
            st = _SlotState(req, jax.random.PRNGKey(req.seed))
            st.spec = (self.spec_enabled and req.temperature <= 0.0
                       and req.speculative is not False
                       and req.kv_import is None)
            if req.kv_import is not None:
                self._account_migrated(slot, st, req)
            else:
                self._account_claim(slot, st, info)
            self._slots[slot] = st
            admitted.append((slot, req))
        stat_set("decode_queue_depth", len(self._queue))
        return admitted

    def _account_migrated(self, slot: int, st: _SlotState, req) -> None:
        """Admit a request whose prompt K/V arrives as a migration
        payload (disaggregated serving): install the pages into the
        slot's fresh claim, then start the slot exactly like a
        full-prefix-cache hit — the pages hold prompt positions
        ``0..n-1``, so the first decode step re-derives the last prompt
        position's logits (its own K/V write aims at trash) and samples
        the first token with ``fold_in(base_key, 0)``.  That is the
        SAME sampling path as a local prefill's first token, which is
        what makes migrated decode bitwise-equal to local."""
        n = len(req.prompt)
        self._cache.install_pages(slot, req.kv_import)
        st.phase = "decode"
        st.write_trash_once = True
        st.last_token = req.prompt[-1]
        st.prefill_pos = n
        self._cache.lengths[slot] = n - 1
        stat_add("decode_migrated_admissions")
        self._tev(req, "admit", slot=slot,
                  queue_wait_ms=round(
                      (st.t_admit - req.t_enqueue) * 1e3, 3),
                  migrated_pages=req.kv_import.n_pages,
                  migrated_bytes=req.kv_import.nbytes,
                  prefill_skipped=True)
        # drop the payload reference: the arrays live in the pools now,
        # and holding them would pin the transport buffers for the
        # request's whole lifetime
        req.kv_import = None

    def _account_claim(self, slot: int, st: _SlotState, info) -> None:
        """Fold one admission's prefix-cache outcome into the slot's
        phase plan and the hit-rate accounting."""
        req = st.req
        n = len(req.prompt)
        self._hit_pages += info.hit_pages
        self._prompt_pages += info.prompt_pages
        if info.hit_pages:
            stat_add("decode_prefix_pages_hit", info.hit_pages)
        stat_add("decode_prefix_pages_total", info.prompt_pages)
        total = stat_get("decode_prefix_pages_total")
        if total:
            hits = stat_get("decode_prefix_pages_hit")
            # deprecated integer-percent form (kept for dashboards) +
            # the float-precision _ppm companion (same pattern as
            # cluster_step_time_skew_ppm)
            stat_set("decode_cache_hit_rate", int(100 * hits / total))
            stat_set("decode_cache_hit_rate_ppm",
                     int(1e6 * hits / total))
        stat_set("decode_shared_pages", self._cache.shared_pages)
        self._tev(req, "admit", slot=slot,
                  queue_wait_ms=round(
                      (st.t_admit - req.t_enqueue) * 1e3, 3),
                  prompt_pages=info.prompt_pages,
                  fresh_pages=info.fresh_pages,
                  hit_pages=info.hit_pages,
                  hit_tokens=info.hit_tokens,
                  cow_spare=bool(info.partial),
                  prefill_skipped=info.hit_tokens >= n)
        if info.hit_tokens >= n:
            # the ENTIRE prompt is cache-covered: skip prefill — the
            # first decode step re-derives the last prompt position's
            # logits (its K/V write aims at trash: the shared pages
            # already hold that position) and samples the first token
            st.phase = "decode"
            st.write_trash_once = True
            st.last_token = req.prompt[-1]
            st.prefill_pos = n
            # the first step's query is the LAST prompt position: its
            # K/V (and everything before) is already in the shared
            # pages, so the length cursor starts one short of the
            # prompt and the step's own write goes to trash
            self._cache.lengths[slot] = n - 1
            stat_add("decode_prefill_skipped")
        else:
            st.phase = "prefill"
            st.prefill_pos = info.hit_tokens  # page-aligned by design

    def _release(self, slot: int):
        st = self._slots[slot]
        register = None
        if st is not None and self._cache.prefix is not None \
                and st.phase == "decode" \
                and (not self.spec_enabled or st.spec):
            # register this slot's pages for future prefix hits — only
            # when the draft pools are synced too (a non-speculative
            # slot on a spec engine never wrote draft K/V; stale draft
            # bytes could not corrupt output, only acceptance, but we
            # keep the index clean).  Content = prompt + generated,
            # truncated to the positions actually written — minus any
            # trailing positions a spec slot wrote through the normal
            # step (target-only; the draft bytes there are stale).
            seq = st.req.prompt + st.req.generated
            register = seq[:int(self._cache.lengths[slot])
                           - st.draft_lag]
        # release BEFORE clearing the slot so the cache's register/
        # evict events can still be attributed to the owning request
        self._cache.release(slot, register_tokens=register)
        self._slots[slot] = None
        stat_set("decode_free_pages", self._cache.allocator.num_free)
        stat_set("decode_shared_pages", self._cache.shared_pages)

    def _export_slot_kv(self, slot: int) -> None:
        """Gather the slot's prompt-covering pages into a migration
        payload on ``req.kv_export`` — the disagg prefill->decode
        handoff.  Runs on the engine thread right before the slot
        releases, so the pages still hold positions ``0..n-1`` and the
        gather cannot race a donated step."""
        st = self._slots[slot]
        req = st.req
        cc = self._cache.config
        n = len(req.prompt)
        if int(self._cache.lengths[slot]) < n - 1:
            return  # prefill never covered the prompt; router re-runs
        n_pages = cc.pages_for(n)
        pages = self._cache.slot_pages(slot)[:n_pages]
        with otrace.span("serving/migrate_export", slot=slot,
                         pages=n_pages):
            arrays = self._cache.export_pages(pages)
        req.kv_export = kv_cache.KVPageExport(
            n_tokens=n, n_pages=n_pages, src_pages=pages,
            arrays=arrays, quantized=cc.quantized,
            page_size=cc.page_size)
        stat_add("decode_kv_exports")
        self._tev(req, "kv_export", pages=n_pages,
                  bytes=req.kv_export.nbytes)

    def _finish_slot(self, slot: int, error=None):
        st = self._slots[slot]
        if error is None and st.req.extract_kv \
                and st.phase == "decode":
            # export BEFORE _finish: the handoff thread wakes on the
            # request's completion and must find the payload attached
            try:
                self._export_slot_kv(slot)
            except Exception as e:  # noqa: BLE001 — a failed export
                # must fail the REQUEST (the router re-dispatches), not
                # the engine loop
                error = e
        if error is None:
            if st.req._finish():
                stat_add("decode_completed")
        else:
            if st.req._finish(error=error):
                stat_add("decode_failed")
        self._release(slot)

    def _reap_live(self):
        """The mid-decode deadline reap: runs at EVERY step boundary so
        a stalled/abandoned client frees its slot now, not after
        max_new_tokens."""
        now = time.monotonic()
        for i, st in enumerate(self._slots):
            if st is None:
                continue
            if st.req.done():  # client-side reap/abandon won the race
                stat_add("decode_abandoned")
                self._release(i)
            elif st.req.expired(now):
                self._expire(st.req, "mid-decode (slot freed)")
                self._release(i)

    def _loop(self):
        while True:
            with self._cond:
                if self._abort:
                    for i, st in enumerate(self._slots):
                        if st is not None:
                            self._finish_slot(i, ServerClosedError(
                                "engine stopped mid-generation"))
                    return
                self._reap_queue_locked()
                admitted = self._admit_locked()
                if not admitted and not self.live_slots:
                    if self._closing and not self._queue:
                        return
                    # short cap keeps queued deadlines (and a pages-
                    # blocked head) honest while idle
                    self._cond.wait(0.05 if self._queue else None)
                    continue
            self._service_prefills()
            self._reap_live()
            self._run_decode_round()

    # -- device work: prefill ---------------------------------------------
    def _service_prefills(self):
        """Advance prefill-phase slots.  Chunked mode dispatches ONE
        chunk per engine-loop iteration (round-robin across prefilling
        slots) so the decoding slots keep stepping between chunks;
        unchunked mode completes each prefill in one dispatch."""
        pre = [i for i, st in enumerate(self._slots)
               if st is not None and st.phase == "prefill"]
        if not pre:
            return
        chunk = self.config.prefill_chunk_pages
        if chunk > 0 and self.config.ragged_prefill_rows > 0:
            # ragged packing: several prompts' tails share one
            # fixed-width multi-lane dispatch instead of each padding
            # its own chunk executable
            self._run_prefill_ragged(pre)
        elif chunk > 0:
            pick = min(pre, key=lambda i:
                       (i - self._prefill_rr) % self.config.slots)
            self._prefill_rr = (pick + 1) % self.config.slots
            self._run_prefill_rows(
                pick, chunk * self.config.page_size)
        else:
            for i in pre:
                st = self._slots[i]
                if st.prefill_pos == 0:
                    self._run_prefill_full(i)
                else:
                    # prefix-cache suffix: only the unmatched tail of
                    # the prompt is computed, in one dispatch
                    rows = self._buckets.seq_bucket(
                        len(st.req.prompt) - st.prefill_pos)
                    self._run_prefill_rows(i, rows)

    def _run_prefill_full(self, slot: int):
        """The whole-prompt prefill fast path (no cache hit, chunking
        off): page-wholesale K/V writes + locally-built full-width
        attention, one dispatch."""
        import jax.numpy as jnp

        st = self._slots[slot]
        req = st.req
        try:
            t_pad = self._buckets.seq_bucket(len(req.prompt))
            tokens = np.zeros((t_pad,), np.int32)
            tokens[:len(req.prompt)] = req.prompt
            args = lambda w: (w, jnp.asarray(tokens),  # noqa: E731
                              np.int32(len(req.prompt)),
                              jnp.asarray(self._cache.page_table[slot]),
                              st.base_key,
                              np.float32(req.temperature),
                              np.int32(req.top_k),
                              np.float32(req.top_p))
            t0 = time.monotonic()
            with otrace.span("serving/decode_prefill", slot=slot,
                             bucket=t_pad):
                tok, last = self._exe.run_persistent(
                    self._prefill_fn(t_pad), self._state_vars,
                    args=args(self.weights), scope=self._scope)
                if st.spec:
                    # mirror the prefill into the draft's pools (same
                    # page ids) so proposals can read the prompt
                    self._exe.run_persistent(
                        self._prefill_fn(t_pad, "draft"),
                        self._draft_state_vars,
                        args=args(self.draft_weights), scope=self._scope)
            stat_time("decode_prefill_seconds", time.monotonic() - t0)
            self._tev(req, "prefill", slot=slot, bucket=t_pad,
                      tokens=len(req.prompt),
                      dur_ms=round((time.monotonic() - t0) * 1e3, 3))
            stat_add("decode_prefills")
            record_pad_waste(len(req.prompt), t_pad)
            st.prefill_pos = len(req.prompt)
            st.phase = "decode"
            self._cache.lengths[slot] = len(req.prompt)
            if req.record_logits:
                req.logits_trace.append(np.asarray(last))
            self._deliver(slot, int(np.asarray(tok)))
        except Exception as e:  # noqa: BLE001 — fault isolation per req
            stat_add("decode_prefill_errors")
            self._finish_slot(slot, e)

    def _run_prefill_rows(self, slot: int, rows: int):
        """One prefill chunk of ``rows`` positions starting at the
        slot's prefill cursor (page-aligned).  Serves both chunked
        prefill and the prefix-cache suffix (start > 0): attention
        gathers the already-present pages for positions below the
        cursor, so the chunk's logits stay bitwise-equal to a full
        prefill.  The FINAL chunk samples the request's first token."""
        import jax.numpy as jnp

        st = self._slots[slot]
        req = st.req
        cc = self._cache.config
        try:
            n = len(req.prompt)
            start = st.prefill_pos
            n_live = min(rows, n - start)
            final = start + n_live >= n
            tokens = np.zeros((1, rows), np.int32)
            tokens[0, :n_live] = req.prompt[start:start + n_live]
            write_page = np.zeros((1, rows), np.int32)
            write_off = np.zeros((1, rows), np.int32)
            for r in range(n_live):
                pos = start + r
                write_page[0, r] = self._cache.page_table[slot][
                    pos // cc.page_size]
                write_off[0, r] = pos % cc.page_size
            t0 = time.monotonic()
            args = lambda w: (w, jnp.asarray(tokens),  # noqa: E731
                              np.asarray([start], np.int32),
                              np.asarray([min(n - 1 - start, rows - 1)],
                                         np.int32),
                              jnp.asarray(
                                  self._cache.page_table[slot:slot + 1]),
                              jnp.asarray(write_page),
                              jnp.asarray(write_off),
                              jnp.asarray(
                                  np.asarray(st.base_key)[None]),
                              np.zeros((1,), np.int32),
                              np.asarray([req.temperature], np.float32),
                              np.asarray([req.top_k], np.int32),
                              np.asarray([req.top_p], np.float32))
            with otrace.span("serving/decode_prefill_chunk", slot=slot,
                             start=start, rows=rows):
                tok, _greedy, logits = self._exe.run_persistent(
                    self._rows_fn(rows, 1), self._state_vars,
                    args=args(self.weights), scope=self._scope)
                if st.spec:
                    self._exe.run_persistent(
                        self._rows_fn(rows, 1, "draft"),
                        self._draft_state_vars,
                        args=args(self.draft_weights), scope=self._scope)
            stat_time("decode_prefill_seconds", time.monotonic() - t0)
            stat_add("prefill_chunks")
            record_pad_waste(n_live, rows)
            self._prefill_chunk_count += 1
            st.chunks += 1
            self._tev(req, "prefill_chunk", slot=slot, start=start,
                      rows=rows, live=n_live, final=final,
                      dur_ms=round((time.monotonic() - t0) * 1e3, 3))
            st.prefill_pos += n_live
            if final:
                stat_add("decode_prefills")
                st.phase = "decode"
                self._cache.lengths[slot] = n
                if req.record_logits:
                    req.logits_trace.append(
                        np.asarray(logits)[0, n - 1 - start].copy())
                self._deliver(slot, int(np.asarray(tok)[0]))
        except Exception as e:  # noqa: BLE001 — fault isolation per req
            stat_add("decode_prefill_errors")
            self._finish_slot(slot, e)

    def _run_prefill_ragged(self, pre: List[int]):
        """Pack several prompts' tails into ONE fixed-width multi-lane
        dispatch: each of the ``ragged_prefill_rows`` lanes is one
        (slot, position) query row with its own page-table row, start,
        and (page, offset) write coords — the per-row coordinates of
        the chunk executable already make lanes independent, so the
        only thing padding bought (one shape per dispatch) is kept
        while its cost (dead rows rounding each prompt up to its own
        power-of-two bucket) is shared across requests.  Lanes of the
        SAME request at consecutive positions are sound because every
        layer writes all rows' K/V before its attention reads
        (``_build_rows_fn``), and per-lane logits stay bitwise-equal
        to the padded chunk path by the same chunk-equivalence
        contract; dead lanes write to the trash page (page 0) and are
        ignored.  One fixed lane count -> ONE extra executable."""
        import jax.numpy as jnp

        L = self.config.ragged_prefill_rows
        cc = self._cache.config
        per_slot_cap = self.config.prefill_chunk_pages * cc.page_size

        # round-robin lane assignment in chunk-sized shares: every
        # prefilling slot gets a fair share first, then further rounds
        # deal the leftover lanes out (all of a prompt's pages are
        # reserved at admission, so one slot absorbing several chunks
        # in one dispatch is sound) — dead lanes only remain when the
        # total outstanding prefill work is smaller than the dispatch
        order = sorted(pre, key=lambda i:
                       (i - self._prefill_rr) % self.config.slots)
        assigned = {i: 0 for i in order}
        lanes_left = L
        progress = True
        while lanes_left > 0 and progress:
            progress = False
            for i in order:
                st = self._slots[i]
                t = min(len(st.req.prompt) - st.prefill_pos
                        - assigned[i], per_slot_cap, lanes_left)
                if t <= 0:
                    continue
                assigned[i] += t
                lanes_left -= t
                progress = True
        picks = [(i, self._slots[i].prefill_pos, assigned[i])
                 for i in order if assigned[i] > 0]
        if not picks:
            return
        self._prefill_rr = (picks[-1][0] + 1) % self.config.slots
        live = L - lanes_left

        tokens = np.zeros((L, 1), np.int32)
        start = np.zeros((L,), np.int32)
        page_table = np.zeros((L,) + self._cache.page_table[0].shape,
                              np.int32)
        write_page = np.zeros((L, 1), np.int32)
        write_off = np.zeros((L, 1), np.int32)
        key0 = np.asarray(self._slots[picks[0][0]].base_key)
        base_keys = np.zeros((L,) + key0.shape, key0.dtype)
        temp = np.zeros((L,), np.float32)
        top_k = np.zeros((L,), np.int32)
        top_p = np.ones((L,), np.float32)
        lane = 0
        spec_any = False
        for i, s, t in picks:
            st = self._slots[i]
            req = st.req
            spec_any = spec_any or st.spec
            for j in range(t):
                pos = s + j
                tokens[lane, 0] = req.prompt[pos]
                start[lane] = pos
                page_table[lane] = self._cache.page_table[i]
                write_page[lane, 0] = self._cache.page_table[i][
                    pos // cc.page_size]
                write_off[lane, 0] = pos % cc.page_size
                base_keys[lane] = np.asarray(st.base_key)
                temp[lane] = req.temperature
                top_k[lane] = req.top_k
                top_p[lane] = req.top_p
                lane += 1
        try:
            t0 = time.monotonic()
            args = lambda w: (w, jnp.asarray(tokens),  # noqa: E731
                              jnp.asarray(start),
                              np.zeros((L,), np.int32),
                              jnp.asarray(page_table),
                              jnp.asarray(write_page),
                              jnp.asarray(write_off),
                              jnp.asarray(base_keys),
                              np.zeros((L,), np.int32),
                              jnp.asarray(temp), jnp.asarray(top_k),
                              jnp.asarray(top_p))
            with otrace.span("serving/decode_prefill_ragged", lanes=L,
                             live=live, slots=len(picks)):
                tok, _greedy, logits = self._exe.run_persistent(
                    self._rows_fn(1, L), self._state_vars,
                    args=args(self.weights), scope=self._scope)
                if spec_any:
                    self._exe.run_persistent(
                        self._rows_fn(1, L, "draft"),
                        self._draft_state_vars,
                        args=args(self.draft_weights), scope=self._scope)
            stat_time("decode_prefill_seconds", time.monotonic() - t0)
            stat_add("prefill_chunks")
            stat_add("decode_ragged_dispatches")
            record_pad_waste(live, L)
            self._prefill_chunk_count += 1
            dur = round((time.monotonic() - t0) * 1e3, 3)
            lane = 0
            for i, s, t in picks:
                st = self._slots[i]
                req = st.req
                lane += t
                n = len(req.prompt)
                final = s + t >= n
                st.chunks += 1
                self._tev(req, "prefill_chunk", slot=i, start=s, rows=t,
                          live=t, final=final, ragged=True, dur_ms=dur)
                st.prefill_pos += t
                if final:
                    stat_add("decode_prefills")
                    st.phase = "decode"
                    self._cache.lengths[i] = n
                    if req.record_logits:
                        req.logits_trace.append(
                            np.asarray(logits)[lane - 1, 0].copy())
                    self._deliver(i, int(np.asarray(tok)[lane - 1]))
        except Exception as e:  # noqa: BLE001 — the packed dispatch is
            # shared: fail every packed request, not just one
            stat_add("decode_prefill_errors")
            for i, _s, _t in picks:
                if self._slots[i] is not None:
                    self._finish_slot(i, e)

    # -- device work: decode ----------------------------------------------
    def _deliver(self, slot: int, token: int):
        """Account one sampled token for a live slot; finish + free the
        slot the moment its request is done."""
        st = self._slots[slot]
        now = time.monotonic()
        if st.n_generated > 0:
            stat_time("tpot_seconds", now - st.t_last)
        st.t_last = now
        st.n_generated += 1
        st.last_token = token
        self.tokens_total += 1
        stat_add("decode_tokens_total")
        st.req._emit(token)
        self._tev(st.req, "token", slot=slot, token=int(token),
                  n=st.n_generated)
        eos = self.config.eos_id
        if eos is not None and token == eos:
            st.req.finish_reason = "eos"
            self._finish_slot(slot)
        elif st.n_generated >= st.req.max_new_tokens:
            st.req.finish_reason = "budget"
            self._finish_slot(slot)

    def _perform_cow(self, slot, plans):
        """Run the device half of every planned copy-on-write BEFORE
        the write dispatch that needed it (the host tables were already
        swapped by plan_cow)."""
        if not plans:
            return
        if self._cow_fn is None:
            self._cow_fn = self._build_cow_fn()
        st = self._slots[slot]
        for src, dst in plans:
            t0 = time.monotonic()
            self._exe.run_persistent(
                self._cow_fn, self._cow_state,
                args=(np.int32(src), np.int32(dst)), scope=self._scope)
            stat_add("decode_cow_copies")
            self._cow_copies += 1
            if st is not None:
                self._tev(st.req, "cow", slot=slot, src=int(src),
                          dst=int(dst),
                          dur_ms=round((time.monotonic() - t0) * 1e3, 3))

    def _run_decode_round(self):
        decoding = [i for i, st in enumerate(self._slots)
                    if st is not None and st.phase == "decode"]
        if not decoding:
            return
        stat_max("decode_slot_occupancy_max", len(decoding))
        spec = [i for i in decoding
                if self._slots[i].spec
                and (self._slots[i].req.max_new_tokens
                     - self._slots[i].n_generated) >= 2]
        if spec:
            self._run_spec(spec)
        normal = [i for i in decoding
                  if self._slots[i] is not None and i not in set(spec)]
        if normal:
            self._run_step(normal)

    def _run_step(self, live_idx):
        import jax.numpy as jnp

        c = self._cache.config
        s = c.num_slots
        # copy-on-write any shared page this step would write (a
        # borrowed partial tail at its first divergent token)
        for i in live_idx:
            if not self._slots[i].write_trash_once:
                self._perform_cow(i, self._cache.plan_cow(
                    i, [int(self._cache.lengths[i])]))
        tokens = np.zeros((s,), np.int32)
        positions = np.zeros((s,), np.int32)
        live = np.zeros((s,), bool)
        write_page = np.zeros((s,), np.int32)
        write_off = np.zeros((s,), np.int32)
        counters = np.zeros((s,), np.int32)
        temp = np.zeros((s,), np.float32)
        top_k = np.zeros((s,), np.int32)
        top_p = np.ones((s,), np.float32)
        base_keys = np.zeros((s, 2), np.uint32)
        for i in live_idx:
            st = self._slots[i]
            tokens[i] = st.last_token
            positions[i] = self._cache.lengths[i]
            live[i] = True
            if st.write_trash_once:
                # cache-hit first step: the shared pages already hold
                # this position's K/V — re-deriving it writes identical
                # bytes, but shared pages are immutable, so aim at trash
                write_page[i], write_off[i] = 0, 0
            else:
                write_page[i], write_off[i] = self._cache.write_coords(i)
            counters[i] = st.n_generated
            temp[i] = st.req.temperature
            top_k[i] = st.req.top_k
            top_p[i] = st.req.top_p
            base_keys[i] = np.asarray(st.base_key)
        t0 = time.monotonic()
        try:
            with otrace.span("serving/decode_step", live=len(live_idx)):
                nxt, logits = self._exe.run_persistent(
                    self._step_fn, self._state_vars,
                    args=(self.weights, jnp.asarray(tokens),
                          jnp.asarray(positions), jnp.asarray(live),
                          jnp.asarray(self._cache.page_table),
                          jnp.asarray(write_page),
                          jnp.asarray(write_off),
                          jnp.asarray(base_keys), jnp.asarray(counters),
                          jnp.asarray(temp), jnp.asarray(top_k),
                          jnp.asarray(top_p)),
                    scope=self._scope)
                nxt = np.asarray(nxt)  # THE per-step sync point
        except Exception as e:  # noqa: BLE001 — fail the batch loudly,
            # free every slot, keep the consumer thread alive
            stat_add("decode_step_errors")
            for i in live_idx:
                self._finish_slot(i, e)
            return
        stat_time("decode_step_seconds", time.monotonic() - t0)
        logits_np = None
        for i in live_idx:
            st = self._slots[i]
            st.write_trash_once = False
            if st.spec:
                st.draft_lag += 1  # target-only write: draft is stale
            self._cache.lengths[i] += 1
            if st.req.record_logits:
                if logits_np is None:
                    logits_np = np.asarray(logits)
                st.req.logits_trace.append(logits_np[i].copy())
            self._deliver(i, int(nxt[i]))
        stat_set("decode_slot_occupancy", self.live_slots)
        stat_add("decode_steps")

    def _run_spec(self, spec_idx):
        """One speculative round for the greedy slots: a k-token draft
        burst (ONE dispatch) then ONE batched target step verifying all
        k+1 positions.  Every emitted token is the TARGET's argmax at
        its position — bitwise-identical to non-speculative greedy
        decode; proposals only decide how many tokens this round
        yields (1..k+1)."""
        import jax.numpy as jnp

        c = self._cache.config
        s = c.num_slots
        k = self.config.spec_k
        rows = k + 1
        k_live = {}
        for i in spec_idx:
            st = self._slots[i]
            rem = st.req.max_new_tokens - st.n_generated
            k_live[i] = min(k, rem - 1)
            # CoW the pages this round's window writes (skip the
            # trash-aimed first position on the cache-hit path)
            n = int(self._cache.lengths[i])
            lo = n + (1 if st.write_trash_once else 0)
            self._perform_cow(i, self._cache.plan_cow(
                i, range(lo, n + k_live[i] + 1)))
        tok0 = np.zeros((s,), np.int32)
        start = np.zeros((s,), np.int32)
        live = np.zeros((s,), bool)
        trash_first = np.zeros((s,), bool)
        for i in spec_idx:
            st = self._slots[i]
            tok0[i] = st.last_token
            start[i] = self._cache.lengths[i]
            live[i] = True
            trash_first[i] = st.write_trash_once
        t0 = time.monotonic()
        try:
            if self._propose_fn is None:
                self._propose_fn = self._build_propose_fn(k)
            with otrace.span("serving/decode_spec", live=len(spec_idx),
                             k=k):
                (props,) = self._exe.run_persistent(
                    self._propose_fn, self._draft_state_vars,
                    args=(self.draft_weights, jnp.asarray(tok0),
                          jnp.asarray(start), jnp.asarray(live),
                          jnp.asarray(trash_first),
                          jnp.asarray(self._cache.page_table)),
                    scope=self._scope)
                props = np.asarray(props)            # [S, k+1]
                tokens = np.zeros((s, rows), np.int32)
                write_page = np.zeros((s, rows), np.int32)
                write_off = np.zeros((s, rows), np.int32)
                for i in spec_idx:
                    tokens[i, 0] = tok0[i]
                    tokens[i, 1:] = props[i, :k]
                    for r in range(k_live[i] + 1):
                        if r == 0 and trash_first[i]:
                            continue  # stays (0, 0): trash
                        pos = int(start[i]) + r
                        write_page[i, r] = self._cache.page_table[i][
                            pos // c.page_size]
                        write_off[i, r] = pos % c.page_size
                _tok, greedy, logits = self._exe.run_persistent(
                    self._rows_fn(rows, s), self._state_vars,
                    args=(self.weights, jnp.asarray(tokens),
                          jnp.asarray(start),
                          np.zeros((s,), np.int32),
                          jnp.asarray(self._cache.page_table),
                          jnp.asarray(write_page),
                          jnp.asarray(write_off),
                          np.zeros((s, 2), np.uint32),
                          np.zeros((s,), np.int32),
                          np.zeros((s,), np.float32),
                          np.zeros((s,), np.int32),
                          np.ones((s,), np.float32)),
                    scope=self._scope)
                greedy = np.asarray(greedy)          # [S, k+1]
        except Exception as e:  # noqa: BLE001 — batch fault isolation
            stat_add("decode_step_errors")
            for i in spec_idx:
                if self._slots[i] is not None:
                    self._finish_slot(i, e)
            return
        stat_time("decode_step_seconds", time.monotonic() - t0)
        logits_np = None
        proposed = accepted = 0
        for i in spec_idx:
            st = self._slots[i]
            a = 0
            while a < k_live[i] and int(props[i, a]) == int(greedy[i, a]):
                a += 1
            proposed += k_live[i]
            accepted += a
            self._tev(st.req, "spec_round", slot=i,
                      proposed=k_live[i], accepted=a)
            st.write_trash_once = False
            for j in range(a + 1):
                self._cache.lengths[i] += 1
                if st.req.record_logits:
                    if logits_np is None:
                        logits_np = np.asarray(logits)
                    st.req.logits_trace.append(logits_np[i, j].copy())
                self._deliver(i, int(greedy[i, j]))
                if self._slots[i] is None:
                    break  # finished (EOS/budget) mid-emission
        self._spec_proposed += proposed
        self._spec_accepted += accepted
        stat_add("decode_spec_proposed", proposed)
        stat_add("decode_spec_accepted", accepted)
        stat_add("decode_spec_rounds")
        total = stat_get("decode_spec_proposed")
        if total:
            acc = stat_get("decode_spec_accepted")
            # deprecated integer-percent + float-precision _ppm
            stat_set("spec_accept_rate", int(100 * acc / total))
            stat_set("spec_accept_rate_ppm", int(1e6 * acc / total))
        stat_set("decode_slot_occupancy", self.live_slots)

    # -- oracle / observability ------------------------------------------
    def recompute_logits(self, tokens: Sequence[int],
                         quantized: Optional[bool] = None) -> np.ndarray:
        """Full-recompute oracle: run the ENTIRE sequence through the
        prefill path from scratch (no cache reuse, no prefix sharing)
        and return the last position's logits.  Runs on THROWAWAY page
        pools — the prefill body only ever WRITES pages (its attention
        reads the locally built K/V, so fresh zero pools are
        numerically identical), and touching the live pools would race
        the engine thread's donating step.  Safe to call while the
        engine is serving.

        ``quantized`` defaults to False: the oracle is the FULL-
        PRECISION reference, which on a kv-quantized engine is what the
        quality-delta accounting compares against.  Pass
        ``quantized=True`` on a quantized engine for the quantized
        self-oracle — the recompute through the same per-position
        quant-dequant the cache stores, which the composition tests pin
        BITWISE against streamed decode.  ``tests/test_decode_engine.py``
        compares the default oracle bitwise on unquantized engines;
        ``tests/test_decode_prefix_spec.py`` does the same for the
        shared-prefix, CoW, chunked, and speculative paths."""
        import jax
        import jax.numpy as jnp

        qz = bool(quantized) if quantized is not None else False
        tokens = [int(t) for t in tokens]
        t_pad = self._buckets.seq_bucket(len(tokens))
        arr = np.zeros((t_pad,), np.int32)
        arr[:len(tokens)] = tokens
        cc = self._cache.config
        shape = (cc.num_layers, cc.num_pages, cc.page_size, cc.num_heads,
                 cc.head_dim)
        if qz:
            sshape = shape[:-1]
            scratch = (jnp.zeros(shape, jnp.int8),
                       jnp.zeros(shape, jnp.int8),
                       jnp.full(sshape, kv_cache.SCALE_EPS,
                                cc.scale_dtype),
                       jnp.full(sshape, kv_cache.SCALE_EPS,
                                cc.scale_dtype))
        else:
            scratch = (jnp.zeros(shape, cc.dtype),
                       jnp.zeros(shape, cc.dtype))
        (tok, last), _ = self._prefill_fn(t_pad, quantized=qz)(
            scratch, self.weights, jnp.asarray(arr),
            np.int32(len(tokens)),
            jnp.zeros((cc.pages_per_slot,), jnp.int32),
            jax.random.PRNGKey(0), np.float32(0.0), np.int32(0),
            np.float32(1.0))
        return np.asarray(last)

    def debug_requests(self) -> List[dict]:
        """Live in-flight table (the ``/debug/requests`` route): one
        row per occupied slot and per queued request — trace id, age,
        slot, phase, pages held, prefill chunks done, tokens emitted,
        deadline headroom.  Read-mostly and engine-thread-racy by
        design (a scrape must never block the step loop); a row for a
        slot that frees mid-snapshot simply disappears next scrape."""
        now = time.monotonic()
        rows: List[dict] = []
        for i, st in enumerate(list(self._slots)):
            if st is None:
                continue
            req = st.req
            rows.append({
                "trace_id": req.trace.trace_id
                if req.trace is not None else None,
                "replica": self.name,
                "slot": i,
                "phase": st.phase,
                "age_ms": round((now - req.t_enqueue) * 1e3, 3),
                "prompt_len": len(req.prompt),
                "prefill_pos": st.prefill_pos,
                "chunks_done": st.chunks,
                "pages": len(self._cache.slot_pages(i)),
                "tokens": st.n_generated,
                "max_new_tokens": req.max_new_tokens,
                "speculative": st.spec,
                "deadline_in_ms": None if req.deadline is None
                else round((req.deadline - now) * 1e3, 3),
            })
        with self._cond:
            queued = list(self._queue)
        for req in queued:
            if req.done():
                continue
            rows.append({
                "trace_id": req.trace.trace_id
                if req.trace is not None else None,
                "replica": self.name,
                "slot": None,
                "phase": "queued",
                "age_ms": round((now - req.t_enqueue) * 1e3, 3),
                "prompt_len": len(req.prompt),
                "tokens": 0,
                "max_new_tokens": req.max_new_tokens,
                "deadline_in_ms": None if req.deadline is None
                else round((req.deadline - now) * 1e3, 3),
            })
        return rows

    def stats(self) -> dict:
        with self._cond:
            depth = len(self._queue)
        hp, pp = self._hit_pages, self._prompt_pages
        sp, sa = self._spec_proposed, self._spec_accepted
        return {
            "name": self.name,
            "slots": self.config.slots,
            "live_slots": self.live_slots,
            "free_slots": self.free_slots,
            "queue_depth": depth,
            "tokens_total": self.tokens_total,
            "free_pages": self._cache.allocator.num_free,
            "num_pages": self._cache.config.num_pages,
            "cache_bytes": self._cache.config.cache_bytes(),
            "continuous": self._continuous,
            "prefix_cache": self.config.prefix_cache,
            "kv_quant": self.config.kv_quant,
            "page_bytes": self._cache.config.page_bytes(),
            "prefix_hit_pages": hp,
            "prefix_prompt_pages": pp,
            "cache_hit_rate": round(hp / pp, 4) if pp else 0.0,
            "shared_pages": self._cache.shared_pages,
            "cow_copies": self._cow_copies,
            "prefill_chunks": self._prefill_chunk_count,
            "ragged_prefill_rows": self.config.ragged_prefill_rows,
            "ragged_dispatches": stat_get("decode_ragged_dispatches"),
            "prefill_pad_waste": stat_get("prefill_pad_waste") / 1e6,
            "spec_enabled": self.spec_enabled,
            "spec_proposed": sp,
            "spec_accepted": sa,
            "spec_accept_rate": round(sa / sp, 4) if sp else 0.0,
        }
