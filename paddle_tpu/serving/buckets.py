"""Shape bucketing for the serving layer.

The Executor's compile cache holds one XLA executable per distinct feed
shape, so a variable-length request stream compiles an executable per
length — a compile storm that leaves the chip idle exactly when traffic
arrives.  A ``BucketSpec`` pins the shape universe up front: every
request is padded UP to the smallest configured (batch-size,
sequence-length) bucket that holds it, so the cache holds exactly
``len(batch_sizes) * len(seq_lens)`` executables and the serving warmup
can pre-compile all of them before the first request.

Padding contract: the pad value (default 0) must be semantically inert
for the model — true for row-wise inference nets whose padded positions
are masked or contribute zeros (embedding-sum, relu-matmul chains,
attention with an explicit mask input).  Padded BATCH rows are always
sliced off before results are returned, so only padded SEQUENCE
positions can observe the pad value; symmetrically, a FETCH whose shape
retains a dynamic inner dim is returned padded to its seq bucket (the
server cannot know which output axes track the input length) — reduce
or mask such dims in-model, or slice client-side.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


class ServingError(RuntimeError):
    """Base class for serving-layer failures."""


class RequestTooLargeError(ServingError):
    """A request exceeds the largest configured bucket."""


class QueueFullError(ServingError):
    """Backpressure: the bounded request queue is at capacity."""


class DeadlineExceededError(ServingError):
    """The request's deadline passed before a result was produced."""


class ServerClosedError(ServingError):
    """The server is draining or stopped and accepts no new requests."""


class RequestAbandonedError(ServingError):
    """The client explicitly abandoned the request (RequestBase.abandon);
    the engine frees its slot/queue entry at the next boundary."""


class BucketSpec:
    """The static bucket grid: batch sizes x sequence lengths.

    ``batch_sizes`` bounds how many rows one compiled executable
    processes; ``seq_lens`` bounds every dynamic (declared ``-1``)
    non-batch feed dim.  ``seq_lens=None`` means the model has no
    dynamic inner dims (or the caller accepts one executable per
    distinct inner shape).
    """

    def __init__(self, batch_sizes: Sequence[int] = (1, 2, 4, 8),
                 seq_lens: Sequence[int] = None):
        bs = sorted({int(b) for b in batch_sizes})
        if not bs or bs[0] < 1:
            raise ValueError(f"batch_sizes must be positive ints, got "
                             f"{batch_sizes!r}")
        self.batch_sizes: Tuple[int, ...] = tuple(bs)
        if seq_lens is None:
            self.seq_lens = None
        else:
            sl = sorted({int(s) for s in seq_lens})
            if not sl or sl[0] < 1:
                raise ValueError(f"seq_lens must be positive ints, got "
                                 f"{seq_lens!r}")
            self.seq_lens = tuple(sl)

    @property
    def max_batch(self) -> int:
        return self.batch_sizes[-1]

    def n_buckets(self) -> int:
        return len(self.batch_sizes) * len(self.seq_lens or (None,))

    def batch_bucket(self, n: int) -> int:
        for b in self.batch_sizes:
            if b >= n:
                return b
        raise RequestTooLargeError(
            f"batch of {n} rows exceeds the largest configured batch "
            f"bucket {self.max_batch}")

    def seq_bucket(self, length: int) -> int:
        if self.seq_lens is None:
            return int(length)  # exact-shape mode: no inner padding
        for s in self.seq_lens:
            if s >= length:
                return s
        raise RequestTooLargeError(
            f"sequence length {length} exceeds the largest configured "
            f"seq bucket {self.seq_lens[-1]}")


def feed_plans(program, feed_names) -> Dict[str, tuple]:
    """The model's feed contract: name -> (declared shape, np dtype).

    Serving requires every feed's leading dim to be the dynamic batch
    dim (that is what gets coalesced); a model exported with a static
    batch cannot be micro-batched and is rejected loudly here rather
    than producing shape errors under traffic.
    """
    from ..framework import dtypes

    block = program.global_block
    plans: Dict[str, tuple] = {}
    for name in feed_names:
        var = block._find_var_recursive(name)
        if var is None:
            raise KeyError(f"feed var {name!r} not found in program")
        shape = tuple(int(s) for s in (var.shape or ()))
        if not shape or shape[0] not in (-1, 0):
            raise ValueError(
                f"feed {name!r} declares shape {shape}: serving needs a "
                f"dynamic (-1) leading batch dim to coalesce requests")
        plans[name] = (shape, dtypes.to_np(var.dtype))
    return plans


def plan_request(feeds: Dict[str, np.ndarray], plans: Dict[str, tuple],
                 spec: BucketSpec):
    """Validate one request against the feed contract and compute its
    coalescing key.

    Returns ``(arrays, nrows, key)`` where ``key`` is the tuple of
    per-feed padded inner shapes — two requests coalesce iff their keys
    are equal (they pad to the same executable).  Raises
    ``RequestTooLargeError`` when any dim exceeds the bucket grid, and
    plain ``KeyError``/``ValueError`` for contract violations.
    """
    missing = [n for n in plans if n not in feeds]
    if missing:
        raise KeyError(f"missing inputs: {missing}")
    arrays: Dict[str, np.ndarray] = {}
    nrows = None
    key: List[tuple] = []
    for name in sorted(plans):
        shape, np_dtype = plans[name]
        arr = np.asarray(feeds[name])
        if arr.dtype != np_dtype:
            arr = arr.astype(np_dtype)
        if arr.ndim != len(shape):
            raise ValueError(
                f"feed {name!r}: rank {arr.ndim} != declared rank "
                f"{len(shape)} {shape}")
        if arr.shape[0] < 1:
            raise ValueError(f"feed {name!r} has an empty batch dim")
        if nrows is None:
            nrows = int(arr.shape[0])
        elif int(arr.shape[0]) != nrows:
            raise ValueError(
                f"feeds disagree on the batch dim: {name!r} has "
                f"{arr.shape[0]} rows, earlier feeds have {nrows}")
        if nrows > spec.max_batch:
            raise RequestTooLargeError(
                f"request batch {nrows} exceeds the largest configured "
                f"batch bucket {spec.max_batch}")
        inner = []
        for d_decl, d_act in zip(shape[1:], arr.shape[1:]):
            if d_decl in (-1, 0):
                inner.append(spec.seq_bucket(int(d_act)))
            elif int(d_decl) != int(d_act):
                raise ValueError(
                    f"feed {name!r}: shape {tuple(arr.shape)} does not "
                    f"match declared {shape}")
            else:
                inner.append(int(d_act))
        arrays[name] = arr
        key.append((name, tuple(inner)))
    return arrays, nrows, tuple(key)


def assemble(requests, key, spec: BucketSpec, pad_value=0):
    """Coalesce same-key requests into one padded bucket batch.

    Rows concatenate in request order; dynamic inner dims pad to the
    key's bucketed extents; the batch dim pads up to its batch bucket.
    Returns ``(feed dict, total live rows, bucket batch)`` — callers
    slice results back out with the per-request row counts.
    """
    total = sum(r.nrows for r in requests)
    bucket_rows = spec.batch_bucket(total)
    feeds: Dict[str, np.ndarray] = {}
    for name, inner in key:
        parts = []
        for r in requests:
            a = r.feeds[name]
            widths = [(0, 0)] + [(0, t - s)
                                 for t, s in zip(inner, a.shape[1:])]
            if any(w[1] for w in widths):
                a = np.pad(a, widths, constant_values=pad_value)
            parts.append(a)
        if bucket_rows > total:
            parts.append(np.full((bucket_rows - total,) + tuple(inner),
                                 pad_value, parts[0].dtype))
        feeds[name] = parts[0] if len(parts) == 1 \
            else np.concatenate(parts, axis=0)
    return feeds, total, bucket_rows


def bucket_feed_specs(plans: Dict[str, tuple], spec: BucketSpec):
    """Enumerate the warmup grid: one Executor feed spec per bucket.

    Models with no dynamic inner dims collapse the seq axis (the grid
    de-duplicates); models WITH dynamic inner dims but ``seq_lens=None``
    have an open-ended shape universe and return only what is closed —
    the caller should warn that warmup cannot cover exact-shape mode.
    """
    specs = []
    seen = set()
    open_ended = spec.seq_lens is None and any(
        any(d in (-1, 0) for d in shape[1:])
        for shape, _ in plans.values())
    if open_ended:
        return [], True
    for b in spec.batch_sizes:
        for s in (spec.seq_lens or (None,)):
            fs = {}
            for name, (shape, np_dtype) in plans.items():
                dims = [b] + [s if d in (-1, 0) else int(d)
                              for d in shape[1:]]
                fs[name] = (tuple(dims), np_dtype)
            fp = tuple(sorted((n, v[0], str(np.dtype(v[1])))
                              for n, v in fs.items()))
            if fp not in seen:
                seen.add(fp)
                specs.append(fs)
    return specs, False


def prefill_bucket_grid(max_seq_len: int, page_size: int):
    """Prompt-length buckets for the decode engine's prefill compiles
    (serving/decode.py): page-multiple powers of two capped at
    max_seq_len, so the prefill executable universe stays
    O(log(max_seq/page)) and every bucket scatters whole KV pages.

    The rounding buys a tiny executable universe at the price of dead
    query rows — a 65-token prompt dispatches a 128-row executable.
    Every admission must account that waste through
    ``record_pad_waste`` so the cost is measurable (and so ragged
    packing's A/B is visible on old padded rounds too)."""
    out = []
    b = int(page_size)
    while b < max_seq_len:
        out.append(b)
        b *= 2
    out.append(int(max_seq_len))
    return tuple(out)


def record_pad_waste(live_tokens: int, dispatched_tokens: int) -> None:
    """Account one prefill dispatch's padding: ``dispatched - live``
    query rows computed attention for nobody.  Keeps the running
    counters and re-derives the ``prefill_pad_waste`` gauge (cumulative
    padded fraction of all dispatched prefill rows, in parts-per-million
    — the stat registry is integer-only) — the number ragged packing
    (FLAGS_decode_ragged_prefill) exists to drive down."""
    from ..monitor import stat_add, stat_get, stat_set

    live = max(0, int(live_tokens))
    pad = max(0, int(dispatched_tokens) - live)
    stat_add("prefill_padded_tokens_total", pad)
    stat_add("prefill_live_tokens_total", live)
    padded = stat_get("prefill_padded_tokens_total")
    total = padded + stat_get("prefill_live_tokens_total")
    if total:
        stat_set("prefill_pad_waste", int(padded * 1_000_000 / total))
