"""`serving.Server`: dynamic-batching inference server over a Predictor.

Role parity: the reference splits AnalysisPredictor (compile + run)
from Paddle Serving (batching, health, metrics); this module is that
serving layer rebuilt TPU-native on three pieces that already exist —
the compile-once ``inference.Predictor``, the ``Executor`` compile
cache (now pre-warmed per shape bucket via ``Executor.warmup``), and
``monitor.StatRegistry`` for runtime counters.

Lifecycle::

    srv = serving.Server(model_dir, serving.ServingConfig(
        batch_sizes=(1, 2, 4, 8), seq_lens=(16, 32), http_port=0))
    srv.start()                  # AOT-warms every bucket, then serves
    outs = srv.infer({"x": x})   # thread-safe, blocks for the result
    srv.stop(drain=True)         # refuse new work, finish the queue

``http_port`` exposes GET ``/stats`` (counter snapshot incl. latency
p50/p95/p99), ``/health`` (liveness + queue depth), and ``/metrics``
(Prometheus text exposition, registered by the fleet KV HTTP server
itself) — point a Prometheus scraper at the port and the serving
latency histogram + every runtime counter shows up.
"""
from __future__ import annotations

import logging
import time
from typing import Dict, Optional, Sequence

from ..monitor import stat_add, stat_get
from .batcher import _UNSET, Batcher, InferenceRequest
from .buckets import BucketSpec, bucket_feed_specs, feed_plans

logger = logging.getLogger(__name__)


def _debug_request_route(trace_id: str) -> Dict:
    """GET ``/debug/request/<id>``: full timeline JSON for one trace
    (in flight or retained), from the process trace store."""
    from ..observe.request_trace import get_trace_store

    tr = get_trace_store().get(trace_id)
    if tr is None:
        return {"error": f"no trace {trace_id!r} in flight or retained "
                         f"(head-sampled out, or fell off the ring — "
                         f"see FLAGS_request_trace_sample / "
                         f"FLAGS_request_trace_ring)"}
    return tr.to_dict()


class ServingConfig:
    """Knobs for the serving layer (reference Paddle Serving's
    server-config proto, collapsed to what the TPU path needs)."""

    def __init__(self,
                 batch_sizes: Sequence[int] = (1, 2, 4, 8),
                 seq_lens: Sequence[int] = None,
                 max_queue: int = 128,
                 batch_window_ms: float = 5.0,
                 default_deadline_ms: Optional[float] = None,
                 pad_value=0,
                 http_port: Optional[int] = None):
        self.bucket_spec = BucketSpec(batch_sizes, seq_lens)
        self.max_queue = int(max_queue)
        self.batch_window_ms = float(batch_window_ms)
        self.default_deadline_ms = default_deadline_ms
        self.pad_value = pad_value
        self.http_port = http_port  # None: no HTTP; 0: ephemeral port


class Server:
    """Batches concurrent ``infer`` calls through one Predictor."""

    def __init__(self, model, config: Optional[ServingConfig] = None):
        from ..inference import Config as InferConfig
        from ..inference import Predictor

        if isinstance(model, Predictor):
            predictor = model
        elif isinstance(model, (InferConfig, str)):
            predictor = Predictor(model)
        else:
            raise TypeError(
                f"model must be a Predictor, inference.Config, or model "
                f"dir path, got {type(model).__name__}")
        self._predictor = predictor
        self._config = config or ServingConfig()
        self._plans = feed_plans(predictor._program,
                                 predictor.get_input_names())
        self._batcher = Batcher(
            self._run_batch, self._plans, self._config.bucket_spec,
            max_queue=self._config.max_queue,
            batch_window_ms=self._config.batch_window_ms,
            default_deadline_ms=self._config.default_deadline_ms,
            pad_value=self._config.pad_value)
        self._kv = None
        self._t_start = None
        self._started = False

    # -- execution -------------------------------------------------------
    def _run_batch(self, feeds):
        # single-threaded by construction (the batcher's one consumer):
        # the Predictor/Executor pair is not re-entrant
        return self._predictor.run(feeds)

    # -- lifecycle -------------------------------------------------------
    def warmup(self) -> int:
        """AOT-compile every bucket's executable; returns fresh-compile
        count.  Serving traffic after warmup only ever cache-hits."""
        specs, open_ended = bucket_feed_specs(
            self._plans, self._config.bucket_spec)
        if open_ended:
            logger.warning(
                "serving warmup skipped: the model has dynamic inner "
                "dims but no seq_lens are configured (exact-shape mode "
                "compiles per distinct shape, on demand)")
            return 0
        n = self._predictor._exe.warmup(
            self._predictor._program, specs,
            fetch_list=self._predictor._fetch_targets,
            scope=self._predictor._scope)
        stat_add("serving_warmup_compiles", n)
        return n

    def start(self, warmup: bool = True) -> "Server":
        if self._started:
            return self
        if warmup:
            self.warmup()
        self._batcher.start()
        if self._config.http_port is not None:
            from ..distributed.fleet.utils.http_server import KVServer

            self._kv = KVServer(self._config.http_port,
                                routes={"/stats": self.stats,
                                        "/health": self.health,
                                        "/debug/requests":
                                            self.debug_requests,
                                        "/debug/request/":
                                            _debug_request_route})
            self._kv.start()
        self._t_start = time.monotonic()
        self._started = True
        from ..observe import flight as _flight

        _flight.record("serving/start",
                       http_port=self._config.http_port,
                       warmup=bool(warmup))
        return self

    def stop(self, drain: bool = True):
        self._batcher.stop(drain=drain)
        if self._kv is not None:
            self._kv.stop()
            self._kv = None
        self._started = False
        from ..observe import flight as _flight

        _flight.record("serving/stop", drain=bool(drain))

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=exc[0] is None)  # error exit: don't drain
        return False

    # -- request path ----------------------------------------------------
    def infer(self, feeds: Dict, deadline_ms=_UNSET):
        """Blocking inference; safe to call from many threads.  Returns
        the fetch list with exactly the caller's BATCH rows (batch
        padding is invisible; a fetch that retains a dynamic inner dim
        comes back padded to its seq bucket — reduce or mask in-model,
        or slice client-side with the request's true length).  Raises
        QueueFullError / DeadlineExceededError / RequestTooLargeError
        per the backpressure contract."""
        return self._batcher.infer(feeds, deadline_ms=deadline_ms)

    def submit(self, feeds: Dict, deadline_ms=_UNSET) -> InferenceRequest:
        """Async variant: returns a future-like InferenceRequest."""
        return self._batcher.submit(feeds, deadline_ms=deadline_ms)

    # -- observability ---------------------------------------------------
    @property
    def http_port(self) -> Optional[int]:
        return self._kv.port if self._kv is not None else None

    def stats(self) -> Dict[str, float]:
        """Snapshot of the serving/executor counters plus derived
        averages (served over GET /stats)."""
        from ..monitor import export_stats

        out = {n: v for n, v in export_stats()
               if n.startswith("serving_") or n.startswith("executor_")}
        completed = out.get("serving_completed", 0)
        if completed:
            out["serving_latency_ms_avg"] = round(
                out.get("serving_latency_us_total", 0) / completed / 1e3,
                3)
        batches = out.get("serving_batches", 0)
        if batches:
            out["serving_batch_occupancy_avg"] = round(
                out.get("serving_batched_requests", 0) / batches, 3)
            rows = out.get("serving_batched_rows", 0)
            out["serving_padding_fraction"] = round(
                out.get("serving_padded_rows", 0)
                / max(rows + out.get("serving_padded_rows", 0), 1), 3)
        return out

    def debug_requests(self) -> Dict:
        """Live in-flight request table (GET ``/debug/requests``)."""
        rows = self._batcher.debug_requests()
        return {"requests": rows, "n": len(rows)}

    def health(self) -> Dict:
        depth = self._batcher.queue_depth
        return {
            "status": "ok" if self._started else "stopped",
            "queue_depth": depth,
            "queue_capacity": self._config.max_queue,
            "uptime_s": round(time.monotonic() - self._t_start, 3)
            if self._t_start is not None else 0.0,
            "buckets": self._config.bucket_spec.n_buckets(),
            "compiles": stat_get("executor_compile"),
        }


def least_loaded_order(engines):
    """Deterministic least-loaded dispatch order over decode engines:
    most free slots first, then shortest queue, then LOWEST index.
    The index tie-break matters: Python's sort is stable, but the
    iteration order of a replica list is an accident of construction —
    pinning ties to the lowest index makes router A/Bs and the disagg
    bench reproducible run-to-run (tests/test_disagg.py pins it).
    Shared by :class:`DecodeServer` and the disagg router."""
    engines = list(engines)
    order = sorted(range(len(engines)),
                   key=lambda i: (-engines[i].free_slots,
                                  engines[i].queue_depth, i))
    return [engines[i] for i in order]


class DecodeServer:
    """N replicated decode engines (serving/decode.py) behind ONE
    admission point with least-loaded dispatch — the generative
    counterpart of ``Server``.

    Every replica is a full ``DecodeEngine``: its own Executor, slot
    batch, and paged KV cache, all fed from the shared (read-only)
    weight arrays.  ``submit`` routes each request to the replica with
    the most free slots (ties: shortest queue), falling back across
    replicas when one's queue is full.  Per-request sampling is keyed
    by the request's own seed, so WHICH replica serves a request never
    changes its tokens (tests/test_decode_engine.py pins 2-replica parity).

    ``http_port`` serves GET ``/stats`` (aggregate + one entry per
    replica), ``/health``, and ``/metrics`` (Prometheus; includes
    decode_tokens_total, decode_slot_occupancy, ttft_seconds /
    tpot_seconds histograms)."""

    def __init__(self, model, weights, config=None, replicas: int = 1,
                 http_port: Optional[int] = None, draft_model=None,
                 draft_weights=None):
        from .decode import DecodeConfig, DecodeEngine

        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        self._config = config or DecodeConfig()
        self._engines = [
            DecodeEngine(model, weights, self._config,
                         name=f"replica-{i}", draft_model=draft_model,
                         draft_weights=draft_weights)
            for i in range(replicas)
        ]
        self._http_port = http_port
        self._kv = None
        self._t_start = None
        self._started = False

    @property
    def replicas(self):
        return list(self._engines)

    # -- request path ----------------------------------------------------
    def _pick(self):
        """Least-loaded dispatch order (see
        :func:`least_loaded_order`)."""
        return least_loaded_order(self._engines)

    def submit(self, prompt, **kw):
        from .buckets import QueueFullError

        last_err = None
        for eng in self._pick():
            try:
                return eng.submit(prompt, **kw)
            except QueueFullError as e:
                last_err = e
        raise last_err

    def generate(self, prompt, **kw):
        return self.submit(prompt, **kw).result()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "DecodeServer":
        if self._started:
            return self
        for eng in self._engines:
            eng.start()
        if self._http_port is not None:
            from ..distributed.fleet.utils.http_server import KVServer

            self._kv = KVServer(self._http_port,
                                routes={"/stats": self.stats,
                                        "/health": self.health,
                                        "/debug/requests":
                                            self.debug_requests,
                                        "/debug/request/":
                                            _debug_request_route,
                                        "/debug/slo": self.debug_slo})
            self._kv.start()
        self._t_start = time.monotonic()
        self._started = True
        return self

    def stop(self, drain: bool = True):
        for eng in self._engines:
            eng.stop(drain=drain)
        if self._kv is not None:
            self._kv.stop()
            self._kv = None
        self._started = False

    def __enter__(self) -> "DecodeServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=exc[0] is None)
        return False

    # -- observability ---------------------------------------------------
    @property
    def http_port(self) -> Optional[int]:
        return self._kv.port if self._kv is not None else None

    def debug_requests(self) -> Dict:
        """GET ``/debug/requests``: replica-tagged live in-flight rows
        aggregated across every engine (each row carries its replica
        name and trace id; follow ``/debug/request/<id>`` for the full
        timeline)."""
        rows = []
        for eng in self._engines:
            rows.extend(eng.debug_requests())
        return {"requests": rows, "n": len(rows),
                "replicas": len(self._engines)}

    def debug_slo(self) -> Dict:
        """GET ``/debug/slo``: objectives, multi-window burn rates,
        budget remaining, and goodput (observe/slo.py snapshot)."""
        from ..observe import slo as _slo

        return _slo.snapshot()

    def stats(self) -> Dict:
        per = [e.stats() for e in self._engines]
        hit = sum(p["prefix_hit_pages"] for p in per)
        total = sum(p["prefix_prompt_pages"] for p in per)
        proposed = sum(p["spec_proposed"] for p in per)
        accepted = sum(p["spec_accepted"] for p in per)
        slo_snap = self.debug_slo()
        return {
            "goodput_rps": slo_snap.get("goodput_rps", 0.0),
            "slo_violations": slo_snap.get("violations_total", 0),
            "replicas": per,
            "n_replicas": len(per),
            "tokens_total": sum(p["tokens_total"] for p in per),
            "live_slots": sum(p["live_slots"] for p in per),
            "free_slots": sum(p["free_slots"] for p in per),
            "queue_depth": sum(p["queue_depth"] for p in per),
            # tentpole aggregates: fleet-wide prefix-cache hit rate,
            # shared-page footprint, CoW traffic, chunked-prefill and
            # speculative-decode activity (per-replica rows above)
            "cache_hit_rate": round(hit / total, 4) if total else 0.0,
            "shared_pages": sum(p["shared_pages"] for p in per),
            "cow_copies": sum(p["cow_copies"] for p in per),
            "prefill_chunks": sum(p["prefill_chunks"] for p in per),
            "spec_accept_rate": round(accepted / proposed, 4)
            if proposed else 0.0,
            "spec_proposed": proposed,
            "spec_accepted": accepted,
            # quantized KV cache: fleet-wide pool bytes reflect the
            # int8+scale page cost when FLAGS_decode_kv_quant is on
            "kv_quant": all(p["kv_quant"] for p in per) if per
            else False,
            "cache_bytes": sum(p["cache_bytes"] for p in per),
        }

    def health(self) -> Dict:
        return {
            "status": "ok" if self._started else "stopped",
            "replicas": len(self._engines),
            "free_slots": sum(e.free_slots for e in self._engines),
            "queue_depth": sum(e.queue_depth for e in self._engines),
            "uptime_s": round(time.monotonic() - self._t_start, 3)
            if self._t_start is not None else 0.0,
        }
