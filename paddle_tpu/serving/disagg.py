"""Disaggregated prefill/decode serving: phase-aware routing, KV-page
migration, and SLO-driven replica re-roling.

Prefill is compute-bound (one big batched matmul pass over the prompt)
and decode is memory-bound (one token per step, bandwidth-limited page
reads); a replica serving both phases wastes both resources and lets
one long prompt's prefill steal step time from every decoding request
beside it — the observation behind DistServe (OSDI'24) and Mooncake.
This module splits one model's replica fleet into two ROLES over the
existing :class:`~paddle_tpu.serving.decode.DecodeEngine`:

- **Prefill replicas** run only (chunked) prefill: the router submits
  each request with ``max_new_tokens=1, extract_kv=True``, so the
  engine prefills all prompt positions, samples (and discards) the
  first token, and gathers the prompt-covering KV pages into a
  :class:`~paddle_tpu.serving.kv_cache.KVPageExport` before the slot
  releases.
- **Decode replicas** admit by INSTALLING the migrated pages
  (``submit(kv_import=...)``): admission claims all-fresh pages,
  scatters the payload into every pool (data pages AND the quantized
  scale planes), and starts the slot exactly like a full-prefix-cache
  hit — lengths begin at ``len(prompt) - 1`` and the first decode step
  samples with ``fold_in(base_key, 0)``, so tokens are BITWISE equal
  to a local prefill with the same seed (tests/test_disagg.py pins it
  at kv_quant on and off).

**Migration** is a device-to-device pool-slice copy when the replicas
share a process/backend (the gather result feeds the destination
scatter directly), with a host-bounce fallback (``np.asarray`` out,
``device_put`` in) when they do not or when
``FLAGS_disagg_migrate_host_bounce`` forces it.  A migrated-in page is
a FRESH page owned by its admitting slot — refcount exactly 1, never
in the destination's :class:`~paddle_tpu.serving.kv_cache.PrefixIndex`
while slot-owned (``PagedKVCache.debug_check()`` audits exactly that)
— so refcounts never cross engine boundaries.  Telemetry:
``migrate_pages_total`` / ``migrate_bytes_total`` / ``migrate_seconds``
plus a ``serving/migrate`` tracer span per handoff.

**Fault tolerance**: the router watches each prefill leg; a replica
that dies mid-stream (the ``kill_prefill_replica`` chaos fault, a
crash, a handoff timeout) fails only that leg — the router re-dispatches
the request to a surviving prefill replica
(``disagg_redispatches_total``), falling back to a decode replica's
local prefill when no prefill capacity remains
(``disagg_local_fallbacks``), so a replica death drops zero requests.

**Autoscaling** (:class:`Autoscaler`): a policy loop re-roles replicas
between the two sets at step boundaries — ttft-objective SLO burn
(``observe/slo.py``) above ``FLAGS_disagg_autoscale_burn_high`` moves
a decode replica to the prefill set (prefill capacity is what ttft
burn starves); mean decode queue depth above
``FLAGS_disagg_autoscale_queue_high`` while burn sits under
``FLAGS_disagg_autoscale_burn_low`` moves one back.  The split
thresholds are hysteresis and ``FLAGS_disagg_autoscale_cooldown_s`` is
the anti-flap floor (a trigger inside the window is counted and
dropped).  A re-role drains the replica (no new dispatch, in-flight
work finishes), runs the elastic supervisor's device preflight before
the replica rejoins, and aborts (undrains) on preflight failure.
"""
from __future__ import annotations

import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..framework import flags as _flags
from ..monitor import stat_add, stat_set
from ..observe import tracer as otrace
from .batcher import _UNSET
from .buckets import QueueFullError, ServerClosedError
from .kv_cache import KVPageExport
from .server import least_loaded_order

__all__ = ["Autoscaler", "DisaggConfig", "DisaggRequest", "DisaggServer"]


def _flag(name, default):
    try:
        return _flags.flag(name)
    except KeyError:  # pragma: no cover - partial installs
        return default


class DisaggConfig:
    """Static knobs of one :class:`DisaggServer` (defaults from the
    ``FLAGS_disagg_*`` family; see framework/flags.py for the long
    rationale of each)."""

    def __init__(self, prefill_replicas: Optional[int] = None,
                 decode_replicas: Optional[int] = None,
                 host_bounce: Optional[bool] = None,
                 handoff_timeout_s: Optional[float] = None,
                 redispatch_retries: Optional[int] = None,
                 autoscale_interval_s: Optional[float] = None,
                 autoscale_cooldown_s: Optional[float] = None,
                 autoscale_burn_high: Optional[float] = None,
                 autoscale_burn_low: Optional[float] = None,
                 autoscale_queue_high: Optional[int] = None,
                 burn_objective: str = "ttft",
                 min_prefill: int = 1, min_decode: int = 1,
                 drain_timeout_s: float = 60.0):
        def pick(v, flag, default):
            return (_flag(flag, default) if v is None else v)

        self.prefill_replicas = int(pick(
            prefill_replicas, "disagg_prefill_replicas", 1))
        self.decode_replicas = int(pick(
            decode_replicas, "disagg_decode_replicas", 1))
        self.host_bounce = bool(pick(
            host_bounce, "disagg_migrate_host_bounce", False))
        self.handoff_timeout_s = float(pick(
            handoff_timeout_s, "disagg_handoff_timeout_s", 120.0))
        self.redispatch_retries = int(pick(
            redispatch_retries, "disagg_redispatch_retries", 2))
        self.autoscale_interval_s = float(pick(
            autoscale_interval_s, "disagg_autoscale_interval_s", 1.0))
        self.autoscale_cooldown_s = float(pick(
            autoscale_cooldown_s, "disagg_autoscale_cooldown_s", 30.0))
        self.autoscale_burn_high = float(pick(
            autoscale_burn_high, "disagg_autoscale_burn_high", 1.0))
        self.autoscale_burn_low = float(pick(
            autoscale_burn_low, "disagg_autoscale_burn_low", 0.25))
        self.autoscale_queue_high = int(pick(
            autoscale_queue_high, "disagg_autoscale_queue_high", 4))
        self.burn_objective = str(burn_objective)
        self.min_prefill = int(min_prefill)
        self.min_decode = int(min_decode)
        self.drain_timeout_s = float(drain_timeout_s)
        if self.prefill_replicas < 1 or self.decode_replicas < 1:
            raise ValueError(
                "a DisaggServer needs at least one replica per role")
        if self.autoscale_burn_low > self.autoscale_burn_high:
            raise ValueError(
                f"autoscale_burn_low ({self.autoscale_burn_low}) must "
                f"not exceed autoscale_burn_high "
                f"({self.autoscale_burn_high}) — the hysteresis band "
                f"would invert and the autoscaler could flap")


class _Replica:
    """One engine plus its routing state (role/draining/dead are the
    ROUTER's bookkeeping — the engine itself is role-agnostic)."""

    __slots__ = ("index", "engine", "role", "draining", "dead")

    def __init__(self, index: int, engine, role: str):
        self.index = index
        self.engine = engine
        self.role = role          # "prefill" | "decode"
        self.draining = False     # autoscaler: no NEW dispatch
        self.dead = False         # failed mid-stream; never picked again


class DisaggRequest:
    """Client-facing handle for one disaggregated request.

    The request exists before its decode leg does (the prefill +
    handoff happen first), so this object owns the logical enqueue
    time and proxies everything else to the decode-side
    :class:`~paddle_tpu.serving.decode.DecodeRequest` once the handoff
    binds it.  ``result()`` / ``tokens()`` block through the handoff
    transparently; a handoff that exhausts its retries fails the
    request with the underlying error."""

    def __init__(self, prompt: Sequence[int]):
        self.prompt = [int(t) for t in prompt]
        self.t_enqueue = time.monotonic()
        self._bound = threading.Event()
        self._decode_req = None
        self._err: Optional[BaseException] = None

    # router side --------------------------------------------------------
    def _bind(self, decode_req) -> None:
        self._decode_req = decode_req
        self._bound.set()

    def _fail(self, err: BaseException) -> None:
        self._err = err
        self._bound.set()

    # client side --------------------------------------------------------
    @property
    def decode_request(self):
        """The bound decode-side request (None until the handoff
        completes)."""
        return self._decode_req

    @property
    def error(self) -> Optional[BaseException]:
        if self._err is not None:
            return self._err
        r = self._decode_req
        return r._error if r is not None else None

    @property
    def generated(self) -> List[int]:
        r = self._decode_req
        return list(r.generated) if r is not None else []

    @property
    def t_first_token(self) -> Optional[float]:
        r = self._decode_req
        return r.t_first_token if r is not None else None

    def done(self) -> bool:
        if not self._bound.is_set():
            return False
        return self._decode_req is None or self._decode_req.done()

    def _wait_bound(self, timeout: Optional[float]) -> float:
        t0 = time.monotonic()
        if not self._bound.wait(timeout):
            raise TimeoutError(
                "disagg handoff did not complete within the wait "
                "budget")
        if self._decode_req is None:
            raise self._err
        if timeout is None:
            return None
        return max(timeout - (time.monotonic() - t0), 0.0)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        rem = self._wait_bound(timeout)
        return self._decode_req.result(timeout=rem)

    def tokens(self, timeout: Optional[float] = None):
        rem = self._wait_bound(timeout)
        yield from self._decode_req.tokens(timeout=rem)


class DisaggServer:
    """Phase-aware router over a prefill replica set and a decode
    replica set of :class:`~paddle_tpu.serving.decode.DecodeEngine`
    (module docstring has the full mechanics).  Construction mirrors
    :class:`~paddle_tpu.serving.server.DecodeServer`: every replica is
    a full engine over the shared read-only weights; roles (and the
    autoscaler's re-roling) are pure router bookkeeping."""

    def __init__(self, model, weights, config=None,
                 disagg: Optional[DisaggConfig] = None, place=None,
                 autoscale: bool = False,
                 autoscaler_kw: Optional[dict] = None):
        from .decode import DecodeConfig, DecodeEngine

        self.config = config or DecodeConfig()
        self.disagg = disagg or DisaggConfig()
        d = self.disagg
        total = d.prefill_replicas + d.decode_replicas
        self._replicas: List[_Replica] = []
        for i in range(total):
            role = "prefill" if i < d.prefill_replicas else "decode"
            eng = DecodeEngine(model, weights, self.config, place=place,
                               name=f"disagg-{i}")
            self._replicas.append(_Replica(i, eng, role))
        self._lock = threading.Lock()
        self._seq = 0  # router-level seed counter: both legs of one
        # request must sample from the SAME key for bitwise parity
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * total),
            thread_name_prefix="disagg-handoff")
        self._started = False
        self.autoscaler = Autoscaler(self, **(autoscaler_kw or {})) \
            if autoscale else None

    # -- replica sets -----------------------------------------------------
    @property
    def replicas(self) -> List[_Replica]:
        return list(self._replicas)

    def role_replicas(self, role: str) -> List[_Replica]:
        """Live, dispatchable replicas of ``role`` (dead and draining
        excluded)."""
        with self._lock:
            return [r for r in self._replicas
                    if r.role == role and not r.dead and not r.draining]

    def _role_counts(self):
        with self._lock:
            pre = sum(1 for r in self._replicas
                      if r.role == "prefill" and not r.dead)
            dec = sum(1 for r in self._replicas
                      if r.role == "decode" and not r.dead)
        stat_set("disagg_prefill_replicas", pre)
        stat_set("disagg_decode_replicas", dec)
        return pre, dec

    def _pick(self, role: str) -> List[_Replica]:
        """Deterministic least-loaded order over one role set — the
        same (free_slots, queue_depth, index) order as
        :func:`~paddle_tpu.serving.server.least_loaded_order`."""
        reps = self.role_replicas(role)
        engines = least_loaded_order([r.engine for r in reps])
        by_eng = {id(r.engine): r for r in reps}
        return [by_eng[id(e)] for e in engines]

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "DisaggServer":
        if self._started:
            return self
        for r in self._replicas:
            r.engine.start()
        self._started = True
        self._role_counts()
        if self.autoscaler is not None:
            self.autoscaler.start()
        from ..observe import flight as _flight

        _flight.record("serving/disagg_start",
                       prefill=self.disagg.prefill_replicas,
                       decode=self.disagg.decode_replicas)
        return self

    def stop(self, drain: bool = True):
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self._pool.shutdown(wait=drain)
        for r in self._replicas:
            if not r.dead:
                r.engine.stop(drain=drain)
        self._started = False

    def __enter__(self) -> "DisaggServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=exc[0] is None)
        return False

    # -- request path -----------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0,
               seed: Optional[int] = None, deadline_ms=_UNSET,
               on_token: Optional[Callable[[int], None]] = None,
               record_logits: bool = False) -> DisaggRequest:
        if not self._started:
            raise ServerClosedError("DisaggServer is not started")
        with self._lock:
            if seed is None:
                seed = self._seq
            self._seq += 1
        kw = {"max_new_tokens": max_new_tokens,
              "temperature": float(temperature), "top_k": int(top_k),
              "top_p": float(top_p), "seed": int(seed),
              "deadline_ms": deadline_ms, "on_token": on_token,
              "record_logits": bool(record_logits)}
        dreq = DisaggRequest(prompt)
        stat_add("disagg_requests_total")
        self._dispatch_prefill(dreq, kw, attempt=0)
        return dreq

    def generate(self, prompt, **kw) -> List[int]:
        return self.submit(prompt, **kw).result()

    def _kill_replica(self, rep: _Replica) -> None:
        """Hard-stop one replica (chaos / observed failure): its
        in-flight requests die with ServerClosedError and the router
        never picks it again."""
        with self._lock:
            if rep.dead:
                return
            rep.dead = True
        stat_add("disagg_replica_deaths")
        from ..observe import flight as _flight

        _flight.record("serving/disagg_replica_death",
                       replica=rep.index, role=rep.role)
        rep.engine.stop(drain=False)
        self._role_counts()

    def _dispatch_prefill(self, dreq: DisaggRequest, kw: dict,
                          attempt: int) -> None:
        """Submit the prefill leg to the least-loaded live prefill
        replica and hand the future to a handoff worker.  With no
        prefill capacity left, degrade to a decode replica's LOCAL
        prefill — a dead prefill fleet slows requests down but never
        drops them."""
        for rep in self._pick("prefill"):
            try:
                preq = rep.engine.submit(
                    dreq.prompt, max_new_tokens=1,
                    temperature=kw["temperature"], top_k=kw["top_k"],
                    top_p=kw["top_p"], seed=kw["seed"],
                    deadline_ms=None, extract_kv=True)
            except (QueueFullError, ServerClosedError):
                continue
            self._pool.submit(self._handoff, dreq, preq, rep, kw,
                              attempt)
            return
        stat_add("disagg_local_fallbacks")
        self._submit_decode(dreq, kw, kv_import=None)

    def _submit_decode(self, dreq: DisaggRequest, kw: dict,
                       kv_import) -> None:
        """Bind the decode leg (migrated when ``kv_import`` is given,
        local-prefill fallback otherwise) on the least-loaded decode
        replica, falling through on full queues like DecodeServer."""
        last_err: Optional[BaseException] = None
        for rep in self._pick("decode"):
            try:
                r = rep.engine.submit(
                    dreq.prompt, max_new_tokens=kw["max_new_tokens"],
                    deadline_ms=kw["deadline_ms"],
                    temperature=kw["temperature"], top_k=kw["top_k"],
                    top_p=kw["top_p"], seed=kw["seed"],
                    on_token=kw["on_token"],
                    record_logits=kw["record_logits"],
                    kv_import=kv_import)
            except (QueueFullError, ServerClosedError) as e:
                last_err = e
                continue
            dreq._bind(r)
            return
        stat_add("disagg_dropped_requests")
        dreq._fail(last_err if last_err is not None else
                   ServerClosedError("no live decode replicas"))

    @staticmethod
    def _same_backend(export: KVPageExport, engine) -> bool:
        """True when the payload's buffers already live on the
        destination engine's device (a pool-slice device copy is then
        a no-transport scatter)."""
        try:
            from .kv_cache import K_PAGES_VAR

            src = next(iter(export.arrays.values())).devices()
            dst = engine._scope.get_var(K_PAGES_VAR).devices()
            return src == dst
        except Exception:  # noqa: BLE001 — unknown topology: bounce
            return False

    def _handoff(self, dreq: DisaggRequest, preq, rep: _Replica,
                 kw: dict, attempt: int) -> None:
        """One handoff worker: wait for the prefill leg, migrate its
        pages, bind the decode leg.  Any prefill-side failure
        re-dispatches (up to ``disagg_redispatch_retries``) instead of
        surfacing to the client."""
        d = self.disagg
        # chaos hook: kill the named prefill replica while its prefill
        # is in flight — the recovery path below must finish the
        # request on a survivor (the module is only consulted when
        # something already imported it, the chaos-armory idiom)
        ch = sys.modules.get(
            "paddle_tpu.distributed.fleet.elastic.chaos")
        if ch is not None and ch.take("kill_prefill_replica",
                                      replica=rep.index) is not None:
            self._kill_replica(rep)
        err: Optional[BaseException] = None
        try:
            preq.result(timeout=d.handoff_timeout_s)
        except Exception as e:  # noqa: BLE001 — every failure of the
            err = e             # leg routes the same way: re-dispatch
        export = preq.kv_export
        if err is None and export is None:
            err = RuntimeError(
                "prefill leg completed without a KV export")
        if err is not None:
            stat_add("disagg_prefill_failures")
            if isinstance(err, (ServerClosedError, TimeoutError)):
                # the replica itself is gone/wedged, not the request
                self._kill_replica(rep)
            if attempt < d.redispatch_retries:
                stat_add("disagg_redispatches_total")
                self._dispatch_prefill(dreq, kw, attempt + 1)
            else:
                stat_add("disagg_dropped_requests")
                dreq._fail(err)
            return
        with otrace.span("serving/migrate", replica=rep.index,
                         pages=export.n_pages, bytes=export.nbytes):
            dst_order = self._pick("decode")
            bounce = d.host_bounce or not (
                dst_order and self._same_backend(
                    export, dst_order[0].engine))
            if bounce:
                # host-bounce transport: materialize on host; the
                # destination's install device_puts into its pools
                export = KVPageExport(
                    n_tokens=export.n_tokens, n_pages=export.n_pages,
                    src_pages=export.src_pages,
                    arrays={k: np.asarray(v)
                            for k, v in export.arrays.items()},
                    quantized=export.quantized,
                    page_size=export.page_size)
                stat_add("migrate_host_bounce_total")
            else:
                stat_add("migrate_device_copies_total")
            self._submit_decode(dreq, kw, kv_import=export)
        stat_add("disagg_handoffs_total")

    # -- observability ----------------------------------------------------
    def stats(self) -> dict:
        from ..monitor import stat_get

        pre, dec = self._role_counts()
        out = {
            "prefill_replicas": pre,
            "decode_replicas": dec,
            "handoffs_total": stat_get("disagg_handoffs_total"),
            "redispatches_total":
                stat_get("disagg_redispatches_total"),
            "local_fallbacks": stat_get("disagg_local_fallbacks"),
            "replica_deaths": stat_get("disagg_replica_deaths"),
            "migrate_pages_total": stat_get("migrate_pages_total"),
            "migrate_bytes_total": stat_get("migrate_bytes_total"),
            "replicas": [
                {"index": r.index, "role": r.role, "dead": r.dead,
                 "draining": r.draining,
                 "free_slots": 0 if r.dead else r.engine.free_slots,
                 "queue_depth": 0 if r.dead else r.engine.queue_depth}
                for r in self._replicas],
        }
        return out


class Autoscaler:
    """SLO-driven re-roling between the prefill and decode sets (see
    the module docstring for the policy).  Every signal is injectable
    — ``burn_fn`` (ttft-objective SLO burn), ``queue_fn`` (mean decode
    queue depth), ``preflight`` (the elastic supervisor's device
    probe), ``clock``/``sleep`` — so tests pin the policy without real
    traffic; the defaults read the live SLO plane and run the real
    subprocess preflight."""

    def __init__(self, server: DisaggServer,
                 burn_fn: Optional[Callable[[], float]] = None,
                 queue_fn: Optional[Callable[[], float]] = None,
                 preflight: Optional[Callable[[], bool]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self._server = server
        self._cfg = server.disagg
        self._burn_fn = burn_fn or self._default_burn
        self._queue_fn = queue_fn or self._default_queue
        self._preflight = preflight or self._default_preflight
        self._clock = clock
        self._sleep = sleep
        self._last_rerole = -float("inf")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- default signals --------------------------------------------------
    def _default_burn(self) -> float:
        """Max burn rate (across windows) of every SLO objective whose
        name contains the configured ``burn_objective`` substring
        (default ``ttft``)."""
        from ..observe import slo as _slo

        best = 0.0
        for name, rates in _slo.snapshot().get("burn_rates",
                                               {}).items():
            if self._cfg.burn_objective not in name:
                continue
            best = max(best, max(rates.values(), default=0.0))
        return best

    def _default_queue(self) -> float:
        reps = self._server.role_replicas("decode")
        if not reps:
            return 0.0
        return sum(r.engine.queue_depth for r in reps) / len(reps)

    def _default_preflight(self) -> bool:
        from ..distributed.fleet.elastic.preflight import \
            preflight_device

        return preflight_device(attempts=1).ok

    # -- policy -----------------------------------------------------------
    def tick(self) -> Optional[str]:
        """One policy evaluation; returns the re-role performed
        (``"decode->prefill"`` / ``"prefill->decode"``) or None."""
        cfg = self._cfg
        burn = float(self._burn_fn())
        queue = float(self._queue_fn())
        stat_set("autoscale_burn_ppm", int(burn * 1e6))
        stat_set("autoscale_decode_queue_depth_micro",
                 int(queue * 1e6))
        pre = self._server.role_replicas("prefill")
        dec = self._server.role_replicas("decode")
        self._server._role_counts()
        if burn >= cfg.autoscale_burn_high \
                and len(dec) > cfg.min_decode:
            want, src, dst = "decode->prefill", "decode", "prefill"
        elif queue >= cfg.autoscale_queue_high \
                and burn <= cfg.autoscale_burn_low \
                and len(pre) > cfg.min_prefill:
            want, src, dst = "prefill->decode", "prefill", "decode"
        else:
            return None
        now = self._clock()
        if now - self._last_rerole < cfg.autoscale_cooldown_s:
            # anti-flap: inside the cooldown a trigger is counted and
            # DROPPED (never queued — the signal will still be there
            # next tick if it is real)
            stat_add("autoscale_cooldown_skips_total")
            return None
        if not self._rerole(src, dst):
            return None
        self._last_rerole = self._clock()
        return want

    def _rerole(self, src_role: str, dst_role: str) -> bool:
        """Drain the least-loaded ``src_role`` replica, preflight it,
        and move it to ``dst_role``.  Aborts (undrains, False) on
        drain timeout or preflight failure."""
        order = self._server._pick(src_role)
        if not order:
            return False
        rep = order[0]
        rep.draining = True  # router skips it from here on
        from ..observe import flight as _flight

        _flight.record("serving/autoscale_drain", replica=rep.index,
                       src=src_role, dst=dst_role)
        t0 = self._clock()
        while rep.engine.live_slots or rep.engine.queue_depth:
            if self._clock() - t0 > self._cfg.drain_timeout_s:
                rep.draining = False
                stat_add("autoscale_drain_timeouts")
                return False
            self._sleep(0.01)
        # the elastic supervisor's lesson (BENCH r04/r05): a replica
        # rejoining a set must prove its device works FIRST
        if not self._preflight():
            rep.draining = False
            stat_add("autoscale_preflight_failures")
            return False
        with self._server._lock:
            rep.role = dst_role
            rep.draining = False
        stat_add("autoscale_reroles_total")
        self._server._role_counts()
        _flight.record("serving/autoscale_rerole", replica=rep.index,
                       src=src_role, dst=dst_role)
        return True

    # -- background loop --------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="disagg-autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._cfg.autoscale_interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the policy loop must
                stat_add("autoscale_tick_errors")  # outlive any signal
