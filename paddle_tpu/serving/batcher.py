"""Dynamic micro-batcher: bounded queue + single consumer thread.

Role parity: Paddle Serving's brpc batching frontend collapsed to its
essence — concurrent client requests coalesce into padded bucket
batches (see buckets.py) executed one at a time on the chip.  The
design is single-consumer on purpose: the Predictor/Executor pair is
not re-entrant, and one XLA executable call already saturates the
device, so extra executor threads would only fight over it.

Robustness contract:
- bounded queue — ``submit`` raises ``QueueFullError`` instead of
  growing without limit (explicit backpressure beats silent OOM);
- per-request deadline — an expired request completes with
  ``DeadlineExceededError`` (reaped at dequeue AND on the client's own
  wait, whichever fires first) and never blocks younger requests;
- graceful drain — ``stop(drain=True)`` refuses new work, finishes
  what is queued, then joins the consumer thread.

Observability rides monitor.StatRegistry (serving_* counters/gauges)
and profiler.RecordEvent spans per executed batch.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..monitor import stat_add, stat_max, stat_set
from ..observe import tracer as otrace
from ..observe.histogram import stat_time
from ..profiler import RecordEvent
from .buckets import (BucketSpec, DeadlineExceededError, QueueFullError,
                      RequestAbandonedError, ServerClosedError,
                      ServingError, assemble, plan_request)

class _Unset:
    """"Use the server default" deadline sentinel; the stable repr keeps
    API.spec (which prints default values) deterministic across runs."""

    def __repr__(self):
        return "<server default>"


_UNSET = _Unset()


class RequestBase:
    """Future-like completion/deadline machinery shared by every
    serving request kind: the bucket batcher's ``InferenceRequest``
    below and the decode engine's streaming ``DecodeRequest``
    (serving/decode.py).  The deadline contract is one rule applied at
    EVERY stage a request can sit in: reaped at dequeue, reaped during
    the coalescing window, reaped MID-DECODE at each step boundary
    (the decode scheduler frees the slot so a stalled client cannot
    pin it for the full max_new_tokens), and self-reaped on the
    client's own ``result()`` wait — whichever fires first wins the
    ``_complete`` race."""

    __slots__ = ("deadline", "t_enqueue", "_event", "_lock", "_result",
                 "_error", "trace")

    _deadline_stat = "serving_deadline_exceeded"
    # flat-name outcome counters: <prefix>_requests_total_<outcome>
    _outcome_prefix = "serving"

    def __init__(self, deadline):
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.t_enqueue = time.monotonic()
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result = None
        self._error = None
        self.trace = None  # observe.request_trace.RequestTrace

    def _complete(self, result=None, error=None) -> bool:
        """First completion wins (batcher and client-side deadline can
        race); returns whether THIS call won."""
        with self._lock:
            if self._event.is_set():
                return False
            self._result, self._error = result, error
            self._event.set()
        try:
            # EVERY terminal path funnels here (engine reply, queue
            # reap, client-side deadline self-reap, abandon, cancel),
            # so the per-outcome counters, terminal latency, the SLO
            # observation, and the trace verdict happen exactly once
            self._on_terminal(error)
        except Exception:  # noqa: BLE001 — instrumentation must never
            stat_add("request_trace_errors")  # break completion
        return True

    # -- terminal accounting ---------------------------------------------
    @staticmethod
    def _classify(error) -> str:
        if error is None:
            return "completed"
        if isinstance(error, DeadlineExceededError):
            return "deadline"
        if isinstance(error, RequestAbandonedError):
            return "abandoned"
        if isinstance(error, QueueFullError):
            return "rejected"
        if isinstance(error, ServerClosedError):
            return "cancelled"
        return "error"

    def _on_terminal(self, error) -> None:
        outcome = self._classify(error)
        latency = time.monotonic() - self.t_enqueue
        stat_add(f"{self._outcome_prefix}_requests_total_{outcome}")
        self._finish_stats(outcome, latency)
        if self.trace is None:
            return
        summary = self._summary(outcome, latency)
        try:
            violations = self._slo_check(summary)
        except Exception:  # noqa: BLE001 — a broken objective must not
            # leak the trace in the in-flight map forever
            stat_add("request_trace_errors")
            violations = ()
        from ..observe.request_trace import get_trace_store

        summary.pop("outcome", None)  # stored top-level on the trace
        get_trace_store().finish(
            self.trace, outcome=outcome,
            reason=summary.pop("reason", None)
            or (f"{type(error).__name__}: {error}" if error else None),
            violations=violations, **summary)

    def _finish_stats(self, outcome: str, latency: float) -> None:
        """Terminal latency for the abnormal paths — the completed path
        records ``serving_latency_seconds`` at reply time already, but
        error-rate SLOs need deadline/abandon/cancel in the
        distribution's denominator too."""
        if outcome != "completed":
            stat_time("serving_latency_seconds", latency)

    def _summary(self, outcome: str, latency: float) -> dict:
        return {"outcome": outcome, "latency_s": round(latency, 6)}

    def _slo_check(self, summary: dict):
        return ()

    def abandon(self, reason: str = "client abandoned") -> bool:
        """Client-side give-up: completes the request with
        ``RequestAbandonedError`` (outcome ``abandoned``); the engine
        frees any slot/queue entry it holds at the next boundary."""
        return self._complete(error=RequestAbandonedError(reason))

    def expired(self, now=None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) >= self.deadline

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until completed; raises the request's error if it
        failed.  A deadline-carrying request stops waiting at its
        deadline and completes itself with ``DeadlineExceededError`` if
        the batcher has not produced a result by then.  ``timeout`` is
        the CALLER's wait budget and wins when shorter than the
        deadline: the call raises ``TimeoutError`` and the request stays
        in flight."""
        if self.deadline is not None:
            remaining = max(self.deadline - time.monotonic(), 0.0)
            budget = remaining if timeout is None \
                else min(remaining, timeout)
            if not self._event.wait(budget):
                if timeout is not None and timeout < remaining:
                    raise TimeoutError(
                        "request not completed within timeout")
                if self._complete(error=DeadlineExceededError(
                        f"deadline exceeded after "
                        f"{time.monotonic() - self.t_enqueue:.3f}s "
                        f"(never completed)")):
                    stat_add(self._deadline_stat)
        if not self._event.wait(timeout):
            raise TimeoutError("request not completed within timeout")
        if self._error is not None:
            raise self._error
        return self._result


class InferenceRequest(RequestBase):
    """Future-like handle for one in-flight bucket-batcher request."""

    __slots__ = ("feeds", "nrows", "key")

    def __init__(self, feeds, nrows, key, deadline):
        super().__init__(deadline)
        self.feeds = feeds
        self.nrows = nrows
        self.key = key


class Batcher:
    """The queue + consumer loop; ``runner`` executes one padded batch
    (a dict of bucket-shaped feeds) and returns the fetch list."""

    def __init__(self, runner, plans: Dict[str, tuple], spec: BucketSpec,
                 max_queue: int = 128, batch_window_ms: float = 5.0,
                 default_deadline_ms: Optional[float] = None,
                 pad_value=0):
        self._runner = runner
        self._plans = plans
        self._spec = spec
        self._max_queue = int(max_queue)
        if self._max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self._window = float(batch_window_ms) / 1e3
        self._default_deadline_ms = default_deadline_ms
        self._pad_value = pad_value
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._closing = False
        self._paused = False
        self._thread = None

    # -- client side -----------------------------------------------------
    def submit(self, feeds, deadline_ms=_UNSET) -> InferenceRequest:
        from ..observe.request_trace import get_trace_store

        with otrace.span("serving/enqueue"):
            try:
                arrays, nrows, key = plan_request(feeds, self._plans,
                                                  self._spec)
            except ServingError:
                stat_add("serving_requests_total_rejected")
                raise
            if deadline_ms is _UNSET:
                deadline_ms = self._default_deadline_ms
            deadline = None if deadline_ms is None \
                else time.monotonic() + float(deadline_ms) / 1e3
            req = InferenceRequest(arrays, nrows, key, deadline)
            req.trace = get_trace_store().start(
                "serving", replica="batcher", nrows=nrows,
                key=str(key),
                deadline_ms=None if deadline_ms is None
                else float(deadline_ms))
            with self._cond:
                if self._closing:
                    err = ServerClosedError("server is draining/stopped")
                    req._complete(error=err)
                    raise err
                if len(self._queue) >= self._max_queue:
                    stat_add("serving_rejected_queue_full")
                    err = QueueFullError(
                        f"request queue is at capacity ({self._max_queue}); "
                        f"retry with backoff")
                    req._complete(error=err)
                    raise err
                self._queue.append(req)
                req.trace.event("enqueue", queue_depth=len(self._queue))
                stat_add("serving_requests")
                stat_set("serving_queue_depth", len(self._queue))
                stat_max("serving_queue_depth_max", len(self._queue))
                self._cond.notify_all()
            return req

    def infer(self, feeds, deadline_ms=_UNSET):
        return self.submit(feeds, deadline_ms=deadline_ms).result()

    # -- lifecycle -------------------------------------------------------
    def start(self):
        with self._cond:  # check-and-spawn must be atomic: a second
            # consumer would race the non-reentrant Predictor
            if self._thread is not None:
                return self
            self._closing = False  # a stopped batcher can restart
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="serving-batcher")
            # started INSIDE the lock: a concurrent stop() must never
            # observe (and join) an assigned-but-unstarted thread
            self._thread.start()
        return self

    def pause(self):
        """Hold the consumer (tests / maintenance); queued requests stay
        queued, backpressure still applies."""
        with self._cond:
            self._paused = True
            self._cond.notify_all()

    def resume(self):
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def stop(self, drain: bool = True):
        with self._cond:
            self._closing = True
            # with no consumer thread there is nothing to drain INTO —
            # cancel the queue rather than strand its waiters
            if not drain or self._thread is None:
                while self._queue:
                    req = self._queue.popleft()
                    if req._complete(error=ServerClosedError(
                            "server stopped before the request ran")):
                        stat_add("serving_cancelled")
                stat_set("serving_queue_depth", 0)
            self._paused = False  # a paused server still drains
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def debug_requests(self):
        """Live in-flight table for the ``/debug/requests`` route: one
        row per queued request (trace id, age, rows, bucket key)."""
        with self._cond:
            q = list(self._queue)
        now = time.monotonic()
        return [{
            "trace_id": r.trace.trace_id if r.trace is not None else None,
            "replica": "batcher",
            "phase": "queued",
            "age_ms": round((now - r.t_enqueue) * 1e3, 3),
            "rows": r.nrows,
            "key": str(r.key),
            "deadline_in_ms": None if r.deadline is None
            else round((r.deadline - now) * 1e3, 3),
        } for r in q if not r.done()]

    # -- consumer side ---------------------------------------------------
    def _reap_expired_locked(self):
        now = time.monotonic()
        live = [r for r in self._queue
                if not (r.done() or
                        (r.expired(now) and self._expire(r)))]
        if len(live) != len(self._queue):
            self._queue = collections.deque(live)
            stat_set("serving_queue_depth", len(self._queue))

    @staticmethod
    def _expire(req) -> bool:
        if req._complete(error=DeadlineExceededError(
                "deadline exceeded while queued")):
            stat_add("serving_deadline_exceeded")
        return True  # drop from the queue either way

    def _group_rows_locked(self, key) -> int:
        return sum(r.nrows for r in self._queue
                   if r.key == key and not r.done())

    def _take_group_locked(self, key):
        taken, rest, total = [], [], 0
        now = time.monotonic()
        for r in self._queue:
            if r.done():
                continue  # client-side deadline already answered it
            if r.expired(now):
                # the deadline lapsed during the coalescing window:
                # honor the "reaped at dequeue" contract rather than
                # doing chip work the client contractually abandoned
                self._expire(r)
                continue
            if r.key == key and total + r.nrows <= self._spec.max_batch:
                taken.append(r)
                total += r.nrows
            else:
                rest.append(r)
        self._queue = collections.deque(rest)
        stat_set("serving_queue_depth", len(self._queue))
        return taken

    def _loop(self):
        while True:
            with self._cond:
                while True:
                    self._reap_expired_locked()
                    if self._queue and not self._paused:
                        break
                    if self._closing and not self._queue:
                        return
                    # wake early for new arrivals / resume / stop; the
                    # short cap keeps queued deadlines honest while
                    # paused or idle
                    self._cond.wait(0.05 if self._queue else None)
                head = self._queue[0]
                # the coalescing window IS the span: its duration shows
                # how long requests sat waiting for batch-mates
                with otrace.span("serving/coalesce"):
                    window_end = head.t_enqueue + self._window
                    while (not self._closing
                           and self._group_rows_locked(head.key)
                           < self._spec.max_batch):
                        remaining = window_end - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(remaining)
                    batch = self._take_group_locked(head.key)
            if batch:
                self._execute(batch)

    def _execute(self, requests):
        try:  # assembly failures must not kill the consumer thread
            with otrace.span("serving/pad", requests=len(requests)):
                feeds, total, bucket_rows = assemble(
                    requests, requests[0].key, self._spec, self._pad_value)
            for r in requests:
                if r.trace is not None:
                    r.trace.event("execute", bucket_rows=bucket_rows,
                                  batch_mates=len(requests))
            with otrace.span("serving/execute", rows=bucket_rows,
                             requests=len(requests)):
                with RecordEvent(f"serving/batch_b{bucket_rows}"):
                    outs = self._runner(feeds)
                if hasattr(outs, "numpy"):
                    # lazy StepHandle from the pipelined Executor: the
                    # reply path must own host copies, so the one sync
                    # happens here — inside the execute span, so batch
                    # latency attribution stays truthful
                    outs = outs.numpy()
                outs = [np.asarray(o) for o in outs]
        except Exception as e:  # noqa: BLE001 — fault isolation per batch
            for r in requests:
                if r._complete(error=e):
                    stat_add("serving_failed")
            return
        bad = [tuple(o.shape) for o in outs
               if not o.shape or o.shape[0] != bucket_rows]
        if bad:
            # a fetch that is not batch-major cannot be sliced back into
            # per-request rows — fail LOUDLY instead of returning
            # other requests' data
            err = ServingError(
                f"fetch output shapes {bad} do not lead with the batch "
                f"dim ({bucket_rows} rows): this model's fetches cannot "
                f"be micro-batched")
            for r in requests:
                if r._complete(error=err):
                    stat_add("serving_failed")
            return
        now = time.monotonic()
        offset = 0
        with otrace.span("serving/reply", requests=len(requests)):
            for r in requests:
                # copy: a view would pin the whole bucket-padded batch
                # (and other requests' rows) for as long as the client
                # holds it
                sliced = [o[offset:offset + r.nrows].copy() for o in outs]
                offset += r.nrows
                if r._complete(result=sliced):
                    stat_add("serving_completed")
                    stat_add("serving_latency_us_total",
                             int((now - r.t_enqueue) * 1e6))
                    # tail latency is THE serving metric: p50/p95/p99
                    # ride /stats, /metrics, and export_stats()
                    stat_time("serving_latency_seconds", now - r.t_enqueue)
        stat_add("serving_batches")
        stat_add("serving_batched_requests", len(requests))
        stat_add("serving_batched_rows", total)
        stat_add("serving_padded_rows", bucket_rows - total)
        stat_max("serving_max_batch_occupancy", len(requests))
